"""AWS EventStream binary framing for SelectObjectContent responses
(pkg/s3select/message.go).

Frame layout:
  total_length  uint32 BE
  headers_len   uint32 BE
  prelude_crc   uint32 BE  (CRC32 of the first 8 bytes)
  headers       [name_len u8][name][type u8=7][value_len u16 BE][value]...
  payload
  message_crc   uint32 BE  (CRC32 of everything above)
"""

from __future__ import annotations

import struct
import zlib

_HDR_STRING = 7


def _headers(pairs: "list[tuple[str, str]]") -> bytes:
    out = bytearray()
    for name, value in pairs:
        nb, vb = name.encode(), value.encode()
        out.append(len(nb))
        out += nb
        out.append(_HDR_STRING)
        out += struct.pack(">H", len(vb))
        out += vb
    return bytes(out)


def frame(pairs: "list[tuple[str, str]]", payload: bytes = b"") -> bytes:
    headers = _headers(pairs)
    total = 12 + len(headers) + len(payload) + 4
    prelude = struct.pack(">II", total, len(headers))
    prelude += struct.pack(">I", zlib.crc32(prelude))
    body = prelude + headers + payload
    return body + struct.pack(">I", zlib.crc32(body))


def records_message(payload: bytes) -> bytes:
    return frame(
        [
            (":message-type", "event"),
            (":event-type", "Records"),
            (":content-type", "application/octet-stream"),
        ],
        payload,
    )


def continuation_message() -> bytes:
    return frame(
        [(":message-type", "event"), (":event-type", "Cont")]
    )


def _stats_xml(scanned: int, processed: int, returned: int) -> bytes:
    return (
        '<?xml version="1.0" encoding="UTF-8"?><Stats>'
        f"<BytesScanned>{scanned}</BytesScanned>"
        f"<BytesProcessed>{processed}</BytesProcessed>"
        f"<BytesReturned>{returned}</BytesReturned></Stats>"
    ).encode()


def progress_message(scanned: int, processed: int, returned: int) -> bytes:
    return frame(
        [
            (":message-type", "event"),
            (":event-type", "Progress"),
            (":content-type", "text/xml"),
        ],
        (
            '<?xml version="1.0" encoding="UTF-8"?><Progress>'
            f"<BytesScanned>{scanned}</BytesScanned>"
            f"<BytesProcessed>{processed}</BytesProcessed>"
            f"<BytesReturned>{returned}</BytesReturned></Progress>"
        ).encode(),
    )


def stats_message(scanned: int, processed: int, returned: int) -> bytes:
    return frame(
        [
            (":message-type", "event"),
            (":event-type", "Stats"),
            (":content-type", "text/xml"),
        ],
        _stats_xml(scanned, processed, returned),
    )


def end_message() -> bytes:
    return frame([(":message-type", "event"), (":event-type", "End")])


def error_message(code: str, message: str) -> bytes:
    return frame(
        [
            (":message-type", "error"),
            (":error-code", code),
            (":error-message", message),
        ]
    )


# -- decoding (for tests / client-side) ----------------------------------


def decode_all(data: bytes) -> "list[dict]":
    """Parse a concatenated EventStream byte string into messages:
    [{"headers": {..}, "payload": b".."}]."""
    out = []
    pos = 0
    while pos < len(data):
        if len(data) - pos < 16:
            raise ValueError("truncated prelude")
        total, hlen = struct.unpack_from(">II", data, pos)
        pcrc = struct.unpack_from(">I", data, pos + 8)[0]
        if zlib.crc32(data[pos:pos + 8]) != pcrc:
            raise ValueError("prelude CRC mismatch")
        frame_bytes = data[pos:pos + total]
        mcrc = struct.unpack_from(">I", data, pos + total - 4)[0]
        if zlib.crc32(frame_bytes[:-4]) != mcrc:
            raise ValueError("message CRC mismatch")
        hdrs = {}
        hpos = pos + 12
        hend = hpos + hlen
        while hpos < hend:
            nlen = data[hpos]
            hpos += 1
            name = data[hpos:hpos + nlen].decode()
            hpos += nlen
            vtype = data[hpos]
            hpos += 1
            if vtype != _HDR_STRING:
                raise ValueError(f"unsupported header type {vtype}")
            vlen = struct.unpack_from(">H", data, hpos)[0]
            hpos += 2
            hdrs[name] = data[hpos:hpos + vlen].decode()
            hpos += vlen
        payload = data[hend:pos + total - 4]
        out.append({"headers": hdrs, "payload": payload})
        pos += total
    return out
