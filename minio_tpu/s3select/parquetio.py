"""Parquet record reader for S3 Select
(pkg/s3select/parquet/reader.go + the minio/parquet-go internals).

A self-contained reader for the common analytics layout - flat
schemas, PLAIN or dictionary encoding, uncompressed pages - built
from the format spec up: a Thrift compact-protocol decoder for the
footer metadata, the RLE/bit-packed hybrid for definition levels and
dictionary indexes, and PLAIN decoders for the physical types.  No
external parquet/thrift dependency exists in this image, so the wire
format is implemented directly; unsupported shapes (nested schemas,
compressed pages, v2-only encodings) raise ParquetError with a
precise reason rather than misreading data.

A minimal writer lives at the bottom: the test suite uses it to
produce real files (single row group, PLAIN, uncompressed), and it
doubles as documentation of the subset the reader guarantees.
"""

from __future__ import annotations

import struct

from .sql import MISSING, SQLError

MAGIC = b"PAR1"


class ParquetError(SQLError):
    def __init__(self, message: str):
        super().__init__(message, "InvalidParquet")


# ---------------------------------------------------------------------------
# Thrift compact protocol (just enough for parquet metadata)
# ---------------------------------------------------------------------------

_CT_STOP = 0
_CT_TRUE = 1
_CT_FALSE = 2
_CT_BYTE = 3
_CT_I16 = 4
_CT_I32 = 5
_CT_I64 = 6
_CT_DOUBLE = 7
_CT_BINARY = 8
_CT_LIST = 9
_CT_SET = 10
_CT_MAP = 11
_CT_STRUCT = 12


# raw decoder faults a corrupt file can produce; every public entry
# point converts them to ParquetError so the select plane answers
# with a precise 4xx instead of a generic 500
_DECODE_FAULTS = (
    IndexError,
    struct.error,
    TypeError,
    AttributeError,
    UnicodeDecodeError,
    ValueError,
    KeyError,
    OverflowError,
    MemoryError,
)


class _Thrift:
    """Compact-protocol decoder producing {field_id: value} dicts."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _byte(self) -> int:
        if self.pos >= len(self.buf):
            raise ParquetError("truncated thrift metadata")
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def _varint(self) -> int:
        out = shift = 0
        while True:
            b = self._byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def _zigzag(self) -> int:
        v = self._varint()
        return (v >> 1) ^ -(v & 1)

    def _value(self, ctype: int):
        if ctype == _CT_TRUE:
            return True
        if ctype == _CT_FALSE:
            return False
        if ctype in (_CT_BYTE, _CT_I16, _CT_I32, _CT_I64):
            return self._zigzag()
        if ctype == _CT_DOUBLE:
            v = struct.unpack_from("<d", self.buf, self.pos)[0]
            self.pos += 8
            return v
        if ctype == _CT_BINARY:
            n = self._varint()
            v = self.buf[self.pos : self.pos + n]
            self.pos += n
            return v
        if ctype in (_CT_LIST, _CT_SET):
            head = self._byte()
            n = head >> 4
            etype = head & 0x0F
            if n == 15:
                n = self._varint()
            return [self._value(etype) for _ in range(n)]
        if ctype == _CT_STRUCT:
            return self.struct()
        if ctype == _CT_MAP:
            n = self._varint()
            if n == 0:
                return {}
            kv = self._byte()
            kt, vt = kv >> 4, kv & 0x0F
            return {
                self._value(kt): self._value(vt) for _ in range(n)
            }
        raise ParquetError(f"thrift compact type {ctype}")

    def struct(self) -> dict:
        out: dict = {}
        fid = 0
        while True:
            head = self._byte()
            if head == _CT_STOP:
                return out
            delta = head >> 4
            ctype = head & 0x0F
            if delta:
                fid += delta
            else:
                fid = self._zigzag()
            if ctype in (_CT_TRUE, _CT_FALSE):
                out[fid] = ctype == _CT_TRUE
            else:
                out[fid] = self._value(ctype)


# physical types (parquet.thrift Type)
T_BOOLEAN, T_INT32, T_INT64, T_INT96 = 0, 1, 2, 3
T_FLOAT, T_DOUBLE, T_BYTE_ARRAY, T_FIXED = 4, 5, 6, 7

ENC_PLAIN = 0
ENC_PLAIN_DICT = 2
ENC_RLE = 3
ENC_RLE_DICT = 8

CODEC_UNCOMPRESSED = 0


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid (definition levels + dictionary indexes)
# ---------------------------------------------------------------------------


def _read_rle_hybrid(
    buf: bytes, pos: int, end: int, bit_width: int, count: int
) -> "list[int]":
    out: "list[int]" = []
    if bit_width == 0:
        return [0] * count
    while len(out) < count and pos < end:
        header = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        if header & 1:  # bit-packed run: (header>>1)*8 values
            groups = header >> 1
            nbytes = groups * bit_width
            bits = int.from_bytes(
                buf[pos : pos + nbytes], "little"
            )
            pos += nbytes
            mask = (1 << bit_width) - 1
            for i in range(groups * 8):
                out.append((bits >> (i * bit_width)) & mask)
        else:  # RLE run
            n = header >> 1
            w = (bit_width + 7) // 8
            v = int.from_bytes(buf[pos : pos + w], "little")
            pos += w
            out.extend([v] * n)
    return out[:count]


# ---------------------------------------------------------------------------
# the reader
# ---------------------------------------------------------------------------


class ParquetColumn:
    def __init__(self, name: str, ptype: int, required: bool):
        self.name = name
        self.ptype = ptype
        self.required = required


def _decode_plain(buf: bytes, pos: int, ptype: int, n: int):
    """n PLAIN-encoded values of one physical type."""
    vals: list = []
    if ptype == T_BOOLEAN:
        for i in range(n):
            vals.append(bool(buf[pos + i // 8] >> (i % 8) & 1))
        return vals, pos + (n + 7) // 8
    if ptype == T_INT32:
        vals = list(struct.unpack_from(f"<{n}i", buf, pos))
        return vals, pos + 4 * n
    if ptype == T_INT64:
        vals = list(struct.unpack_from(f"<{n}q", buf, pos))
        return vals, pos + 8 * n
    if ptype == T_FLOAT:
        vals = list(struct.unpack_from(f"<{n}f", buf, pos))
        return vals, pos + 4 * n
    if ptype == T_DOUBLE:
        vals = list(struct.unpack_from(f"<{n}d", buf, pos))
        return vals, pos + 8 * n
    if ptype == T_BYTE_ARRAY:
        for _ in range(n):
            ln = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
            vals.append(
                buf[pos : pos + ln].decode("utf-8", "replace")
            )
            pos += ln
        return vals, pos
    raise ParquetError(f"unsupported physical type {ptype}")


class ParquetReader:
    """Reads a whole (small-to-medium) parquet object into columns;
    S3 Select payloads are bounded by the request, matching the
    reference reader's per-rowgroup materialization."""

    def __init__(self, data: bytes):
        if len(data) < 12 or data[:4] != MAGIC or data[-4:] != MAGIC:
            raise ParquetError("not a parquet file (magic)")
        flen = struct.unpack_from("<I", data, len(data) - 8)[0]
        meta_start = len(data) - 8 - flen
        if meta_start < 4:
            raise ParquetError("corrupt footer length")
        try:
            md = _Thrift(data, meta_start).struct()
            # FileMetaData: 2=schema list, 3=num_rows, 4=row_groups
            self.num_rows = md.get(3, 0)
            schema = md.get(2) or []
            self.columns: "list[ParquetColumn]" = []
            for el in schema[1:]:  # [0] is the root
                # SchemaElement: 1=type 3=repetition 4=name
                # 5=num_children
                if el.get(5):
                    raise ParquetError(
                        "nested parquet schemas are not supported"
                    )
                rep = el.get(3, 0)  # 0=required 1=optional 2=repeated
                if rep == 2:
                    raise ParquetError(
                        "repeated parquet fields are not supported"
                    )
                self.columns.append(
                    ParquetColumn(
                        el.get(4, b"").decode(),
                        el.get(1, 0),
                        rep == 0,
                    )
                )
            self._row_groups = md.get(4) or []
        except _DECODE_FAULTS as e:
            raise ParquetError(
                f"corrupt parquet footer: {type(e).__name__}"
            ) from None
        self._data = data

    def _read_column_chunk(self, col_meta: dict, col: ParquetColumn):
        """All values of one column chunk, Nones for null slots."""
        # ColumnMetaData: 1=type 2=encodings 3=path 4=codec
        # 5=num_values 9=data_page_offset 11=dictionary_page_offset
        codec = col_meta.get(4, 0)
        if codec != CODEC_UNCOMPRESSED:
            raise ParquetError(
                f"compressed parquet pages (codec {codec}) are not "
                "supported"
            )
        num_values = col_meta.get(5, 0)
        pos = col_meta.get(11) or col_meta.get(9)
        buf = self._data
        dictionary = None
        out: list = []
        while len(out) < num_values:
            th = _Thrift(buf, pos)
            ph = th.struct()
            # PageHeader: 1=page_type 2=uncompressed_size
            # 3=compressed_size 5=data_page_header 7=dict_page_header
            ptype_page = ph.get(1)
            page_len = ph.get(3, 0)
            body = th.pos
            if ptype_page == 2:  # DICTIONARY_PAGE
                dph = ph.get(7) or {}
                n = dph.get(1, 0)
                dictionary, _ = _decode_plain(
                    buf, body, col.ptype, n
                )
            elif ptype_page == 0:  # DATA_PAGE v1
                dph = ph.get(5) or {}
                n = dph.get(1, 0)
                enc = dph.get(2, ENC_PLAIN)
                p = body
                end = body + page_len
                if col.required:
                    defs = [1] * n
                else:
                    # definition levels: RLE hybrid with a 4-byte
                    # length prefix, bit width 1 (max level 1)
                    ln = struct.unpack_from("<I", buf, p)[0]
                    p += 4
                    defs = _read_rle_hybrid(buf, p, p + ln, 1, n)
                    p += ln
                npresent = sum(defs)
                if enc == ENC_PLAIN:
                    vals, _ = _decode_plain(
                        buf, p, col.ptype, npresent
                    )
                elif enc in (ENC_PLAIN_DICT, ENC_RLE_DICT):
                    if dictionary is None:
                        raise ParquetError(
                            "dictionary-encoded page without a "
                            "dictionary page"
                        )
                    bw = buf[p]
                    p += 1
                    idxs = _read_rle_hybrid(
                        buf, p, end, bw, npresent
                    )
                    try:
                        vals = [dictionary[i] for i in idxs]
                    except IndexError:
                        raise ParquetError(
                            "dictionary index out of range"
                        ) from None
                else:
                    raise ParquetError(
                        f"page encoding {enc} is not supported"
                    )
                it = iter(vals)
                out.extend(
                    next(it) if d else None for d in defs
                )
            else:
                raise ParquetError(
                    f"page type {ptype_page} is not supported"
                )
            pos = body + page_len
        return out[:num_values]

    def rows(self):
        """Yield row dicts (column name -> value; None stays null).
        A file whose row groups do not add up to the footer's
        num_rows is corrupt - better a loud error than a silently
        truncated result set."""
        try:
            yield from self._rows_inner()
        except ParquetError:
            raise
        except _DECODE_FAULTS as e:
            raise ParquetError(
                f"corrupt parquet structure: {type(e).__name__}"
            ) from None

    def _rows_inner(self):
        yielded = 0
        for rg in self._row_groups:
            # RowGroup: 1=columns list, 2=total_byte_size, 3=num_rows
            cols: "list[list]" = []
            names: "list[str]" = []
            try:
                chunks = rg.get(1) or []
                for cc, col in zip(chunks, self.columns):
                    # ColumnChunk: 3=meta_data
                    meta = cc.get(3) or {}
                    names.append(col.name)
                    cols.append(
                        self._read_column_chunk(meta, col)
                    )
                nrows = rg.get(3, 0)
            except _DECODE_FAULTS as e:
                raise ParquetError(
                    f"corrupt parquet pages: {type(e).__name__}"
                ) from None
            if any(len(v) < nrows for v in cols):
                raise ParquetError(
                    "row group shorter than its declared num_rows"
                )
            for i in range(nrows):
                yield {
                    name: (MISSING if vals[i] is None else vals[i])
                    for name, vals in zip(names, cols)
                }
            yielded += nrows
        if yielded != self.num_rows:
            raise ParquetError(
                f"file declares {self.num_rows} rows but row groups "
                f"carry {yielded}"
            )


def read_records(stream):
    """S3 Select record source (select.go parquet branch): parquet
    needs random access to the footer, so the object is materialized
    (the reference's reader seeks the underlying object the same
    way; select payload sizes make this bounded)."""
    data = stream.read()
    yield from ParquetReader(data).rows()


def clean_raw_row(row: dict) -> dict:
    """SELECT * cleanup: drop null slots (JSON-style omission)."""
    return {k: v for k, v in row.items() if v is not MISSING}


# ---------------------------------------------------------------------------
# minimal writer (tests + subset documentation): flat schema, one row
# group, PLAIN encoding, uncompressed, v1 data pages
# ---------------------------------------------------------------------------


class _ThriftW:
    def __init__(self):
        self.out = bytearray()
        self._fid = [0]

    def _varint(self, v: int):
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                return

    def _zigzag(self, v: int):
        self._varint((v << 1) ^ (v >> 63) if v < 0 else v << 1)

    def field(self, fid: int, ctype: int):
        delta = fid - self._fid[-1]
        self._fid[-1] = fid
        if 0 < delta <= 15:
            self.out.append((delta << 4) | ctype)
        else:
            self.out.append(ctype)
            self._zigzag(fid)

    def i(self, fid: int, v: int):
        self.field(fid, _CT_I64)
        self._zigzag(v)

    def b(self, fid: int, v: bytes):
        self.field(fid, _CT_BINARY)
        self._varint(len(v))
        self.out += v

    def begin_struct(self, fid: int):
        self.field(fid, _CT_STRUCT)
        self._fid.append(0)

    def end_struct(self):
        self.out.append(_CT_STOP)
        self._fid.pop()

    def begin_list(self, fid: int, etype: int, n: int):
        self.field(fid, _CT_LIST)
        if n < 15:
            self.out.append((n << 4) | etype)
        else:
            self.out.append(0xF0 | etype)
            self._varint(n)
        self._fid.append(0)  # list elements are structs here

    def end_list(self):
        self._fid.pop()


def _encode_plain(ptype: int, vals: list) -> bytes:
    if ptype == T_BOOLEAN:
        out = bytearray((len(vals) + 7) // 8)
        for i, v in enumerate(vals):
            if v:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)
    if ptype == T_INT64:
        return struct.pack(f"<{len(vals)}q", *vals)
    if ptype == T_DOUBLE:
        return struct.pack(f"<{len(vals)}d", *vals)
    if ptype == T_BYTE_ARRAY:
        out = bytearray()
        for v in vals:
            raw = str(v).encode()
            out += struct.pack("<I", len(raw)) + raw
        return bytes(out)
    raise ParquetError(f"writer: unsupported type {ptype}")


def write_parquet(columns: "list[tuple[str, int, list]]") -> bytes:
    """(name, physical_type, values) columns -> parquet bytes.
    None values mark nulls (the column becomes OPTIONAL)."""
    nrows = len(columns[0][2]) if columns else 0
    body = bytearray(MAGIC)
    chunk_meta = []
    for name, ptype, vals in columns:
        required = all(v is not None for v in vals)
        present = [v for v in vals if v is not None]
        payload = bytearray()
        if not required:
            # definition levels, RLE hybrid (one RLE run per value
            # would be wasteful; bit-pack in groups of 8)
            defs = [0 if v is None else 1 for v in vals]
            groups = (len(defs) + 7) // 8
            bits = bytearray(groups)
            for i, d in enumerate(defs):
                if d:
                    bits[i // 8] |= 1 << (i % 8)
            hybrid = bytes([(groups << 1) | 1]) + bytes(bits)
            payload += struct.pack("<I", len(hybrid)) + hybrid
        payload += _encode_plain(ptype, present)
        # PageHeader
        ph = _ThriftW()
        ph.field(1, _CT_I32)
        ph._zigzag(0)  # DATA_PAGE
        ph.field(2, _CT_I32)
        ph._zigzag(len(payload))
        ph.field(3, _CT_I32)
        ph._zigzag(len(payload))
        ph.begin_struct(5)  # DataPageHeader
        ph.field(1, _CT_I32)
        ph._zigzag(nrows)
        ph.field(2, _CT_I32)
        ph._zigzag(ENC_PLAIN)
        ph.field(3, _CT_I32)
        ph._zigzag(ENC_RLE)
        ph.field(4, _CT_I32)
        ph._zigzag(ENC_RLE)
        ph.end_struct()
        ph.out.append(_CT_STOP)
        offset = len(body)
        body += ph.out + payload
        chunk_meta.append(
            (name, ptype, required, offset, len(ph.out) + len(payload))
        )
    # FileMetaData
    fm = _ThriftW()
    fm.field(1, _CT_I32)
    fm._zigzag(1)  # version
    fm.begin_list(2, _CT_STRUCT, len(columns) + 1)  # schema
    fm._fid.append(0)  # root element struct
    fm.field(4, _CT_BINARY)
    fm._varint(len(b"schema"))
    fm.out += b"schema"
    fm.field(5, _CT_I32)
    fm._zigzag(len(columns))
    fm.out.append(_CT_STOP)
    fm._fid.pop()
    for name, ptype, required, _off, _ln in chunk_meta:
        fm._fid.append(0)
        fm.field(1, _CT_I32)
        fm._zigzag(ptype)
        fm.field(3, _CT_I32)
        fm._zigzag(0 if required else 1)
        fm.field(4, _CT_BINARY)
        fm._varint(len(name.encode()))
        fm.out += name.encode()
        fm.out.append(_CT_STOP)
        fm._fid.pop()
    fm.end_list()
    fm.i(3, nrows)
    fm.begin_list(4, _CT_STRUCT, 1)  # one row group
    fm._fid.append(0)
    fm.begin_list(1, _CT_STRUCT, len(chunk_meta))  # columns
    for name, ptype, required, off, ln in chunk_meta:
        fm._fid.append(0)
        fm.begin_struct(3)  # ColumnMetaData
        fm.field(1, _CT_I32)
        fm._zigzag(ptype)
        fm.begin_list(2, _CT_I32, 1)
        fm._zigzag(ENC_PLAIN)
        fm._fid.pop()
        fm.begin_list(3, _CT_BINARY, 1)
        fm._varint(len(name.encode()))
        fm.out += name.encode()
        fm._fid.pop()
        fm.field(4, _CT_I32)
        fm._zigzag(CODEC_UNCOMPRESSED)
        fm.i(5, nrows)
        fm.field(7, _CT_I64)
        fm._zigzag(ln)
        fm.field(8, _CT_I64)
        fm._zigzag(ln)
        fm.field(9, _CT_I64)
        fm._zigzag(off)
        fm.end_struct()
        fm.out.append(_CT_STOP)
        fm._fid.pop()
    fm.end_list()
    fm.i(2, len(body) - 4)  # total_byte_size
    fm.i(3, nrows)
    fm.out.append(_CT_STOP)
    fm._fid.pop()
    fm.end_list()
    fm.out.append(_CT_STOP)
    meta = bytes(fm.out)
    return bytes(body) + meta + struct.pack("<I", len(meta)) + MAGIC
