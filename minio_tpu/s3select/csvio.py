"""Streaming CSV record reader/writer for S3 Select
(pkg/s3select/csv/reader.go + the RequestProgress CSV options).

Rows surface as dicts: header names when FileHeaderInfo=USE, positional
``_1.._N`` otherwise.
"""

from __future__ import annotations

import csv
import io

from .sql import to_output


class CSVArgs:
    """InputSerialization.CSV options (csv/args.go)."""

    def __init__(
        self,
        file_header_info: str = "NONE",  # NONE | USE | IGNORE
        record_delimiter: str = "\n",
        field_delimiter: str = ",",
        quote_character: str = '"',
        quote_escape_character: str = '"',
        comments: str = "",
    ):
        self.file_header_info = (file_header_info or "NONE").upper()
        self.record_delimiter = record_delimiter or "\n"
        self.field_delimiter = field_delimiter or ","
        self.quote_character = quote_character or '"'
        self.quote_escape_character = quote_escape_character or '"'
        self.comments = comments


def read_records(stream, args: CSVArgs):
    """Yield row dicts from a binary file-like object."""
    text = io.TextIOWrapper(stream, encoding="utf-8", newline="")
    rd = "\n" if args.record_delimiter in ("\n", "\r\n") else args.record_delimiter

    # quote-escape semantics: same char as the quote -> doubled quotes
    # (csv doublequote mode); a distinct char -> escapechar mode
    csv_opts = {
        "delimiter": args.field_delimiter,
        "quotechar": args.quote_character,
    }
    if args.quote_escape_character != args.quote_character:
        csv_opts["doublequote"] = False
        csv_opts["escapechar"] = args.quote_escape_character

    if rd != "\n":
        # uncommon delimiter: re-split manually, then parse each record
        data = text.read()
        lines = data.split(args.record_delimiter)
        if lines and lines[-1] == "":
            lines.pop()
        reader = csv.reader(lines, **csv_opts)
    else:
        reader = csv.reader(text, **csv_opts)

    header: "list[str] | None" = None
    mode = args.file_header_info
    # the header is the first NON-COMMENT record, not reader index 0
    header_pending = mode in ("USE", "IGNORE")
    for rec in reader:
        if args.comments and rec and rec[0].startswith(args.comments):
            continue
        if header_pending:
            if mode == "USE":
                header = [h.strip() for h in rec]
            header_pending = False
            continue
        row: dict = {}
        for j, v in enumerate(rec):
            row[f"_{j + 1}"] = v
            if header is not None and j < len(header):
                row[header[j]] = v
        yield row


class CSVWriter:
    """OutputSerialization.CSV record serializer."""

    def __init__(
        self,
        record_delimiter: str = "\n",
        field_delimiter: str = ",",
        quote_character: str = '"',
        quote_fields: str = "ASNEEDED",  # ASNEEDED | ALWAYS
    ):
        self.rd = record_delimiter or "\n"
        self.fd = field_delimiter or ","
        self.qc = quote_character or '"'
        self.always = (quote_fields or "ASNEEDED").upper() == "ALWAYS"

    def _field(self, s: str) -> str:
        needs = self.always or any(
            c in s for c in (self.fd, self.qc, "\n", "\r")
        )
        if needs:
            return self.qc + s.replace(self.qc, self.qc * 2) + self.qc
        return s

    def serialize(self, record: dict) -> bytes:
        """Emit every key as-is: projected records are fully
        intentional; SELECT * rows are cleaned by the engine first."""
        return (
            self.fd.join(
                self._field(to_output(v)) for v in record.values()
            )
            + self.rd
        ).encode()


def positional(k: str) -> bool:
    """Reader-minted positional alias (_1.._N)."""
    return k.startswith("_") and k[1:].isdigit()


def clean_raw_row(row: dict) -> dict:
    """SELECT * cleanup for CSV rows: when header names exist, emit
    them (file order) and drop the shadowing _N aliases."""
    named = {k: v for k, v in row.items() if not positional(k)}
    return named or row
