"""TPU-pushdown S3 Select: device-side scan/filter as a pre-filter.

The device engine never decides a match.  One fused SWAR pass
(ops/select_step.py) runs a CONSERVATIVE candidate screen compiled
from the WHERE tree — it may flag rows that do not match, never the
reverse — and only the candidate row slices cross D2H through the
drain seam.  The candidate bytes are then re-fed to the proven host
engines (``vector.FastScan._chunk``, with its own row-engine
fallback), so exactness, projections, aggregates, LIMIT, and every
output-serialization rule are inherited rather than re-implemented:
the device's contribution is pure, result-proportional filtering.

Fallback ladder (exactness-over-speed, mirroring vector.py):

* unsupported WHERE shape / unresolvable column -> host engine for
  the whole stream (``screen=None``);
* hazard chunk (quote, bare CR, NUL, digit-e exponent under any
  numeric screen), candidate ratio above the
  screen-usefulness cap, candidate overflow, or a row wider than the
  widest window -> host engine for that chunk;
* anything the host fast path then dislikes -> its row engine, as
  always.

MTPU111: device buffers cross D2H only inside the ``_drain_*`` seam
functions below; an eager ``np.asarray``/``jax.device_get`` anywhere
else in this module fails the analysis gate.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from . import sql, vector
from ..ops import select_step as ss

DEV_CHUNK = 32 << 20  # stream read size: amortize the fixed jit cost
_RATIO_CAP = 0.25  # screen candidates / rows above this: host chunk
_MIN_RATIO_ROWS = 4096  # don't ratio-fallback tiny chunks
_MAX_CANDS = 1 << 20
_ROW_WINDOWS = (256, 1024, 4096)  # forward row-span ladder
_BACK_WINDOW = 1024  # backward anchor scan for mid-row field hits
# Longest literal integer-part the len/nd/deep atoms enumerate; a
# wider literal raises _Unscreenable (host engine) so query input
# cannot unroll the jitted screen — this bounds _max_shift and the
# per-statement compile cost.
_LEN_CAP = 30


class SelectStats:
    """Thread-safe counters behind miniotpu_select_* (server/metrics)."""

    ENGINES = ("device", "host", "row")
    REASONS = (
        "unsupported", "hazard", "ratio", "overflow", "wide", "error",
    )

    def __init__(self):
        self._mu = threading.Lock()
        self.reset()

    def reset(self):
        with getattr(self, "_mu", threading.Lock()):
            self.requests = {e: 0 for e in self.ENGINES}
            self.fallbacks = {r: 0 for r in self.REASONS}
            self.scanned_bytes = 0
            self.returned_bytes = 0
            self.device_seconds = 0.0

    def request(self, engine: str) -> None:
        with self._mu:
            self.requests[engine] = self.requests.get(engine, 0) + 1

    def fallback(self, reason: str) -> None:
        with self._mu:
            self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1

    def io(self, scanned: int, returned: int) -> None:
        with self._mu:
            self.scanned_bytes += scanned
            self.returned_bytes += returned

    def device_time(self, seconds: float) -> None:
        with self._mu:
            self.device_seconds += seconds

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "requests": dict(self.requests),
                "fallbacks": dict(self.fallbacks),
                "scanned_bytes": self.scanned_bytes,
                "returned_bytes": self.returned_bytes,
                "device_seconds": self.device_seconds,
            }


STATS = SelectStats()


def select_mode() -> str:
    """MINIO_TPU_SELECT: device | host | row | auto (default).

    ``row`` is the bisection oracle — the per-row engine, byte-for-byte
    the pre-device behavior; ``host`` pins the numpy columnar scan."""
    mode = os.environ.get("MINIO_TPU_SELECT", "auto").strip().lower()
    return mode if mode in ("device", "host", "row", "auto") else "auto"


# -- placement: scans ride the least-loaded submesh --------------------

_router = None
_router_mu = threading.Lock()


def _scan_router():
    global _router
    with _router_mu:
        if _router is None:
            import jax

            from ..parallel.rules import PlacementRouter

            _router = PlacementRouter(jax.devices())
        return _router


# -- screen compilation ------------------------------------------------


class _Unscreenable(Exception):
    pass


def _lit_bytes(value) -> bytes:
    if isinstance(value, bool):
        raise _Unscreenable("bool literal")
    if isinstance(value, (int, float)):
        return sql._to_str(value).encode("utf-8", "replace")
    if isinstance(value, str):
        return value.encode("utf-8", "replace")
    raise _Unscreenable(f"literal {type(value).__name__}")


def _numeric_atoms(op: str, lit) -> tuple:
    """OR-branches for a numeric compare: the numeric coercion branch
    (length window + nonconforming first bytes) unioned with the exact
    lexicographic screen of the string-compare branch sql._compare
    takes when a field fails to coerce."""
    s = _lit_bytes(lit)
    digits = len(s.lstrip(b"+-").split(b".")[0])
    if digits > _LEN_CAP:
        raise _Unscreenable(f"literal width {digits} > {_LEN_CAP}")
    nonconf = ("byte0", 43, 48)  # '+' ',' '-' '.' '/' '0' first byte
    if op in ("<", "<="):
        return (
            (("len", 0, digits),),
            (("nd", digits + 2),),
            (nonconf,),
            (("lex", s, "le" if op == "<=" else "lt"),),
        )
    if op in (">", ">="):
        # deep(digits) == len(digits, inf): any field at least as long
        # as the literal's integer part may exceed it
        return (
            (("deep", digits),),
            (nonconf,),
            (("lex", s, "ge" if op == ">=" else "gt"),),
        )
    if op == "=":
        return (
            (("lex", s, "eq"),),
            (nonconf,),
            (("nd", digits + 2),),
        )
    raise _Unscreenable(f"numeric op {op}")


def _string_atoms(op: str, lit: str) -> tuple:
    s = _lit_bytes(lit)
    modes = {"<": "lt", "<=": "le", "=": "eq", ">=": "ge", ">": "gt"}
    if op not in modes:
        raise _Unscreenable(f"string op {op}")
    return ((("lex", s, modes[op]),),)


_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}


class _Screen:
    __slots__ = ("atoms", "anchor", "sci_guard")

    def __init__(self, atoms, anchor, sci_guard=False):
        self.atoms = atoms
        self.anchor = anchor
        self.sci_guard = sci_guard


def _column_index(node, header) -> int:
    """0-based field index of a Column node; positional ``_N`` always
    resolves, named columns need the (lowercased) header row."""
    name = node.name
    if name.startswith("_") and name[1:].isdigit():
        n = int(name[1:])
        if n < 1:
            raise _Unscreenable(f"column {name}")
        return n - 1
    if header is None:
        raise _Unscreenable("named column without header")
    try:
        return header.index(name.lower())
    except ValueError:
        raise _Unscreenable(f"unknown column {name}") from None


def _compare_screen(node, header) -> _Screen:
    left, right = node.left, node.right
    op = node.op
    if isinstance(right, sql.Column) and isinstance(left, sql.Literal):
        left, right = right, left
        op = _FLIP.get(op) or _unscreen(f"op {node.op}")
    if not (
        isinstance(left, sql.Column) and isinstance(right, sql.Literal)
    ):
        raise _Unscreenable("compare shape")
    j = _column_index(left, header)
    val = right.value
    if isinstance(val, bool) or val is None:
        raise _Unscreenable("literal kind")
    sci = False
    if isinstance(val, (int, float)):
        atoms = _numeric_atoms(op, val)
        # any numeric compare can be matched by a digit-prefixed
        # exponent field no shape atom bounds ("1e6" > 99999 without
        # tripping deep/byte0/lex); the kernel's sci hazard covers
        # that gap for every op
        sci = True
    elif isinstance(val, str):
        atoms = _string_atoms(op, val)
    else:
        raise _Unscreenable("literal kind")
    return _Screen(atoms, "row" if j == 0 else "field", sci)


def _unscreen(msg):
    raise _Unscreenable(msg)


def compile_screen(node, header=None) -> _Screen:
    """WHERE tree -> conservative screen; raises _Unscreenable."""
    if isinstance(node, sql.Compare):
        return _compare_screen(node, header)
    if isinstance(node, sql.Between) and not node.negate:
        hi = sql.Compare("<=", node.expr, node.hi)
        return _compare_screen(hi, header)
    if isinstance(node, sql.In) and not node.negate:
        branches = []
        anchor = "row"
        for opt in node.options:
            scr = _compare_screen(
                sql.Compare("=", node.expr, opt), header
            )
            branches.extend(scr.atoms)
            if scr.anchor == "field":
                anchor = "field"
        return _Screen(tuple(branches), anchor, True)
    if isinstance(node, sql.Logical):
        if node.op == "and":
            err = None
            for term in (node.left, node.right):
                try:
                    return compile_screen(term, header)
                except _Unscreenable as e:
                    err = e
            raise err
        if node.op == "or" and node.right is not None:
            a = compile_screen(node.left, header)
            b = compile_screen(node.right, header)
            anchor = (
                "row"
                if a.anchor == b.anchor == "row"
                else "field"
            )
            return _Screen(
                a.atoms + b.atoms, anchor,
                a.sci_guard or b.sci_guard,
            )
    raise _Unscreenable(type(node).__name__)


def device_eligible(stmt, req) -> bool:
    """Static gate: the host fast path must be eligible (it is the
    exactness layer), there must be a WHERE to screen on, and the
    screen must compile — possibly deferred when it needs the header
    row (DeviceScan retries with the header, then pins host)."""
    if not vector.eligible(stmt, req):
        return False
    if stmt.where is None:
        return False
    try:
        compile_screen(stmt.where, None)
    except _Unscreenable:
        if req.csv_args.file_header_info != "USE":
            return False
    return True


# -- drain seam: the only D2H crossings in this module -----------------


def _drain_scalars(*vals):
    return tuple(np.asarray(v).item() for v in vals)


def _drain_array(dev):
    return np.asarray(dev)


def _drain_fallback_chunk(dev_arr, nbytes: int) -> bytes:
    """Whole-chunk readback, used only when a device-ineligible chunk
    arrived device-resident (cache-tier source) and must fall back to
    the host engines."""
    return _drain_array(dev_arr[:nbytes]).tobytes()


def drain_plane(dev_arr, nbytes: int) -> bytes:
    """Full readback of a cache-tier byte plane for queries the device
    engine cannot take (no WHERE, JSON output of a row scan, mode
    pins) — the engine layer wraps this in a BytesIO and runs the host
    path it would have run over a spooled object."""
    return _drain_fallback_chunk(dev_arr, nbytes)


# -- the scan ----------------------------------------------------------


class DeviceScan(vector.FastScan):
    """FastScan whose chunks are pre-filtered on device.

    ``_chunk`` screens the chunk's word planes on device, drains the
    candidate row spans, and hands ONLY those rows (plus the chunk's
    first row, which has no preceding anchor and covers the pending
    header) to the parent's exact machinery."""

    read_size = DEV_CHUNK

    def __init__(self, stmt, req, writer, clean, sink):
        super().__init__(stmt, req, writer, clean, sink)
        self._screen = None
        self._screen_failed = False
        self._header_seen = False
        try:
            self._screen = compile_screen(stmt.where, None)
        except _Unscreenable:
            pass  # retry once the header row is known

    # -- screen lifecycle ----------------------------------------------

    def _ensure_screen(self, data: bytes):
        if self._screen is not None or self._screen_failed:
            return self._screen
        a = self.req.csv_args
        if a.file_header_info != "USE" or self._header_seen:
            self._screen_failed = True
            STATS.fallback("unsupported")
            return None
        self._header_seen = True
        line = data.split(b"\n", 1)[0].rstrip(b"\r")
        header = [
            c.strip().strip(a.quote_character).lower()
            for c in line.decode("utf-8", "replace").split(
                a.field_delimiter
            )
        ]
        try:
            self._screen = compile_screen(self.stmt.where, header)
        except _Unscreenable:
            self._screen_failed = True
            STATS.fallback("unsupported")
        return self._screen

    # -- per-chunk device filter ---------------------------------------

    def _chunk(self, data: bytes) -> None:
        scr = self._ensure_screen(data)
        if scr is None:
            return super()._chunk(data)
        filtered = self._filter_host_bytes(data, scr)
        if filtered is None:
            return super()._chunk(data)
        if filtered:
            super()._chunk(filtered)

    def _filter_host_bytes(self, data: bytes, scr):
        """Screen host bytes on device -> candidate-row bytes, or None
        for a whole-chunk host fallback."""
        import jax
        from jax.experimental import enable_x64

        t0 = time.perf_counter()
        router = _scan_router()
        sub = router.route(1)
        try:
            with enable_x64():
                pad = (-len(data)) % ss.BLOCK_BYTES
                arr_np = np.frombuffer(
                    data + bytes([ss.PAD_BYTE]) * pad, dtype=np.uint8
                )
                dev = (
                    sub.devices[0] if sub is not None else None
                )
                arr = jax.device_put(arr_np, device=dev)
                spans = self._screen_spans(arr, len(data), scr)
                if spans is None:
                    return None
                starts, ends = spans
                out = bytearray()
                for s, e in zip(starts.tolist(), ends.tolist()):
                    out += data[s:e]
                return bytes(out)
        finally:
            if sub is not None:
                router.release(sub)
            STATS.device_time(time.perf_counter() - t0)

    def _screen_spans(self, arr, nbytes: int, scr):
        """Shared device phase: (starts, ends) numpy row spans of the
        candidate rows (newline included), or None -> chunk fallback."""
        import jax.numpy as jnp

        a = self.req.csv_args
        cand, blk, nrows_d, haz_d = ss.screen_chunk(
            arr,
            fd=self.fd_byte,
            qc=self.qc_byte,
            atoms=scr.atoms,
            anchor=scr.anchor,
            sci_guard=scr.sci_guard,
        )
        cum = jnp.cumsum(blk)
        haz, nrows, count = _drain_scalars(haz_d, nrows_d, cum[-1])
        if haz:
            STATS.fallback("hazard")
            return None
        if count > _MAX_CANDS:
            STATS.fallback("overflow")
            return None
        anchors = np.empty(0, dtype=np.int64)
        if count:
            if (
                nrows >= _MIN_RATIO_ROWS
                and count > nrows * _RATIO_CAP
            ):
                STATS.fallback("ratio")
                return None
            cap = 1 << max(6, (count - 1).bit_length())
            pos_d = ss.extract_positions(cand, cum, cap=cap)
            if scr.anchor == "field":
                anch_d, found_d = ss.anchors_back(
                    arr, pos_d, window=_BACK_WINDOW
                )
                anch = _drain_array(anch_d)[:count]
                found = _drain_array(found_d)[:count]
                if not found.all():
                    STATS.fallback("wide")
                    return None
                anchors = anch
            else:
                anchors = _drain_array(pos_d)[:count]
        # the chunk's first row always rides along: it has no
        # preceding-newline anchor, and it is the pending header row
        anchors = np.unique(np.concatenate([[-1], anchors]))
        anchors = anchors[anchors + 1 < nbytes]
        if not len(anchors):
            return np.empty(0, np.int64), np.empty(0, np.int64)
        starts = anchors + 1
        lens = None
        anchors_d = None
        for window in _ROW_WINDOWS:
            import jax

            if anchors_d is None:
                anchors_d = jax.device_put(
                    anchors.astype(np.int32),
                    device=arr.devices().pop()
                    if hasattr(arr, "devices")
                    else None,
                )
            lens_d, found_d = ss.row_spans(
                arr, anchors_d, window=window
            )
            found = _drain_array(found_d)
            if found.all():
                lens = _drain_array(lens_d)
                break
        if lens is None:
            STATS.fallback("wide")
            return None
        return starts, starts + lens + 1  # keep the newline

    # -- device-resident source (cache-tier scans) ---------------------

    def run_device(self, dev_arr, nbytes: int) -> int:
        """Scan a device-resident byte plane (already padded with
        PAD_BYTE to a BLOCK_BYTES multiple, newline-terminated at
        ``nbytes - 1``); only candidate rows are gathered D2H."""
        import jax
        from jax.experimental import enable_x64

        scr = self._screen
        if scr is None and not self._screen_failed:
            # deferred screen: resolve the header row from a bounded
            # prefix readback, then screen device-side as usual
            head = _drain_fallback_chunk(dev_arr, min(nbytes, 65536))
            scr = self._ensure_screen(head)
        if scr is None:
            # unsupported screen: one full readback, then the host
            # engines own the stream
            data = _drain_fallback_chunk(dev_arr, nbytes)
            super()._chunk(data)
            return self.matched
        t0 = time.perf_counter()
        router = _scan_router()
        sub = router.route(1)
        try:
            with enable_x64():
                spans = self._screen_spans(dev_arr, nbytes, scr)
                if spans is None:
                    data = _drain_fallback_chunk(dev_arr, nbytes)
                    super()._chunk(data)
                    return self.matched
                starts, ends = spans
                if not len(starts):
                    return self.matched
                lens = ends - starts
                wmax = int(lens.max())
                window = 1
                while window < wmax:
                    window <<= 1
                window = max(window, 64)
                starts_d = jax.device_put(starts.astype(np.int32))
                mat = _drain_array(
                    ss.gather_rows(dev_arr, starts_d, window=window)
                )
                out = bytearray()
                for i, ln in enumerate(lens.tolist()):
                    out += mat[i, :ln].tobytes()
                super()._chunk(bytes(out))
                return self.matched
        finally:
            if sub is not None:
                router.release(sub)
            STATS.device_time(time.perf_counter() - t0)


def as_device_plane(chunks, total: int):
    """Assemble cache-tier group buffers into one padded device byte
    plane (device-side concat: no host round-trip).  ``chunks`` are
    device or host arrays in stream order; returns (plane, nbytes)
    with nbytes covering ``total`` plus a terminating newline."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    with enable_x64():
        flat = []
        for c in chunks:
            a = jnp.asarray(c)
            if a.dtype != jnp.uint8:
                a = jax.lax.bitcast_convert_type(a, jnp.uint8)
            flat.append(a.reshape(-1))
        plane = jnp.concatenate(flat)[:total]
        # newline-terminate only when the object doesn't already (an
        # unconditional one would invent a trailing blank row)
        last = _drain_scalars(plane[total - 1])[0] if total else 10
        tail = b"" if last == 10 else b"\n"
        nbytes = total + len(tail)
        pad = (-nbytes) % ss.BLOCK_BYTES
        if tail or pad:
            suffix = jax.device_put(
                np.frombuffer(
                    tail + bytes([ss.PAD_BYTE]) * pad, dtype=np.uint8
                )
            )
            plane = jnp.concatenate([plane, suffix])
        return plane, nbytes
