"""S3 Select: SQL over streamed CSV/JSON objects
(pkg/s3select in the reference, 30k LoC; handler at
cmd/object-handlers.go:91 SelectObjectContentHandler).

Architecture here: a hand-rolled recursive-descent SQL parser and
row-at-a-time evaluator (``sql``), streaming record readers (``csvio``,
``jsonio``), AWS EventStream response framing (``message``), and the
orchestrator (``engine``) that wires request XML -> reader -> evaluator
-> framed response.  The evaluator is a pure host-side component - the
reference's simdjson acceleration is CPU-bound parsing, not a
TPU-shaped workload (SURVEY.md section 2.9: "host-side; not on the
north-star path").
"""

from .engine import S3Select, SelectError

__all__ = ["S3Select", "SelectError"]
