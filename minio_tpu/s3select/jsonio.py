"""Streaming JSON record reader/writer for S3 Select
(pkg/s3select/json/reader.go; Type=DOCUMENT|LINES).

Nested objects flatten onto dotted paths (a.b.c) so the SQL column
model stays flat, mirroring how the reference's jstream record exposes
nested access.
"""

from __future__ import annotations

import json

from .sql import MISSING, SQLError, to_json_value


class JSONArgs:
    def __init__(self, json_type: str = "LINES"):
        self.json_type = (json_type or "LINES").upper()
        if self.json_type not in ("LINES", "DOCUMENT"):
            raise SQLError("bad Json Type", "InvalidJsonType")


def _flatten(obj, prefix: str, out: dict) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            path = f"{prefix}.{k}" if prefix else str(k)
            out[path] = _scalarize(v)
            _flatten(v, path, out)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            path = f"{prefix}.{i}" if prefix else str(i)
            out[path] = _scalarize(v)
            _flatten(v, path, out)


def _scalarize(v):
    """Lists/dicts stay as structured values for output; scalars pass."""
    return v


def _record(obj) -> dict:
    if not isinstance(obj, dict):
        return {"_1": obj}
    out: dict = {}
    _flatten(obj, "", out)
    return out


def read_records(stream, args: JSONArgs):
    """Yield row dicts from a binary stream of JSON."""
    if args.json_type == "DOCUMENT":
        try:
            doc = json.load(stream)
        except ValueError as e:
            raise SQLError(f"bad JSON: {e}", "InvalidTextEncoding") from None
        if isinstance(doc, list):
            for item in doc:
                yield _record(item)
        else:
            yield _record(doc)
        return
    # LINES: one JSON value per line (blank lines skipped)
    for line in stream:
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError as e:
            raise SQLError(
                f"bad JSON line: {e}", "InvalidTextEncoding"
            ) from None
        yield _record(obj)


class JSONWriter:
    """OutputSerialization.JSON serializer (one object per record)."""

    def __init__(self, record_delimiter: str = "\n"):
        self.rd = record_delimiter or "\n"

    def serialize(self, record: dict) -> bytes:
        """Emit every key as-is (projected records are intentional;
        SELECT * rows are cleaned by the engine first)."""
        clean = {
            k: to_json_value(v)
            for k, v in record.items()
            if v is not MISSING
        }
        return (json.dumps(clean, default=str) + self.rd).encode()


def clean_raw_row(row: dict) -> dict:
    """SELECT * cleanup for JSON rows: emit the document's top-level
    keys only (flattened dotted child paths are internal)."""
    return {k: v for k, v in row.items() if "." not in k}
