"""Vectorized CSV scan for S3 Select (pkg/s3select/csv/reader.go).

The reference gets its CSV speed from a zero-copy splitter feeding a
worker pool of C-backed record parsers; the tpu-native equivalent is a
columnar batch scan: each ~1 MiB chunk is split into rows/fields with
numpy index arithmetic (no per-row Python), referenced columns are
materialized as fixed-width byte matrices (one ``astype`` parses a
whole numeric column in C), and the WHERE tree is compiled to boolean
mask algebra over those columns.  Matched rows of a ``SELECT *`` are
emitted as raw line slices of the input chunk - the scan never
round-trips bytes through row dicts at all.

Exactness over speed: any shape whose semantics the mask algebra
cannot reproduce bit-for-bit against the row engine - quoted fields,
ragged rows, mixed (non-numeric) columns under numeric comparison,
expressions beyond column/literal algebra - drops to the row engine,
per chunk when the stream allows it (quote-free prefix stays fast) or
statically via :func:`eligible`.
"""

from __future__ import annotations

import csv
import io
import re

import numpy as np

from . import sql
from .sql import MISSING, SQLError

# scan granularity (also the fallback spill unit): 1 MiB keeps the
# chunk plus its boolean/positional temporaries inside L2/L3 - larger
# chunks measurably thrash the cache (2x slower at 4 MiB on 1 core)
CHUNK = 1 << 20

# widest single field the matrix extractor will materialize; wider
# fields are legitimate CSV but force the chunk to the row engine
MAX_FIELD_WIDTH = 4096


class _Ineligible(Exception):
    """Internal: this statement/chunk shape needs the row engine."""


# ---------------------------------------------------------------------------
# static eligibility
# ---------------------------------------------------------------------------


def _supported_where(node) -> bool:
    if node is None:
        return True
    if isinstance(node, sql.Literal):
        return node.value is not None and node.value is not MISSING
    if isinstance(node, sql.Column):
        return True
    if isinstance(node, sql.Arith):
        return node.op != "||" and _supported_where(
            node.left
        ) and _supported_where(node.right)
    if isinstance(node, sql.Compare):
        return _supported_where(node.left) and _supported_where(node.right)
    if isinstance(node, sql.Between):
        return all(
            _supported_where(e) for e in (node.expr, node.lo, node.hi)
        )
    if isinstance(node, sql.In):
        return _supported_where(node.expr) and all(
            isinstance(o, sql.Literal) and _supported_where(o)
            for o in node.options
        )
    if isinstance(node, sql.Like):
        return (
            isinstance(node.expr, sql.Column)
            and node._compiled is not None
        )
    if isinstance(node, sql.IsNull):
        return isinstance(node.expr, sql.Column)
    if isinstance(node, sql.Logical):
        return _supported_where(node.left) and (
            node.right is None or _supported_where(node.right)
        )
    return False


def eligible(stmt, req) -> bool:
    """True when the statement + serialization shapes fit the
    vectorized scan; decided before any stream byte is consumed."""
    if req.input_format != "CSV":
        return False
    a = req.csv_args
    if a.record_delimiter not in ("\n", "\r\n"):
        return False
    if len(a.field_delimiter) != 1 or len(a.quote_character) != 1:
        return False
    if len(a.quote_escape_character) != 1:
        return False
    if a.comments:
        return False
    try:
        a.field_delimiter.encode("ascii")
        a.quote_character.encode("ascii")
        a.quote_escape_character.encode("ascii")
    except UnicodeEncodeError:
        return False
    if stmt.is_aggregate:
        # every aggregate must be COUNT(*) / COUNT(col) / SUM/MIN/
        # MAX/AVG(col)
        for agg in stmt.aggregates:
            if agg.arg is not None and not isinstance(
                agg.arg, sql.Column
            ):
                return False
        # projections must be bare aggregates (wrapping expressions
        # re-evaluate via _resolve_aggregates, which is fine, but the
        # accumulation itself is what we vectorize)
    elif stmt.projections is not None:
        for p in stmt.projections:
            if not isinstance(p.expr, sql.Column):
                return False
    return _supported_where(stmt.where)


# ---------------------------------------------------------------------------
# chunk scanner
# ---------------------------------------------------------------------------


_POW10 = 10.0 ** np.arange(0, 24)


def _parse_decimal_matrix(mat: np.ndarray) -> "np.ndarray | None":
    """Exact vectorized decimal parse of a (rows, W) NUL-padded byte
    matrix -> float64, or None if any row needs the general parser.

    Handles [+-]ddd[.ddd] with <= 15 total digits: the digit sums
    build the integer mantissa M exactly (< 2^53), and one float
    division M / 10^d is correctly rounded - so the result is
    bit-identical to Python's float()/strtod on the same text.
    Scientific notation, inf/nan, hex, and longer digit strings
    return None (caller falls back)."""
    rows, w = mat.shape
    if rows == 0:
        return np.zeros(0, dtype=np.float64)
    if w > 23:
        return None
    is_digit = (mat >= 48) & (mat <= 57)
    is_pad = mat == 0
    if w <= 15 and (is_digit | is_pad).all():
        # unsigned integer column: Horner over the (few) columns,
        # exact in float64 below 10^15
        m = np.zeros(rows, dtype=np.int64)
        for i in range(w):
            d = is_digit[:, i]
            m = np.where(d, m * 10 + (mat[:, i] - 48), m)
        if not is_digit[:, 0].all():
            return None  # empty fields
        return m.astype(np.float64)
    is_dot = mat == 46
    first = mat[:, 0]
    has_sign = (first == 45) | (first == 43)
    allowed = is_digit | is_dot | is_pad
    allowed[:, 0] |= has_sign
    if not allowed.all():
        return None
    ndots = is_dot.sum(axis=1)
    total = is_digit.sum(axis=1)
    if (ndots > 1).any() or (total == 0).any() or (total > 15).any():
        return None
    # digits after position i (within the row) give each digit its
    # place value in the mantissa
    cum = np.cumsum(is_digit, axis=1)
    place = total[:, None] - cum
    dig = (mat - 48) * is_digit
    mant = (dig * _POW10[place]).sum(axis=1)
    # count of digits right of the dot = mantissa scale
    dotpos = np.where(ndots > 0, is_dot.argmax(axis=1), w)
    digits_left = np.take_along_axis(
        cum, np.minimum(dotpos, w - 1)[:, None], axis=1
    ).ravel()
    digits_left = np.where(ndots > 0, digits_left, total)
    scale = total - digits_left
    out = mant / _POW10[scale]
    return np.where(first == 45, -out, out)


class _Chunk:
    """One newline-terminated slice of the stream, split columnarly."""

    def __init__(self, data: bytes, fd_byte: int):
        self.data = data
        arr = np.frombuffer(data, dtype=np.uint8)
        self.arr = arr
        # chunks are <= a few MiB: 32-bit offsets halve the memory
        # traffic of every index matrix built below
        nl = np.flatnonzero(arr == 10).astype(np.int32)
        row_start = np.empty(len(nl), dtype=np.int32)
        if len(nl):
            row_start[0] = 0
            row_start[1:] = nl[:-1] + 1
        row_end = nl.copy()
        # tolerate \r\n rows (strip the \r from every non-empty row)
        nonempty = row_end > row_start
        cr = np.zeros(len(nl), dtype=bool)
        if nonempty.any():
            cr[nonempty] = arr[row_end[nonempty] - 1] == 13
        row_end -= cr
        # drop blank rows (the csv module skips them too)
        keep = row_end > row_start
        self.row_start = row_start[keep]
        self.row_end = row_end[keep]
        self.rows = len(self.row_start)
        self.blank_dropped = self.rows != len(nl)
        self.trimmed = False  # header row dropped in place
        self._fd = fd_byte
        self._seps = None  # (rows, F-1) separator positions
        self._ncols = -1
        self._mat_cache: dict[int, np.ndarray] = {}
        self._str_cache: dict[int, np.ndarray] = {}
        self._num_cache: dict[int, "np.ndarray | None"] = {}

    def drop_first_row(self) -> None:
        """Consume the header row without re-parsing the chunk; call
        before uniform_fields (separator layout is row-relative)."""
        self.row_start = self.row_start[1:]
        self.row_end = self.row_end[1:]
        self.rows -= 1
        self.trimmed = True

    def uniform_fields(self) -> int:
        """Field count when every row has the same; -1 for ragged."""
        if self._ncols != -1:
            return self._ncols
        is_sep = self.arr == self._fd
        seps = np.flatnonzero(is_sep).astype(np.int32)
        # cumulative count beats two binary searches over the
        # separator list (O(n) sequential vs O(rows log seps))
        csum = np.cumsum(is_sep, dtype=np.int32)
        before = csum[self.row_start] - is_sep[self.row_start]
        per_row = csum[self.row_end - 1] - before
        if self.rows == 0:
            self._ncols = 0
            return 0
        first = int(per_row[0])
        if not (per_row == first).all():
            self._ncols = -2
            return -1
        self._ncols = first + 1
        if first:
            idx = before[:, None] + np.arange(
                first, dtype=np.int32
            )[None, :]
            self._seps = seps[idx]
        else:
            self._seps = np.empty((self.rows, 0), dtype=np.int32)
        return self._ncols

    def _bounds(self, j: int):
        F = self._ncols
        starts = (
            self.row_start if j == 0 else self._seps[:, j - 1] + 1
        )
        ends = self._seps[:, j] if j < F - 1 else self.row_end
        return starts, ends

    def _col_matrix(self, j: int) -> np.ndarray:
        """Column j as a (rows, W) uint8 matrix, NUL right-padded."""
        cached = self._mat_cache.get(j)
        if cached is not None:
            return cached
        starts, ends = self._bounds(j)
        widths = ends - starts
        w = int(widths.max()) if len(widths) else 1
        if w > MAX_FIELD_WIDTH:
            raise _Ineligible("oversized field")
        w = max(w, 1)
        idx = starts[:, None] + np.arange(w, dtype=np.int32)[None, :]
        valid = idx < ends[:, None]
        mat = np.where(valid, self.arr[np.where(valid, idx, 0)], 0)
        mat = np.ascontiguousarray(mat, dtype=np.uint8)
        self._mat_cache[j] = mat
        return mat

    def col_str(self, j: int) -> np.ndarray:
        """Column j as a fixed-width S array (NUL right-padded)."""
        cached = self._str_cache.get(j)
        if cached is not None:
            return cached
        mat = self._col_matrix(j)
        out = mat.view(f"S{mat.shape[1]}").ravel()
        self._str_cache[j] = out
        return out

    def col_num(self, j: int) -> "np.ndarray | None":
        """Column j parsed as float64, or None when any field fails
        to parse (mixed columns get exact row-engine semantics)."""
        if j in self._num_cache:
            return self._num_cache[j]
        mat = self._col_matrix(j)
        out = _parse_decimal_matrix(mat)
        if out is None:
            # scientific notation / long digits: numpy's (slower but
            # general) parser, still correctly rounded like float()
            try:
                out = self.col_str(j).astype(np.float64)
            except ValueError:
                out = None
        self._num_cache[j] = out
        return out

    def line(self, i: int) -> bytes:
        return self.data[self.row_start[i] : self.row_end[i]]

    def field(self, i: int, j: int) -> bytes:
        starts, ends = self._bounds(j)
        return self.data[starts[i] : ends[i]]


# ---------------------------------------------------------------------------
# WHERE compiler: AST -> (kind, value) over one chunk
#   kind 'num': float64 array or python float
#   kind 'str': S array or python bytes
#   kind 'bool': bool array (no nulls arise: columns are never null,
#                null literals are statically ineligible)
# ---------------------------------------------------------------------------


class _Cols:
    """Resolves Column names to indices for one header layout."""

    def __init__(self, header: "list[str] | None", ncols: int):
        self.ncols = ncols
        self.by_name: dict[str, int] = {}
        if header:
            for j, h in enumerate(header[:ncols]):
                self.by_name.setdefault(h, j)
                self.by_name.setdefault(h.lower(), j)

    def index(self, name: str) -> int:
        if name.startswith("_") and name[1:].isdigit():
            j = int(name[1:]) - 1
            if 0 <= j < self.ncols:
                return j
            raise _Ineligible(f"positional {name} out of range")
        j = self.by_name.get(name)
        if j is None:
            j = self.by_name.get(name.lower())
        if j is None:
            raise _Ineligible(f"unresolvable column {name}")
        return j


def _lit_value(v):
    if isinstance(v, bool):
        return ("str", sql._to_str(v).encode())
    if isinstance(v, (int, float)):
        return ("num", float(v))
    return ("str", str(v).encode())


def _eval_vec(node, chunk: _Chunk, cols: _Cols):
    if isinstance(node, sql.Literal):
        return _lit_value(node.value)
    if isinstance(node, sql.Column):
        return ("col", cols.index(node.name))
    if isinstance(node, sql.Arith):
        a = _as_num(_eval_vec(node.left, chunk, cols), chunk)
        b = _as_num(_eval_vec(node.right, chunk, cols), chunk)
        if node.op == "+":
            return ("num", a + b)
        if node.op == "-":
            return ("num", a - b)
        if node.op == "*":
            return ("num", a * b)
        if node.op == "/":
            if np.any(b == 0):
                raise SQLError("division by zero", "InvalidDataType")
            return ("num", a / b)
        if node.op == "%":
            if np.any(b == 0):
                raise SQLError("modulo by zero", "InvalidDataType")
            return ("num", np.mod(a, b))
        raise _Ineligible(node.op)
    if isinstance(node, sql.Compare):
        return (
            "bool",
            _vec_compare(
                node.op,
                _eval_vec(node.left, chunk, cols),
                _eval_vec(node.right, chunk, cols),
                chunk,
            ),
        )
    if isinstance(node, sql.Between):
        v = _eval_vec(node.expr, chunk, cols)
        lo = _vec_compare(
            ">=", v, _eval_vec(node.lo, chunk, cols), chunk
        )
        hi = _vec_compare(
            "<=", v, _eval_vec(node.hi, chunk, cols), chunk
        )
        m = lo & hi
        return ("bool", ~m if node.negate else m)
    if isinstance(node, sql.In):
        v = _eval_vec(node.expr, chunk, cols)
        m = np.zeros(chunk.rows, dtype=bool)
        for o in node.options:
            m |= _vec_compare("=", v, _eval_vec(o, chunk, cols), chunk)
        return ("bool", ~m if node.negate else m)
    if isinstance(node, sql.Like):
        j = cols.index(node.expr.name)
        vals = chunk.col_str(j)
        m = _vec_like(node, vals)
        return ("bool", ~m if node.negate else m)
    if isinstance(node, sql.IsNull):
        cols.index(node.expr.name)  # must resolve (else row engine)
        m = np.zeros(chunk.rows, dtype=bool)  # CSV fields never null
        return ("bool", ~m if node.negate else m)
    if isinstance(node, sql.Logical):
        a = _as_bool(_eval_vec(node.left, chunk, cols))
        if node.op == "not":
            return ("bool", ~a)
        b = _as_bool(_eval_vec(node.right, chunk, cols))
        return ("bool", a & b if node.op == "and" else a | b)
    raise _Ineligible(type(node).__name__)


def _vec_like(node, vals: np.ndarray) -> np.ndarray:
    """LIKE over an S column.  The four common wildcard shapes map to
    C-loop string kernels (np.char); anything else (inner '_', mixed
    wildcards, escapes) runs the compiled regex per value."""
    pat = node.pattern.value if isinstance(
        node.pattern, sql.Literal
    ) else None
    esc = node.escape
    if isinstance(pat, str) and esc is None and "_" not in pat:
        body = pat.strip("%")
        if "%" not in body and "_" not in body:
            b = body.encode()
            if pat.startswith("%") and pat.endswith("%") and len(pat) > 1:
                # NUL padding never matches real content
                return np.char.find(vals, b) >= 0
            if pat.endswith("%"):
                return np.char.startswith(vals, b)
            if pat.startswith("%"):
                # trailing NUL pad defeats np endswith: strip first
                return np.char.endswith(
                    np.char.rstrip(vals, b"\x00"), b
                )
            return vals == b
    rx = node._compiled
    return np.fromiter(
        (
            rx.match(x.decode("utf-8", "replace")) is not None
            for x in vals
        ),
        dtype=bool,
        count=len(vals),
    )


def _as_num(tv, chunk: _Chunk):
    kind, v = tv
    if kind == "num":
        return v
    if kind == "col":
        col = chunk.col_num(v)
        if col is None:
            raise _Ineligible("non-numeric column in arithmetic")
        return col
    raise _Ineligible("string operand in arithmetic")


def _as_bool(tv):
    kind, v = tv
    if kind != "bool":
        raise _Ineligible("non-boolean operand in logical")
    return v


def _vec_compare(op: str, a, b, chunk: _Chunk) -> np.ndarray:
    """Mirror sql._compare: numeric compare when both sides coerce
    and they are not both strings; else bytewise string compare."""
    ka, va = a
    kb, vb = b
    # column vs column: CSV fields are strings -> string compare
    if ka == "col" and kb == "col":
        va, vb = chunk.col_str(va), chunk.col_str(vb)
    elif ka == "col":
        if kb == "num":
            col = chunk.col_num(va)
            if col is None:
                # mixed column: per-row semantics flip between numeric
                # and string compare - row engine territory
                raise _Ineligible("mixed column vs numeric literal")
            va = col
        else:
            va = chunk.col_str(va)
    elif kb == "col":
        if ka == "num":
            col = chunk.col_num(vb)
            if col is None:
                raise _Ineligible("numeric literal vs mixed column")
            vb = col
        else:
            vb = chunk.col_str(vb)
    elif ka != kb:
        # literal num vs literal str: the row engine coerces; rare
        raise _Ineligible("cross-type literal compare")
    if op == "=":
        return va == vb
    if op in ("!=", "<>"):
        return va != vb
    if op == "<":
        return va < vb
    if op == "<=":
        return va <= vb
    if op == ">":
        return va > vb
    if op == ">=":
        return va >= vb
    raise _Ineligible(op)


# ---------------------------------------------------------------------------
# the scan driver
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# JSON-lines fast scan (aggregate/filter queries over flat objects)
# ---------------------------------------------------------------------------


def json_eligible(stmt, req) -> bool:
    """The JSON twin of :func:`eligible`, restricted to fully
    aggregate statements (no record output to serialize): the
    reference leans on simdjson here (pkg/s3select/simdj); the numpy
    equivalent extracts referenced scalar fields with one compiled
    regex pass per column and runs the same mask algebra."""
    if req.input_format != "JSON":
        return False
    if req.json_args.json_type != "LINES":
        return False
    if not stmt.is_aggregate:
        return False
    for agg in stmt.aggregates:
        if agg.arg is not None and not isinstance(agg.arg, sql.Column):
            return False
    return _supported_where(stmt.where)


class _JChunk:
    """Column provider over one chunk of flat JSON lines.  Extraction
    is regex-per-column over the raw bytes; any ambiguity (nesting,
    escapes, missing keys, null/bool tokens under numeric use) raises
    _Ineligible so the chunk re-runs on the row engine."""

    _NUM_RX: dict = {}
    _STR_RX: dict = {}

    def __init__(self, data: bytes, nlines: int):
        self.data = data
        self.rows = nlines
        self._num_cache: dict = {}
        self._str_cache: dict = {}
        self._kind: dict = {}  # name -> 'num' | 'str'

    def _extract(self, name: str):
        if name in self._kind:
            return
        key = re.escape(name.encode())
        nrx = _JChunk._NUM_RX.get(name)
        if nrx is None:
            nrx = re.compile(
                rb'"' + key + rb'"\s*:\s*(-?[0-9][^,}\s]*)'
            )
            srx = re.compile(rb'"' + key + rb'"\s*:\s*"([^"]*)"')
            _JChunk._NUM_RX[name] = nrx
            _JChunk._STR_RX[name] = srx
        srx = _JChunk._STR_RX[name]
        vals = nrx.findall(self.data)
        if len(vals) == self.rows:
            try:
                self._num_cache[name] = np.asarray(
                    vals, dtype="S"
                ).astype(np.float64)
                self._kind[name] = "num"
                return
            except ValueError:
                raise _Ineligible(f"non-numeric token for {name}")
        svals = srx.findall(self.data)
        if len(svals) == self.rows:
            self._str_cache[name] = np.asarray(svals, dtype="S")
            self._kind[name] = "str"
            return
        raise _Ineligible(f"irregular key {name}")

    def col_num(self, name: str):
        self._extract(name)
        if self._kind[name] == "num":
            return self._num_cache[name]
        # string-typed field under numeric use: same as CSV columns
        try:
            return self._str_cache[name].astype(np.float64)
        except ValueError:
            return None

    def col_str(self, name: str):
        self._extract(name)
        if self._kind[name] == "str":
            return self._str_cache[name]
        # a native JSON number compared as a string cannot reproduce
        # the row engine's numeric-coercion semantics cheaply
        raise _Ineligible(f"numeric field {name} in string context")


class _JCols:
    """Column resolver for JSON rows: names resolve to themselves;
    existence is validated at extraction time."""

    def index(self, name: str) -> str:
        if name.startswith("_") and name[1:].isdigit():
            raise _Ineligible("positional ref over JSON")
        return name


class FastJSONScan:
    """Aggregate-only scan over flat JSON lines."""

    def __init__(self, stmt, req):
        self.stmt = stmt
        self.req = req

    def run(self, stream) -> None:
        carry = b""
        while True:
            buf = stream.read(CHUNK)
            if not buf:
                break
            data = carry + buf
            cut = data.rfind(b"\n")
            if cut < 0:
                carry = data
                continue
            carry = data[cut + 1 :]
            self._chunk(data[: cut + 1])
        if carry:
            self._chunk(carry + b"\n")

    def _chunk(self, data: bytes) -> None:
        # structural guards: one flat object per line, no escapes, no
        # arrays, no nested objects, no strings containing braces
        nonblank = sum(
            1 for ln in data.splitlines() if ln and not ln.isspace()
        )
        if nonblank == 0:
            return
        if (
            b"\\" in data
            or b"[" in data
            or data.count(b"{") != nonblank
            or data.count(b"}") != nonblank
        ):
            self._slow_chunk(data)
            return
        chunk = _JChunk(data, nonblank)
        cols = _JCols()
        try:
            if self.stmt.where is None:
                mask = np.ones(chunk.rows, dtype=bool)
            else:
                mask = _as_bool(_eval_vec(self.stmt.where, chunk, cols))
                if np.ndim(mask) == 0:
                    mask = np.full(chunk.rows, bool(mask))
            self._accumulate(chunk, cols, mask)
        except _Ineligible:
            self._slow_chunk(data)

    def _accumulate(self, chunk: _JChunk, cols, mask) -> None:
        def exists(name):
            chunk._extract(cols.index(name))
            return True

        _vec_accumulate(
            self.stmt.aggregates,
            mask,
            lambda name: chunk.col_num(cols.index(name)),
            exists,
        )

    def _slow_chunk(self, data: bytes) -> None:
        from . import jsonio

        for row in jsonio.read_records(
            io.BytesIO(data), self.req.json_args
        ):
            if self.stmt.matches(row):
                self.stmt.accumulate(row)


def _vec_accumulate(aggregates, mask, col_num, col_exists) -> None:
    """Shared aggregate accumulator (CSV + JSON fast scans).

    Two-phase so a fallback replay never double-counts: every column
    is resolved and validated BEFORE any aggregate state mutates.
    SUM/AVG fold sequentially from the existing accumulator (numpy
    cumsum is a strict left fold) so results are bit-identical to the
    row engine's value-by-value float additions, independent of chunk
    boundaries."""
    nsel = int(mask.sum())
    plan = []
    for agg in aggregates:
        if agg.func == "count":
            if agg.arg is not None and not col_exists(agg.arg.name):
                raise _Ineligible("unresolvable COUNT column")
            plan.append((agg, None))
            continue
        col = col_num(agg.arg.name)
        if col is None:
            raise _Ineligible("aggregate over non-numeric column")
        vals = col[mask]
        if np.isnan(vals).any():
            # nan ordering in min/max diverges from the row engine
            raise _Ineligible("nan in aggregate column")
        plan.append((agg, vals))
    for agg, vals in plan:
        if agg.func == "count":
            # fields extracted here are never null, so COUNT(col)
            # counts every matched row, like COUNT(*)
            agg.count += nsel
            continue
        if nsel == 0:
            continue
        agg.count += nsel
        if agg.func in ("sum", "avg"):
            base = 0.0 if agg.acc is None else agg.acc
            agg.acc = float(
                np.cumsum(np.concatenate(([base], vals)))[-1]
            )
        elif agg.func == "min":
            v = float(vals.min())
            agg.acc = v if agg.acc is None else min(agg.acc, v)
        elif agg.func == "max":
            v = float(vals.max())
            agg.acc = v if agg.acc is None else max(agg.acc, v)


def _gather(arr: np.ndarray, starts, ends) -> np.ndarray:
    """Variable-width byte ranges -> (rows, Wmax) NUL-padded matrix."""
    widths = ends - starts
    w = max(int(widths.max()) if len(widths) else 1, 1)
    idx = starts[:, None] + np.arange(w)[None, :]
    valid = idx < ends[:, None]
    return np.ascontiguousarray(
        np.where(valid, arr[np.where(valid, idx, 0)], 0),
        dtype=np.uint8,
    )


def _matrix_payload(
    mats: "list[np.ndarray]", fd: bytes, rd: bytes, qc: bytes = b""
) -> bytes:
    """Serialize NUL-padded column matrices to delimited records in
    one pass: interleave constant delimiter/quote columns, flatten,
    and strip the padding NULs with bytes.translate (C speed).  Valid
    because field content never contains NUL (guarded upstream)."""
    rows = mats[0].shape[0]

    def const_col(b: bytes) -> np.ndarray:
        return np.tile(
            np.frombuffer(b, dtype=np.uint8), (rows, 1)
        )

    parts = []
    for i, m in enumerate(mats):
        if i:
            parts.append(const_col(fd))
        if qc:
            parts.append(const_col(qc))
            parts.append(m)
            parts.append(const_col(qc))
        else:
            parts.append(m)
    parts.append(const_col(rd))
    return np.hstack(parts).tobytes().translate(None, b"\x00")


class FastScan:
    """Drives one statement over one CSV byte stream, vectorized with
    exact row-engine fallback at chunk granularity."""

    def __init__(self, stmt, req, writer, clean, emit):
        self.stmt = stmt
        self.req = req
        self.writer = writer
        self.clean = clean
        self.emit = emit  # receives serialized record payload bytes
        a = req.csv_args
        self.fd_byte = ord(a.field_delimiter)
        self.qc_byte = ord(a.quote_character)
        self.qc = a.quote_character
        self.header: "list[str] | None" = None
        self.header_pending = a.file_header_info in ("USE", "IGNORE")
        self.matched = 0
        self.done = False
        # raw-line emit is valid when the output is CSV with the same
        # field delimiter and default ASNEEDED quoting (quote-free
        # chunks can never need quoting)
        w = req.csv_writer_args or {}
        self.raw_ok = (
            req.output_format == "CSV"
            and stmt.projections is None
            and not stmt.is_aggregate
            and (w.get("field_delimiter", ",") == a.field_delimiter)
            and (w.get("quote_fields", "ASNEEDED") == "ASNEEDED")
        )
        self.out_rd = (
            (w.get("record_delimiter") or "\n").encode()
            if req.output_format == "CSV"
            else b""
        )

    # -- stream pump ---------------------------------------------------

    def run(self, stream) -> int:
        a = self.req.csv_args
        esc_mode = a.quote_escape_character != a.quote_character
        esc_byte = ord(a.quote_escape_character)
        carry = b""
        size = getattr(self, "read_size", CHUNK)
        while not self.done:
            buf = stream.read(size)
            if not buf:
                break
            data = carry + buf
            if esc_mode and (
                self.qc_byte in data or esc_byte in data
            ):
                # escaped-quote grammar defeats the parity cut below:
                # hand the rest of the stream to the row engine
                self._slow_stream(data, stream)
                return self.matched
            cut = self._safe_cut(data)
            if cut < 0:
                if len(data) > 4 * size:
                    # a stray unbalanced quote would otherwise buffer
                    # the whole remaining object into carry
                    self._slow_stream(data, stream)
                    return self.matched
                carry = data
                continue
            carry = data[cut + 1 :]
            self._chunk(data[: cut + 1])
        if carry and not self.done:
            self._chunk(carry + b"\n")
        return self.matched

    def _safe_cut(self, data: bytes) -> int:
        """Last newline NOT inside a quoted field: with doubled-quote
        escaping, a newline is a record boundary iff the quote count
        to its left is even."""
        if self.qc_byte not in data:
            return data.rfind(b"\n")
        arr = np.frombuffer(data, dtype=np.uint8)
        nl = np.flatnonzero(arr == 10)
        if len(nl) == 0:
            return -1
        qpos = np.flatnonzero(arr == self.qc_byte)
        even = np.searchsorted(qpos, nl) % 2 == 0
        good = nl[even]
        return int(good[-1]) if len(good) else -1

    # -- per-chunk -----------------------------------------------------

    def _chunk(self, data: bytes) -> None:
        if self.qc_byte in data or 0 in data:
            # quoted grammar or embedded NULs (NUL is the padding
            # sentinel of the columnar matrices): exact row engine
            self._slow_chunk(data)
            return
        chunk = _Chunk(data, self.fd_byte)
        if chunk.blank_dropped:
            # the csv module yields [] for a blank line (an empty
            # record under SELECT *), which the columnar splitter
            # cannot represent - row engine for this chunk
            self._slow_chunk(data)
            return
        cr = np.flatnonzero(chunk.arr == 13)
        if len(cr) and (
            cr[-1] == len(data) - 1
            or not (chunk.arr[cr + 1] == 10).all()
        ):
            # a bare \r is a record boundary to the csv module but
            # field content to the splitter; only \r\n is fast
            self._slow_chunk(data)
            return
        if chunk.rows == 0:
            return
        # after the header row is consumed here, any fallback must
        # replay only the remaining rows, not the header line
        fallback = data
        if self.header_pending:
            if self.req.csv_args.file_header_info == "USE":
                self.header = [
                    f.decode("utf-8", "replace").strip()
                    for f in chunk.line(0).split(
                        self.req.csv_args.field_delimiter.encode()
                    )
                ]
            self.header_pending = False
            chunk.drop_first_row()
            if chunk.rows == 0:
                return
            fallback = data[chunk.row_start[0] :]
        F = chunk.uniform_fields()
        if F < 0:
            self._slow_chunk(fallback)
            return
        cols = _Cols(self.header, F)
        try:
            self._fast_rows(chunk, cols)
        except _Ineligible:
            self._slow_chunk(fallback)

    def _fast_rows(self, chunk: _Chunk, cols: _Cols) -> None:
        stmt = self.stmt
        if stmt.where is None:
            mask = np.ones(chunk.rows, dtype=bool)
        else:
            mask = _as_bool(_eval_vec(stmt.where, chunk, cols))
            if np.ndim(mask) == 0:  # literal-only predicate
                mask = np.full(chunk.rows, bool(mask))
        if stmt.is_aggregate:
            self._accumulate(chunk, cols, mask)
            return
        sel = np.flatnonzero(mask)
        limit_hit = False
        if stmt.limit is not None:
            room = stmt.limit - self.matched
            if len(sel) >= room:
                sel = sel[:room]
                limit_hit = True
        if len(sel) == 0:
            self.done = self.done or limit_hit
            return
        # NOTE every _Ineligible in the emit paths below fires before
        # the first emit() - so a fallback replay of this chunk never
        # double-emits, and matched/done only advance on success
        F = cols.ncols
        oqc = self._out_qc()
        if (
            self.raw_ok
            and self._star_is_whole_line(F)
            and (oqc == self.qc_byte or oqc not in chunk.data)
        ):
            if (
                len(sel) == chunk.rows
                and self.out_rd == b"\n"
                and not chunk.trimmed
                and not (chunk.arr[chunk.row_end] != 10).any()
            ):
                # everything matched, rows already \n-terminated:
                # the chunk IS the payload
                self.emit(chunk.data)
            else:
                self.emit(
                    _matrix_payload(
                        [
                            _gather(
                                chunk.arr,
                                chunk.row_start[sel],
                                chunk.row_end[sel],
                            )
                        ],
                        b"",
                        self.out_rd,
                    )
                )
        else:
            # projected columns / JSON output: records per matched row
            self._emit_records(chunk, cols, sel)
        self.matched += len(sel)
        self.done = self.done or limit_hit

    def _out_qc(self) -> int:
        w = self.req.csv_writer_args or {}
        qc = w.get("quote_character") or '"'
        return ord(qc) if len(qc) == 1 else -1

    def _star_is_whole_line(self, ncols: int) -> bool:
        """SELECT * equals the raw line only when the cleaned row keeps
        every field once, in order (no short or duplicate header)."""
        if self.header is None:
            return True
        return len(self.header) >= ncols and len(
            set(self.header)
        ) == len(self.header)

    def _emit_records(self, chunk: _Chunk, cols: _Cols, sel) -> None:
        stmt = self.stmt
        w = self.req.csv_writer_args or {}
        if self.req.output_format == "CSV":
            ofd = (w.get("field_delimiter") or ",").encode()
            ord_ = (w.get("record_delimiter") or "\n").encode()
            oqc = (w.get("quote_character") or '"').encode()
            always = (
                w.get("quote_fields", "ASNEEDED").upper() == "ALWAYS"
            )
            # field content is free of the INPUT delimiter/quote by
            # construction; a different OUTPUT delimiter/quote may
            # appear inside fields and would then need quoting that
            # the matrix serializer skips - guard on chunk content
            if (
                len(ofd) == 1
                and len(oqc) == 1
                and (
                    ofd[0] == self.fd_byte or ofd[0] not in chunk.data
                )
                and (
                    oqc[0] == self.qc_byte or oqc[0] not in chunk.data
                )
            ):
                js = self._out_columns(cols)
                if js is not None:
                    mats = [chunk._col_matrix(j)[sel] for j in js]
                    self.emit(
                        _matrix_payload(
                            mats, ofd, ord_, oqc if always else b""
                        )
                    )
                    return
        out = bytearray()
        if stmt.projections is None:
            fd = self.req.csv_args.field_delimiter.encode()
            for i in sel:
                fields = [
                    f.decode("utf-8", "replace")
                    for f in chunk.line(int(i)).split(fd)
                ]
                row: dict = {}
                for j, v in enumerate(fields):
                    row[f"_{j + 1}"] = v
                    if self.header and j < len(self.header):
                        row[self.header[j]] = v
                out += self.writer.serialize(self.clean(row))
        else:
            idxs = [
                (p.alias or f"_{k + 1}", cols.index(p.expr.name))
                for k, p in enumerate(stmt.projections)
            ]
            for i in sel:
                rec = {
                    alias: chunk.field(int(i), j).decode(
                        "utf-8", "replace"
                    )
                    for alias, j in idxs
                }
                out += self.writer.serialize(rec)
        self.emit(bytes(out))

    def _out_columns(self, cols: _Cols) -> "list[int] | None":
        """Output column indices for the vectorized CSV serializer, or
        None when the record shape needs the dict path."""
        stmt = self.stmt
        if stmt.projections is not None:
            try:
                return [
                    cols.index(p.expr.name) for p in stmt.projections
                ]
            except _Ineligible:
                return None
        # SELECT *: the cleaned row is the named fields in file order
        if self.header is None:
            return list(range(cols.ncols))
        if len(set(self.header)) != len(self.header):
            return None  # duplicate names collapse in the dict path
        return list(range(min(cols.ncols, len(self.header))))

    def _accumulate(self, chunk: _Chunk, cols: _Cols, mask) -> None:
        _vec_accumulate(
            self.stmt.aggregates,
            mask,
            lambda name: chunk.col_num(cols.index(name)),
            lambda name: cols.index(name) is not None,
        )

    # -- exact fallback (chunk granularity) ----------------------------

    def _slow_chunk(self, data: bytes) -> None:
        """Run one chunk through the row engine: exact semantics for
        quoted/ragged/mixed shapes.  The chunk boundary is safe for
        quoted newlines because _safe_cut only cuts at even quote
        parity; quote-free chunks before and after stay fast."""
        self._slow_rows(
            io.TextIOWrapper(
                io.BytesIO(data), encoding="utf-8", newline=""
            )
        )

    def _slow_stream(self, head: bytes, stream) -> None:
        """Row-engine the rest of the stream (escape-char grammar)."""

        class _Chain(io.RawIOBase):
            def __init__(self):
                self._head = memoryview(head)
                self._off = 0

            def readable(self):
                return True

            def readinto(self, b):
                if self._off < len(self._head):
                    n = min(len(b), len(self._head) - self._off)
                    b[:n] = self._head[self._off : self._off + n]
                    self._off += n
                    return n
                part = stream.read(len(b))
                if not part:
                    return 0
                b[: len(part)] = part
                return len(part)

        self._slow_rows(
            io.TextIOWrapper(
                io.BufferedReader(_Chain()),
                encoding="utf-8",
                newline="",
            )
        )

    def _slow_rows(self, text) -> None:
        a = self.req.csv_args
        opts = {
            "delimiter": a.field_delimiter,
            "quotechar": a.quote_character,
        }
        if a.quote_escape_character != a.quote_character:
            opts["doublequote"] = False
            opts["escapechar"] = a.quote_escape_character
        stmt = self.stmt
        for rec in csv.reader(text, **opts):
            if self.done:
                return
            if self.header_pending:
                if a.file_header_info == "USE":
                    self.header = [h.strip() for h in rec]
                self.header_pending = False
                continue
            row: dict = {}
            for j, v in enumerate(rec):
                row[f"_{j + 1}"] = v
                if self.header and j < len(self.header):
                    row[self.header[j]] = v
            if not stmt.matches(row):
                continue
            if stmt.is_aggregate:
                stmt.accumulate(row)
                continue
            out = stmt.project(row)
            if stmt.projections is None:
                out = self.clean(out)
            self.emit(self.writer.serialize(out))
            self.matched += 1
            if stmt.limit is not None and self.matched >= stmt.limit:
                self.done = True
