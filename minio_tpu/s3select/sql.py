"""SQL parser + evaluator for S3 Select (pkg/s3select/sql/ in the
reference - participle grammar + evaluator; here a recursive-descent
parser over the same language subset).

Supported: SELECT projections (*, columns, expressions, aggregates,
aliases), FROM S3Object [alias], WHERE, LIMIT; operators AND OR NOT,
comparisons, BETWEEN, IN, LIKE, IS [NOT] NULL/MISSING; arithmetic
+ - * / %; functions CAST, COUNT, SUM, MIN, MAX, AVG, COALESCE, NULLIF,
LOWER, UPPER, CHAR_LENGTH/CHARACTER_LENGTH, TRIM, SUBSTRING,
UTCNOW is intentionally absent (no wall-clock inside the evaluator).

Values are dynamically typed: str | int | float | bool | None, with
``MISSING`` as a distinct sentinel (absent column vs SQL NULL), matching
the reference's value system (pkg/s3select/sql/value.go).
"""

from __future__ import annotations

import re


class SQLError(Exception):
    """Parse or evaluation failure; carries an S3 error code."""

    def __init__(self, message: str, code: str = "ParseSelectFailure"):
        super().__init__(message)
        self.code = code


class _Missing:
    __slots__ = ()

    def __repr__(self):
        return "MISSING"

    def __bool__(self):
        return False


MISSING = _Missing()

# -- lexer ---------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<number>\d+(\.\d*)?([eE][+-]?\d+)?|\.\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><>|!=|<=|>=|\|\||[=<>\(\)\*,\.\+\-/%])
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "select", "from", "where", "limit", "as", "and", "or", "not",
    "between", "in", "like", "escape", "is", "null", "missing", "true",
    "false", "cast",
}


class _Token:
    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value):
        self.kind = kind  # number|string|ident|qident|op|kw|eof
        self.value = value

    def __repr__(self):
        return f"{self.kind}:{self.value!r}"


def _lex(text: str) -> "list[_Token]":
    out: list[_Token] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise SQLError(f"bad character {text[pos]!r} at {pos}", "LexerInvalidChar")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        val = m.group()
        if kind == "number":
            out.append(
                _Token("number", float(val) if "." in val or "e" in val
                       or "E" in val else int(val))
            )
        elif kind == "string":
            out.append(_Token("string", val[1:-1].replace("''", "'")))
        elif kind == "qident":
            out.append(_Token("qident", val[1:-1].replace('""', '"')))
        elif kind == "ident":
            low = val.lower()
            if low in _KEYWORDS:
                out.append(_Token("kw", low))
            else:
                out.append(_Token("ident", val))
        else:
            out.append(_Token("op", val))
    out.append(_Token("eof", None))
    return out


# -- AST -----------------------------------------------------------------


class Expr:
    def eval(self, row: dict):  # noqa: D102
        raise NotImplementedError

    def walk(self):
        yield self


class Literal(Expr):
    def __init__(self, value):
        self.value = value

    def eval(self, row):
        return self.value


class Column(Expr):
    """Column reference: name, _N positional, or * (in COUNT)."""

    def __init__(self, name: str):
        self.name = name

    def eval(self, row):
        if self.name in row:
            return row[self.name]
        # case-insensitive fallback (CSV headers are case-preserving
        # but references are case-insensitive in the reference's sql)
        low = self.name.lower()
        for k, v in row.items():
            if k.lower() == low:
                return v
        return MISSING


def _num(v):
    """Coerce to a number for arithmetic/comparison, or None."""
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        try:
            return int(v)
        except ValueError:
            try:
                return float(v)
            except ValueError:
                return None
    return None


def _is_null(v) -> bool:
    return v is None or v is MISSING


class Arith(Expr):
    def __init__(self, op, left, right):
        self.op, self.left, self.right = op, left, right

    def eval(self, row):
        a, b = self.left.eval(row), self.right.eval(row)
        if _is_null(a) or _is_null(b):
            return None
        if self.op == "||":
            return _to_str(a) + _to_str(b)
        na, nb = _num(a), _num(b)
        if na is None or nb is None:
            raise SQLError(
                f"non-numeric operand for {self.op}", "InvalidDataType"
            )
        if self.op == "+":
            return na + nb
        if self.op == "-":
            return na - nb
        if self.op == "*":
            return na * nb
        if self.op == "/":
            if nb == 0:
                raise SQLError("division by zero", "InvalidDataType")
            r = na / nb
            return r
        if self.op == "%":
            if nb == 0:
                raise SQLError("modulo by zero", "InvalidDataType")
            return na % nb
        raise SQLError(f"unknown operator {self.op}", "ParseUnknownOperator")

    def walk(self):
        yield self
        yield from self.left.walk()
        yield from self.right.walk()


def _compare(op: str, a, b):
    if _is_null(a) or _is_null(b):
        return None  # SQL three-valued logic
    # numeric comparison when both sides coerce; else string compare
    na, nb = _num(a), _num(b)
    if na is not None and nb is not None and not (
        isinstance(a, str) and isinstance(b, str)
    ):
        a, b = na, nb
    else:
        a, b = _to_str(a), _to_str(b)
    try:
        if op == "=":
            return a == b
        if op in ("!=", "<>"):
            return a != b
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
    except TypeError:
        return False
    raise SQLError(f"unknown comparison {op}", "ParseUnknownOperator")


class Compare(Expr):
    def __init__(self, op, left, right):
        self.op, self.left, self.right = op, left, right

    def eval(self, row):
        return _compare(self.op, self.left.eval(row), self.right.eval(row))

    def walk(self):
        yield self
        yield from self.left.walk()
        yield from self.right.walk()


class Between(Expr):
    def __init__(self, expr, lo, hi, negate):
        self.expr, self.lo, self.hi, self.negate = expr, lo, hi, negate

    def eval(self, row):
        v = self.expr.eval(row)
        lo = _compare(">=", v, self.lo.eval(row))
        hi = _compare("<=", v, self.hi.eval(row))
        if lo is None or hi is None:
            return None
        r = lo and hi
        return (not r) if self.negate else r

    def walk(self):
        yield self
        for e in (self.expr, self.lo, self.hi):
            yield from e.walk()


class In(Expr):
    def __init__(self, expr, options, negate):
        self.expr, self.options, self.negate = expr, options, negate

    def eval(self, row):
        v = self.expr.eval(row)
        if _is_null(v):
            return None
        hit = any(
            _compare("=", v, o.eval(row)) is True for o in self.options
        )
        return (not hit) if self.negate else hit

    def walk(self):
        yield self
        yield from self.expr.walk()
        for o in self.options:
            yield from o.walk()


class Like(Expr):
    def __init__(self, expr, pattern, escape, negate):
        self.expr, self.pattern = expr, pattern
        self.escape, self.negate = escape, negate
        # literal pattern/escape (the common case): compile ONCE, not
        # per row on the scan hot path
        self._compiled = None
        if isinstance(pattern, Literal) and (
            escape is None or isinstance(escape, Literal)
        ):
            esc = None
            if escape is not None and not _is_null(escape.value):
                esc = _to_str(escape.value)
            if not _is_null(pattern.value):
                self._compiled = self._regex(_to_str(pattern.value), esc)

    def _regex(self, pat: str, esc: "str | None"):
        out = []
        i = 0
        while i < len(pat):
            c = pat[i]
            if esc and c == esc and i + 1 < len(pat):
                out.append(re.escape(pat[i + 1]))
                i += 2
                continue
            if c == "%":
                out.append(".*")
            elif c == "_":
                out.append(".")
            else:
                out.append(re.escape(c))
            i += 1
        return re.compile("^" + "".join(out) + "$", re.DOTALL)

    def eval(self, row):
        v = self.expr.eval(row)
        if _is_null(v):
            return None
        if self._compiled is not None:
            rx = self._compiled
        else:
            p = self.pattern.eval(row)
            if _is_null(p):
                return None
            esc = None
            if self.escape is not None:
                e = self.escape.eval(row)
                if not _is_null(e):
                    esc = _to_str(e)
            rx = self._regex(_to_str(p), esc)
        hit = bool(rx.match(_to_str(v)))
        return (not hit) if self.negate else hit

    def walk(self):
        yield self
        yield from self.expr.walk()
        yield from self.pattern.walk()


class IsNull(Expr):
    def __init__(self, expr, negate, missing_only=False):
        self.expr, self.negate = expr, negate
        self.missing_only = missing_only

    def eval(self, row):
        v = self.expr.eval(row)
        hit = v is MISSING if self.missing_only else _is_null(v)
        return (not hit) if self.negate else hit

    def walk(self):
        yield self
        yield from self.expr.walk()


class Logical(Expr):
    def __init__(self, op, left, right=None):
        self.op, self.left, self.right = op, left, right

    def eval(self, row):
        if self.op == "not":
            v = self.left.eval(row)
            return None if v is None else not _truthy(v)
        a = self.left.eval(row)
        if self.op == "and":
            if a is not None and not _truthy(a):
                return False
            b = self.right.eval(row)
            if b is not None and not _truthy(b):
                return False
            return None if (a is None or b is None) else True
        if self.op == "or":
            if a is not None and _truthy(a):
                return True
            b = self.right.eval(row)
            if b is not None and _truthy(b):
                return True
            return None if (a is None or b is None) else False
        raise SQLError(f"unknown logical {self.op}", "ParseUnknownOperator")

    def walk(self):
        yield self
        yield from self.left.walk()
        if self.right is not None:
            yield from self.right.walk()


def _truthy(v) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        return v.lower() == "true"
    return bool(v)


def _to_str(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None or v is MISSING:
        return ""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


_AGGREGATES = {"count", "sum", "min", "max", "avg"}

_SCALAR_FUNCS = {
    "lower", "upper", "char_length", "character_length", "trim",
    "substring", "coalesce", "nullif", "abs", "float", "integer",
    "string", "to_string",
}


class Call(Expr):
    """Scalar function call."""

    def __init__(self, name: str, args: "list[Expr]"):
        self.name, self.args = name.lower(), args

    def eval(self, row):
        n = self.name
        args = self.args
        if n == "coalesce":
            for a in args:
                v = a.eval(row)
                if not _is_null(v):
                    return v
            return None
        if n == "nullif":
            a, b = args[0].eval(row), args[1].eval(row)
            return None if _compare("=", a, b) is True else a
        vals = [a.eval(row) for a in args]
        if any(_is_null(v) for v in vals):
            return None
        if n == "lower":
            return _to_str(vals[0]).lower()
        if n == "upper":
            return _to_str(vals[0]).upper()
        if n in ("char_length", "character_length"):
            return len(_to_str(vals[0]))
        if n == "trim":
            return _to_str(vals[0]).strip()
        if n == "abs":
            x = _num(vals[0])
            if x is None:
                raise SQLError("ABS needs a number", "InvalidDataType")
            return abs(x)
        if n == "substring":
            s = _to_str(vals[0])
            start = int(_num(vals[1]) or 1)
            # SQL is 1-based; negative/zero clamp like the reference
            begin = max(start - 1, 0)
            if len(vals) > 2:
                length = int(_num(vals[2]) or 0)
                end = max(start - 1 + length, begin)
                return s[begin:end]
            return s[begin:]
        raise SQLError(f"unsupported function {n}", "UnsupportedFunction")

    def walk(self):
        yield self
        for a in self.args:
            yield from a.walk()


class Cast(Expr):
    def __init__(self, expr, type_name: str):
        self.expr, self.type_name = expr, type_name.lower()

    def eval(self, row):
        v = self.expr.eval(row)
        if _is_null(v):
            return None
        t = self.type_name
        try:
            if t in ("int", "integer", "bigint", "smallint"):
                return int(float(v)) if not isinstance(v, bool) else int(v)
            if t in ("float", "double", "decimal", "numeric", "real"):
                return float(v)
            if t in ("string", "varchar", "char", "text"):
                return _to_str(v)
            if t in ("bool", "boolean"):
                if isinstance(v, str):
                    return v.lower() == "true"
                return bool(v)
        except (ValueError, TypeError):
            raise SQLError(
                f"cannot cast {v!r} to {t}", "InvalidDataType"
            ) from None
        raise SQLError(f"unknown CAST type {t}", "UnsupportedFunction")

    def walk(self):
        yield self
        yield from self.expr.walk()


class Aggregate(Expr):
    """COUNT/SUM/MIN/MAX/AVG accumulator node.  ``eval`` accumulates
    per-row; ``result`` reads the final value."""

    def __init__(self, func: str, arg: "Expr | None"):
        self.func = func
        self.arg = arg  # None for COUNT(*)
        self.count = 0
        self.acc = None

    def eval(self, row):
        if self.func == "count":
            if self.arg is None or not _is_null(self.arg.eval(row)):
                self.count += 1
            return None
        v = self.arg.eval(row)
        if _is_null(v):
            return None
        n = _num(v)
        if n is None:
            raise SQLError(
                f"{self.func.upper()} over non-numeric value",
                "InvalidDataType",
            )
        self.count += 1
        if self.acc is None:
            self.acc = n
        elif self.func == "sum" or self.func == "avg":
            self.acc += n
        elif self.func == "min":
            self.acc = min(self.acc, n)
        elif self.func == "max":
            self.acc = max(self.acc, n)
        return None

    def result(self):
        if self.func == "count":
            return self.count
        if self.acc is None:
            return None
        if self.func == "avg":
            return self.acc / self.count
        return self.acc

    def walk(self):
        yield self
        if self.arg is not None:
            yield from self.arg.walk()


# -- parser --------------------------------------------------------------


class _Parser:
    def __init__(self, tokens: "list[_Token]"):
        self.toks = tokens
        self.pos = 0

    def peek(self) -> _Token:
        return self.toks[self.pos]

    def next(self) -> _Token:
        t = self.toks[self.pos]
        self.pos += 1
        return t

    def expect_kw(self, kw: str):
        t = self.next()
        if t.kind != "kw" or t.value != kw:
            raise SQLError(f"expected {kw.upper()}, got {t.value!r}", "ParseExpectedKeyword")

    def accept_kw(self, kw: str) -> bool:
        t = self.peek()
        if t.kind == "kw" and t.value == kw:
            self.pos += 1
            return True
        return False

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.value == op:
            self.pos += 1
            return True
        return False

    # expression grammar: or_expr
    def parse_expr(self) -> Expr:
        return self._or()

    def _or(self) -> Expr:
        left = self._and()
        while self.accept_kw("or"):
            left = Logical("or", left, self._and())
        return left

    def _and(self) -> Expr:
        left = self._not()
        while self.accept_kw("and"):
            left = Logical("and", left, self._not())
        return left

    def _not(self) -> Expr:
        if self.accept_kw("not"):
            return Logical("not", self._not())
        return self._predicate()

    def _predicate(self) -> Expr:
        left = self._additive()
        t = self.peek()
        negate = False
        if t.kind == "kw" and t.value == "not":
            nxt = self.toks[self.pos + 1]
            if nxt.kind == "kw" and nxt.value in ("between", "in", "like"):
                self.pos += 1
                negate = True
                t = self.peek()
        if t.kind == "op" and t.value in ("=", "!=", "<>", "<", "<=", ">", ">="):
            self.pos += 1
            return Compare(t.value, left, self._additive())
        if t.kind == "kw" and t.value == "between":
            self.pos += 1
            lo = self._additive()
            self.expect_kw("and")
            return Between(left, lo, self._additive(), negate)
        if t.kind == "kw" and t.value == "in":
            self.pos += 1
            if not self.accept_op("("):
                raise SQLError("expected ( after IN", "ParseExpectedTokenType")
            opts = [self.parse_expr()]
            while self.accept_op(","):
                opts.append(self.parse_expr())
            if not self.accept_op(")"):
                raise SQLError("expected ) after IN list", "ParseExpectedTokenType")
            return In(left, opts, negate)
        if t.kind == "kw" and t.value == "like":
            self.pos += 1
            pattern = self._additive()
            escape = None
            if self.accept_kw("escape"):
                escape = self._additive()
            return Like(left, pattern, escape, negate)
        if t.kind == "kw" and t.value == "is":
            self.pos += 1
            neg = self.accept_kw("not")
            if self.accept_kw("null"):
                return IsNull(left, neg)
            if self.accept_kw("missing"):
                return IsNull(left, neg, missing_only=True)
            raise SQLError("expected NULL or MISSING after IS", "ParseExpectedKeyword")
        return left

    def _additive(self) -> Expr:
        left = self._multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-", "||"):
                self.pos += 1
                left = Arith(t.value, left, self._multiplicative())
            else:
                return left

    def _multiplicative(self) -> Expr:
        left = self._unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.pos += 1
                left = Arith(t.value, left, self._unary())
            else:
                return left

    def _unary(self) -> Expr:
        if self.accept_op("-"):
            return Arith("-", Literal(0), self._unary())
        if self.accept_op("+"):
            return self._unary()
        return self._primary()

    def _primary(self) -> Expr:
        t = self.next()
        if t.kind == "number":
            return Literal(t.value)
        if t.kind == "string":
            return Literal(t.value)
        if t.kind == "kw" and t.value == "true":
            return Literal(True)
        if t.kind == "kw" and t.value == "false":
            return Literal(False)
        if t.kind == "kw" and t.value == "null":
            return Literal(None)
        if t.kind == "kw" and t.value == "cast":
            if not self.accept_op("("):
                raise SQLError("expected ( after CAST", "ParseExpectedLeftParenAfterCast")
            e = self.parse_expr()
            self.expect_kw("as")
            tt = self.next()
            if tt.kind not in ("ident", "kw"):
                raise SQLError("expected type name in CAST", "ParseExpectedTypeName")
            if not self.accept_op(")"):
                raise SQLError("expected ) after CAST", "ParseCastArity")
            return Cast(e, str(tt.value))
        if t.kind == "op" and t.value == "(":
            e = self.parse_expr()
            if not self.accept_op(")"):
                raise SQLError("missing )", "ParseExpectedTokenType")
            return e
        if t.kind in ("ident", "qident"):
            name = t.value
            low = name.lower() if t.kind == "ident" else None
            # function call?
            if self.peek().kind == "op" and self.peek().value == "(":
                self.pos += 1
                if low in _AGGREGATES:
                    if self.accept_op("*"):
                        arg = None
                    else:
                        arg = self.parse_expr()
                    if not self.accept_op(")"):
                        raise SQLError("missing ) in aggregate", "ParseExpectedTokenType")
                    if low != "count" and arg is None:
                        raise SQLError(f"{low.upper()} needs an argument", "EvaluatorInvalidArguments")
                    return Aggregate(low, arg)
                args: list[Expr] = []
                if not self.accept_op(")"):
                    args.append(self.parse_expr())
                    while self.accept_op(","):
                        args.append(self.parse_expr())
                    if not self.accept_op(")"):
                        raise SQLError("missing ) in call", "ParseExpectedTokenType")
                if low not in _SCALAR_FUNCS:
                    raise SQLError(
                        f"unsupported function {name}",
                        "UnsupportedFunction",
                    )
                return Call(low, args)
            # column path: alias.column / alias."column" / _N
            parts = [name]
            while self.accept_op("."):
                nt = self.next()
                if nt.kind not in ("ident", "qident"):
                    raise SQLError("bad column path", "InvalidKeyPath")
                parts.append(nt.value)
            return Column(".".join(parts))
        raise SQLError(f"unexpected token {t.value!r}", "ParseUnexpectedToken")


class Projection:
    def __init__(self, expr: Expr, alias: str):
        self.expr = expr
        self.alias = alias


class SelectStatement:
    """Parsed SELECT, ready to stream rows through."""

    def __init__(
        self,
        projections: "list[Projection] | None",  # None = SELECT *
        where: "Expr | None",
        limit: "int | None",
        table_alias: str,
    ):
        self.projections = projections
        self.where = where
        self.limit = limit
        self.table_alias = table_alias
        self.aggregates: list[Aggregate] = []
        if projections:
            for p in projections:
                self.aggregates.extend(
                    n for n in p.expr.walk() if isinstance(n, Aggregate)
                )
            if self.aggregates and any(
                not any(isinstance(n, Aggregate) for n in p.expr.walk())
                for p in projections
            ):
                raise SQLError(
                    "cannot mix aggregate and row projections",
                    "UnsupportedSqlStructure",
                )

    @property
    def is_aggregate(self) -> bool:
        return bool(self.aggregates)

    def normalize_column(self, name: str) -> str:
        """Strip the table alias prefix from a column path."""
        alias = self.table_alias
        if alias and name.lower().startswith(alias.lower() + "."):
            return name[len(alias) + 1:]
        if name.lower().startswith("s3object."):
            return name[len("s3object."):]
        return name

    def bind(self) -> None:
        """Rewrite Column names to strip table aliases (done once)."""
        nodes = []
        if self.projections:
            for p in self.projections:
                nodes.extend(p.expr.walk())
        if self.where is not None:
            nodes.extend(self.where.walk())
        for n in nodes:
            if isinstance(n, Column):
                n.name = self.normalize_column(n.name)

    # -- row pipeline --------------------------------------------------

    def matches(self, row: dict) -> bool:
        if self.where is None:
            return True
        v = self.where.eval(row)
        return v is True or (not isinstance(v, (bool, type(None))) and _truthy(v))

    def project(self, row: dict) -> "dict | None":
        """Output record for a matching row (non-aggregate queries)."""
        if self.projections is None:
            return row
        out = {}
        for i, p in enumerate(self.projections):
            out[p.alias or f"_{i + 1}"] = p.expr.eval(row)
        return out

    def accumulate(self, row: dict) -> None:
        for p in self.projections or []:
            p.expr.eval(row)

    def aggregate_result(self) -> dict:
        out = {}
        for i, p in enumerate(self.projections or []):
            # replace every Aggregate node with its final value, then
            # evaluate whatever expression wraps it (CAST, COALESCE,
            # arithmetic over aggregates, ...)
            out[p.alias or f"_{i + 1}"] = _resolve_aggregates(
                p.expr
            ).eval({})
        return out


def _resolve_aggregates(node: Expr) -> Expr:
    """Rewrite Aggregate nodes into Literals of their final results so
    the surrounding expression evaluates normally.  Runs once, after
    the scan, so mutating the tree in place is safe."""
    if isinstance(node, Aggregate):
        return Literal(node.result())
    for attr in ("left", "right", "expr", "lo", "hi", "pattern", "escape"):
        child = getattr(node, attr, None)
        if isinstance(child, Expr):
            setattr(node, attr, _resolve_aggregates(child))
    if isinstance(node, Call):
        node.args = [_resolve_aggregates(a) for a in node.args]
    if isinstance(node, In):
        node.options = [_resolve_aggregates(o) for o in node.options]
    return node


def parse(expression: str) -> SelectStatement:
    """Parse a full S3 Select statement."""
    toks = _lex(expression)
    p = _Parser(toks)
    p.expect_kw("select")
    projections: "list[Projection] | None"
    if p.accept_op("*"):
        projections = None
    else:
        projections = []
        while True:
            e = p.parse_expr()
            alias = ""
            if p.accept_kw("as"):
                t = p.next()
                if t.kind not in ("ident", "qident"):
                    raise SQLError("bad alias", "ParseExpectedIdentForAlias")
                alias = t.value
            elif p.peek().kind in ("ident", "qident"):
                alias = p.next().value
            if not alias and isinstance(e, Column):
                alias = e.name.rpartition(".")[2]
            projections.append(Projection(e, alias))
            if not p.accept_op(","):
                break
    p.expect_kw("from")
    # FROM S3Object[.path] [[AS] alias]
    t = p.next()
    if t.kind not in ("ident", "qident") or t.value.lower() not in (
        "s3object",
    ):
        raise SQLError(
            "FROM must name S3Object", "InvalidDataSource"
        )
    while p.accept_op("."):
        step = p.next()  # json path steps on the table: accepted, ignored
        if step.kind not in ("ident", "qident"):
            raise SQLError("bad table path after FROM S3Object.", "InvalidKeyPath")
    table_alias = ""
    if p.accept_kw("as"):
        at = p.next()
        if at.kind not in ("ident", "qident"):
            raise SQLError("bad table alias", "InvalidTableAlias")
        table_alias = at.value
    elif p.peek().kind == "ident":
        table_alias = p.next().value
    where = None
    if p.accept_kw("where"):
        where = p.parse_expr()
    limit = None
    if p.accept_kw("limit"):
        lt = p.next()
        if lt.kind != "number" or not isinstance(lt.value, int):
            raise SQLError("LIMIT needs an integer", "ParseExpectedNumber")
        limit = lt.value
    if p.peek().kind != "eof":
        raise SQLError(f"trailing tokens at {p.peek().value!r}", "ParseUnexpectedToken")
    stmt = SelectStatement(projections, where, limit, table_alias)
    stmt.bind()
    return stmt


def to_output(v) -> str:
    """Serialize one value for CSV output."""
    return _to_str(v)


def to_json_value(v):
    if v is MISSING:
        return None
    if isinstance(v, float) and v.is_integer():
        return int(v)
    return v
