"""S3 Select orchestrator (pkg/s3select/select.go S3Select.Evaluate).

Parses the SelectObjectContentRequest document, streams object bytes
through the record reader, evaluates the statement row-by-row, and
emits EventStream frames (Records batches -> Stats -> End).
"""

from __future__ import annotations

import bz2
import gzip
import io
import xml.etree.ElementTree as ET

from ..utils.xmlutil import child, child_text, strip_ns
from . import csvio, jsonio, message, sql, vector

# Records payloads batch up to this size before a frame is flushed
# (maxRecordSize/bufioWriterSize in the reference's message writer)
BATCH_BYTES = 128 << 10


class SelectError(Exception):
    def __init__(self, code: str, msg: str):
        super().__init__(msg)
        self.code = code
        self.msg = msg


class SelectRequest:
    """Parsed SelectObjectContentRequest."""

    def __init__(self):
        self.expression = ""
        self.expression_type = "SQL"
        self.compression = "NONE"
        self.input_format = ""  # CSV | JSON | PARQUET
        self.csv_args = csvio.CSVArgs()
        self.json_args = jsonio.JSONArgs()
        self.output_format = ""  # CSV | JSON (defaults to input)
        self.csv_writer_args: dict = {}
        self.json_writer_args: dict = {}
        self.progress = False

    @classmethod
    def from_xml(cls, body: bytes) -> "SelectRequest":
        if not body:
            raise SelectError("EmptyRequestBody", "request body is empty")
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise SelectError(
                "MalformedXML", "request XML is not well-formed"
            ) from None
        if strip_ns(root.tag) != "SelectObjectContentRequest":
            raise SelectError(
                "MalformedXML", "not a SelectObjectContentRequest"
            )
        req = cls()
        req.expression = child_text(root, "Expression")
        req.expression_type = (
            child_text(root, "ExpressionType") or "SQL"
        ).upper()
        if req.expression_type != "SQL":
            raise SelectError(
                "InvalidExpressionType", "only SQL expressions supported"
            )
        if not req.expression:
            raise SelectError("MissingRequiredParameter", "no Expression")

        inser = child(root, "InputSerialization")
        if inser is None:
            raise SelectError(
                "MissingRequiredParameter", "no InputSerialization"
            )
        req.compression = (
            child_text(inser, "CompressionType") or "NONE"
        ).upper()
        if req.compression not in ("NONE", "GZIP", "BZIP2"):
            raise SelectError(
                "InvalidCompressionFormat",
                f"unsupported compression {req.compression}",
            )
        csv_el = child(inser, "CSV")
        json_el = child(inser, "JSON")
        if csv_el is not None:
            req.input_format = "CSV"
            fhi = (child_text(csv_el, "FileHeaderInfo") or "NONE").upper()
            if fhi not in ("NONE", "USE", "IGNORE"):
                raise SelectError(
                    "InvalidFileHeaderInfo", f"bad FileHeaderInfo {fhi}"
                )
            req.csv_args = csvio.CSVArgs(
                file_header_info=fhi,
                record_delimiter=child_text(csv_el, "RecordDelimiter")
                or "\n",
                field_delimiter=child_text(csv_el, "FieldDelimiter") or ",",
                quote_character=child_text(csv_el, "QuoteCharacter")
                or '"',
                quote_escape_character=child_text(
                    csv_el, "QuoteEscapeCharacter"
                )
                or '"',
                comments=child_text(csv_el, "Comments"),
            )
        elif json_el is not None:
            req.input_format = "JSON"
            jt = (child_text(json_el, "Type") or "LINES").upper()
            if jt not in ("LINES", "DOCUMENT"):
                raise SelectError("InvalidJsonType", f"bad Type {jt}")
            req.json_args = jsonio.JSONArgs(jt)
        elif child(inser, "Parquet") is not None:
            req.input_format = "PARQUET"
            if req.compression != "NONE":
                # parquet compression lives inside the pages, not
                # around the stream (select.go parquet branch)
                raise SelectError(
                    "InvalidRequestParameter",
                    "CompressionType must be NONE for Parquet",
                )
        else:
            raise SelectError(
                "InvalidDataSource", "CSV or JSON input required"
            )

        outser = child(root, "OutputSerialization")
        if outser is not None:
            ocsv = child(outser, "CSV")
            ojson = child(outser, "JSON")
            if ocsv is not None:
                req.output_format = "CSV"
                qf = (child_text(ocsv, "QuoteFields") or "ASNEEDED").upper()
                if qf not in ("ASNEEDED", "ALWAYS"):
                    raise SelectError(
                        "InvalidQuoteFields", f"bad QuoteFields {qf}"
                    )
                req.csv_writer_args = {
                    "record_delimiter": child_text(ocsv, "RecordDelimiter")
                    or "\n",
                    "field_delimiter": child_text(ocsv, "FieldDelimiter")
                    or ",",
                    "quote_character": child_text(ocsv, "QuoteCharacter")
                    or '"',
                    "quote_fields": qf,
                }
            elif ojson is not None:
                req.output_format = "JSON"
                req.json_writer_args = {
                    "record_delimiter": child_text(ojson, "RecordDelimiter")
                    or "\n",
                }
        if not req.output_format:
            # parquet is input-only; its records default to JSON out
            req.output_format = (
                "JSON"
                if req.input_format == "PARQUET"
                else req.input_format
            )
        prog = child(root, "RequestProgress")
        if prog is not None:
            req.progress = (
                child_text(prog, "Enabled").lower() == "true"
            )
        return req


class S3Select:
    """One select evaluation over an object byte stream."""

    def __init__(self, request: SelectRequest):
        self.req = request
        try:
            self.stmt = sql.parse(request.expression)
        except sql.SQLError as e:
            raise SelectError(e.code, str(e)) from None

    def _decompress(self, stream):
        if self.req.compression == "GZIP":
            return gzip.GzipFile(fileobj=stream, mode="rb")
        if self.req.compression == "BZIP2":
            return bz2.BZ2File(stream, mode="rb")
        return stream

    def _records(self, stream):
        if self.req.input_format == "CSV":
            return csvio.read_records(stream, self.req.csv_args)
        if self.req.input_format == "PARQUET":
            from . import parquetio

            return parquetio.read_records(stream)
        return jsonio.read_records(stream, self.req.json_args)

    def _writer(self):
        if self.req.output_format == "CSV":
            return csvio.CSVWriter(**self.req.csv_writer_args)
        return jsonio.JSONWriter(**self.req.json_writer_args)

    def device_capable(self) -> bool:
        """True when this statement could run on the device engine
        against a device-resident byte plane (the cache-tier scan
        source): CSV in, no decompression, mode allows, and both the
        host fast path and the screen compiler accept the shape."""
        from . import device

        return (
            device.select_mode() in ("auto", "device")
            and self.req.input_format == "CSV"
            and self.req.compression == "NONE"
            and vector.eligible(self.stmt, self.req)
            and device.device_eligible(self.stmt, self.req)
        )

    def evaluate(
        self, stream, scanned_bytes: int, emit, device_source=None
    ) -> None:
        """Run the query; ``emit(frame_bytes)`` receives EventStream
        frames ready for the wire.  ``scanned_bytes`` is the stored
        object size (BytesScanned in Stats)."""
        stmt = self.stmt
        writer = self._writer()
        returned = 0
        batch = bytearray()
        # SELECT * rows carry reader-internal aliases (_N shadows of
        # named CSV columns, dotted JSON child paths) that projected
        # records never have - clean them per input format
        if self.req.input_format == "CSV":
            clean = csvio.clean_raw_row
        elif self.req.input_format == "PARQUET":
            from . import parquetio

            clean = parquetio.clean_raw_row
        else:
            clean = jsonio.clean_raw_row

        def flush():
            nonlocal returned
            while batch:
                part = bytes(batch[:BATCH_BYTES])
                del batch[:BATCH_BYTES]
                emit(message.records_message(part))
                returned += len(part)

        def sink(payload: bytes):
            batch.extend(payload)
            if len(batch) >= BATCH_BYTES:
                flush()

        from . import device

        def _stream():
            # host engines read a byte stream; a device-resident plane
            # reaches them through the drain seam exactly once
            if stream is not None:
                return stream
            return io.BytesIO(device.drain_plane(*device_source))

        mode = device.select_mode()
        try:
            if mode != "row" and vector.json_eligible(stmt, self.req):
                # flat JSON-lines aggregates: regex column extraction
                # + the same mask algebra as the CSV columnar scan
                device.STATS.request("host")
                vector.FastJSONScan(stmt, self.req).run(
                    self._decompress(_stream())
                )
            elif mode != "row" and vector.eligible(stmt, self.req):
                if mode in ("auto", "device") and device.device_eligible(
                    stmt, self.req
                ):
                    # device pre-filter: conservative SWAR screen on
                    # the word planes, exact host re-filter of the
                    # candidate rows (s3select/device.py)
                    device.STATS.request("device")
                    scan = device.DeviceScan(
                        stmt, self.req, writer, clean, sink
                    )
                else:
                    # columnar scan: numpy masks instead of per-row
                    # eval, with exact row-engine fallback per chunk
                    device.STATS.request("host")
                    scan = vector.FastScan(
                        stmt, self.req, writer, clean, sink
                    )
                if device_source is not None and isinstance(
                    scan, device.DeviceScan
                ):
                    scan.run_device(*device_source)
                else:
                    scan.run(self._decompress(_stream()))
            else:
                device.STATS.request("row")
                records = self._records(self._decompress(_stream()))
                matched = 0
                for row in records:
                    if (
                        stmt.limit is not None
                        and not stmt.is_aggregate
                        and matched >= stmt.limit
                    ):
                        break
                    if not stmt.matches(row):
                        continue
                    if stmt.is_aggregate:
                        stmt.accumulate(row)
                        continue
                    out = stmt.project(row)
                    if stmt.projections is None:
                        out = clean(out)
                    batch.extend(writer.serialize(out))
                    if len(batch) >= BATCH_BYTES:
                        flush()
                    matched += 1
                    if stmt.limit is not None and matched >= stmt.limit:
                        break
            if stmt.is_aggregate:
                batch.extend(writer.serialize(stmt.aggregate_result()))
            flush()
        except sql.SQLError as e:
            raise SelectError(e.code, str(e)) from None
        except (OSError, EOFError) as e:
            raise SelectError(
                "InternalError", f"object read failed: {e}"
            ) from None
        if self.req.progress:
            emit(
                message.progress_message(
                    scanned_bytes, scanned_bytes, returned
                )
            )
        device.STATS.io(scanned_bytes, returned)
        emit(message.stats_message(scanned_bytes, scanned_bytes, returned))
        emit(message.end_message())


def run_select(body: bytes, data: bytes, emit) -> None:
    """Convenience: parse request, evaluate over in-memory bytes."""
    req = SelectRequest.from_xml(body)
    S3Select(req).evaluate(io.BytesIO(data), len(data), emit)
