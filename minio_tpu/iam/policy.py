"""IAM/bucket policy engine (pkg/iam/policy + pkg/bucket/policy).

Policy documents are the standard AWS JSON shape: Version + Statement
list, each statement carrying Effect / Action / Resource / Condition
(and Principal for bucket policies).  Evaluation follows the reference
(pkg/iam/policy/policy.go IsAllowed): an explicit Deny wins over any
Allow; no match is an implicit deny.

Identity policies (attached to users/groups) have no Principal; bucket
policies are resource policies whose statements name principals ("*"
for anonymous).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import ipaddress
import json

ARN_PREFIX = "arn:aws:s3:::"

# ---------------------------------------------------------------------------
# actions (pkg/iam/policy/action.go)
# ---------------------------------------------------------------------------

# bucket-scoped actions evaluate against arn:aws:s3:::bucket; the rest
# against arn:aws:s3:::bucket/object
BUCKET_ACTIONS = frozenset(
    {
        "s3:CreateBucket",
        "s3:DeleteBucket",
        "s3:GetBucketLocation",
        "s3:ListBucket",
        "s3:ListBucketVersions",
        "s3:ListBucketMultipartUploads",
        "s3:GetBucketPolicy",
        "s3:PutBucketPolicy",
        "s3:DeleteBucketPolicy",
        "s3:GetBucketVersioning",
        "s3:PutBucketVersioning",
        "s3:GetBucketTagging",
        "s3:PutBucketTagging",
        "s3:GetBucketNotification",
        "s3:PutBucketNotification",
        "s3:GetLifecycleConfiguration",
        "s3:PutLifecycleConfiguration",
        "s3:GetBucketObjectLockConfiguration",
        "s3:PutBucketObjectLockConfiguration",
        "s3:GetEncryptionConfiguration",
        "s3:PutEncryptionConfiguration",
        "s3:ListAllMyBuckets",
        "s3:ForceDeleteBucket",
    }
)

OBJECT_ACTIONS = frozenset(
    {
        "s3:GetObject",
        "s3:GetObjectVersion",
        "s3:PutObject",
        "s3:DeleteObject",
        "s3:DeleteObjectVersion",
        "s3:ListMultipartUploadParts",
        "s3:AbortMultipartUpload",
        "s3:GetObjectTagging",
        "s3:PutObjectTagging",
        "s3:DeleteObjectTagging",
        "s3:GetObjectRetention",
        "s3:PutObjectRetention",
        "s3:GetObjectLegalHold",
        "s3:PutObjectLegalHold",
        "s3:SelectObjectContent",
    }
)

ALL_ACTIONS = BUCKET_ACTIONS | OBJECT_ACTIONS


def wildcard_match(pattern: str, s: str) -> bool:
    """pkg/wildcard MatchSimple: '*' any sequence, '?' one char."""
    if pattern == "*":
        return True
    return fnmatch.fnmatchcase(s, pattern)


@dataclasses.dataclass
class Args:
    """Evaluation inputs (pkg/iam/policy/args.go Args)."""

    account: str = ""  # access key ("" = anonymous)
    action: str = ""
    bucket: str = ""
    object: str = ""
    is_owner: bool = False
    conditions: "dict[str, list[str]]" = dataclasses.field(
        default_factory=dict
    )

    @property
    def resource(self) -> str:
        if self.action in BUCKET_ACTIONS:
            return ARN_PREFIX + self.bucket
        return ARN_PREFIX + f"{self.bucket}/{self.object}"


class PolicyError(Exception):
    pass


# ---------------------------------------------------------------------------
# condition functions (pkg/policy/condition)
# ---------------------------------------------------------------------------


def _cond_values(args: Args, key: str) -> list[str]:
    # keys may be written aws:SourceIp / s3:prefix etc; context keys are
    # stored lower-cased without a prefix qualifier
    k = key.split(":", 1)[-1].lower()
    return args.conditions.get(k, [])


def _parse_cond_date(raw: str) -> "float | None":
    """ISO-8601 or epoch-seconds -> unix timestamp."""
    import datetime

    raw = raw.strip()
    if raw.isdigit():
        return float(raw)
    try:
        dt = datetime.datetime.fromisoformat(
            raw.replace("Z", "+00:00")
        )
    except ValueError:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt.timestamp()


def _num_cmp(base: str, g: str, values: "list[str]") -> bool:
    try:
        gv = float(g)
        vs = [float(v) for v in values]
    except ValueError:
        return False
    return any(
        {
            "NumericEquals": gv == v,
            "NumericNotEquals": gv != v,
            "NumericLessThan": gv < v,
            "NumericLessThanEquals": gv <= v,
            "NumericGreaterThan": gv > v,
            "NumericGreaterThanEquals": gv >= v,
        }[base]
        for v in vs
    )


def _date_cmp(base: str, g: str, values: "list[str]") -> bool:
    gv = _parse_cond_date(g)
    if gv is None:
        return False
    out = False
    for v in values:
        vv = _parse_cond_date(v)
        if vv is None:
            continue
        out = out or {
            "DateEquals": gv == vv,
            "DateNotEquals": gv != vv,
            "DateLessThan": gv < vv,
            "DateLessThanEquals": gv <= vv,
            "DateGreaterThan": gv > vv,
            "DateGreaterThanEquals": gv >= vv,
        }[base]
    return out


def _one_value_matches(base: str, g: str, values: "list[str]") -> bool:
    """Does ONE context value satisfy the operator against the policy
    value set?  (pkg/iam/policy condition function library.)"""
    if base in ("StringEquals", "StringLike"):
        like = base == "StringLike"
        return any(
            (wildcard_match(v, g) if like else v == g) for v in values
        )
    if base in ("StringEqualsIgnoreCase",):
        return any(v.lower() == g.lower() for v in values)
    if base.startswith("Numeric"):
        return _num_cmp(base, g, values)
    if base.startswith("Date"):
        return _date_cmp(base, g, values)
    if base == "Bool":
        return g.lower() in [v.lower() for v in values]
    if base == "IpAddress":
        try:
            addr = ipaddress.ip_address(g)
        except ValueError:
            return False
        for v in values:
            try:
                if addr in ipaddress.ip_network(v, strict=False):
                    return True
            except ValueError:
                continue
        return False
    raise KeyError(base)  # unreachable: _KNOWN_OPS gates callers


_NEGATED = {
    "StringNotEquals": "StringEquals",
    "StringNotLike": "StringLike",
    "StringNotEqualsIgnoreCase": "StringEqualsIgnoreCase",
    "NotIpAddress": "IpAddress",
    "NumericNotEquals": "NumericEquals",
    "DateNotEquals": "DateEquals",
}

# every operator _one_value_matches understands; checked up front so
# a typo'd operator NEVER matches, even under a vacuous ForAllValues
_KNOWN_OPS = frozenset(
    [
        "StringEquals",
        "StringLike",
        "StringEqualsIgnoreCase",
        "Bool",
        "IpAddress",
    ]
    + [
        f"Numeric{suffix}"
        for suffix in (
            "Equals", "LessThan", "LessThanEquals",
            "GreaterThan", "GreaterThanEquals",
        )
    ]
    + [
        f"Date{suffix}"
        for suffix in (
            "Equals", "LessThan", "LessThanEquals",
            "GreaterThan", "GreaterThanEquals",
        )
    ]
)


def _eval_condition(op: str, key: str, values: list[str], args: Args) -> bool:
    got = _cond_values(args, key)
    qualifier = ""
    base = op
    for q in ("ForAllValues:", "ForAnyValue:"):
        if op.startswith(q):
            qualifier, base = q[:-1], op[len(q):]
            break
    if base == "Null":
        want_absent = values and values[0].lower() == "true"
        return (not got) if want_absent else bool(got)
    neg = base in _NEGATED
    pos_base = _NEGATED.get(base, base)
    if pos_base not in _KNOWN_OPS:
        # unknown operator: no match (conservative deny for Allow
        # statements, no effect for Deny)
        return False

    def pred(g: str) -> bool:
        """Does ONE context value satisfy the (possibly negated)
        operator?  The qualifier quantifies over this predicate."""
        hit = _one_value_matches(pos_base, g, values)
        return (not hit) if neg else hit

    if qualifier == "ForAllValues":
        # vacuously true on an absent key (AWS set-operator semantics)
        return all(pred(g) for g in got)
    if qualifier == "ForAnyValue":
        return any(pred(g) for g in got)
    if neg:
        # default negated ops: true on an absent key, else EVERY
        # context value must satisfy the negation
        return all(pred(g) for g in got)
    return bool(got) and any(pred(g) for g in got)


# ---------------------------------------------------------------------------
# statements + policies
# ---------------------------------------------------------------------------


def _as_list(v) -> list:
    if v is None:
        return []
    if isinstance(v, str):
        return [v]
    return list(v)


@dataclasses.dataclass
class Statement:
    effect: str = "Allow"  # "Allow" | "Deny"
    actions: list = dataclasses.field(default_factory=list)
    resources: list = dataclasses.field(default_factory=list)
    conditions: dict = dataclasses.field(default_factory=dict)
    principals: "list | None" = None  # None = identity policy
    sid: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "Statement":
        effect = d.get("Effect", "")
        if effect not in ("Allow", "Deny"):
            raise PolicyError(f"invalid Effect {effect!r}")
        actions = _as_list(d.get("Action"))
        if not actions:
            raise PolicyError("statement missing Action")
        resources = _as_list(d.get("Resource"))
        principals = None
        if "Principal" in d:
            p = d["Principal"]
            if p == "*":
                principals = ["*"]
            elif isinstance(p, dict):
                principals = _as_list(p.get("AWS"))
            else:
                principals = _as_list(p)
        conditions = d.get("Condition", {}) or {}
        if not isinstance(conditions, dict):
            raise PolicyError("Condition must be an object")
        return cls(
            effect=effect,
            actions=actions,
            resources=resources,
            conditions=conditions,
            principals=principals,
            sid=d.get("Sid", ""),
        )

    def to_dict(self) -> dict:
        d: dict = {"Effect": self.effect, "Action": list(self.actions)}
        if self.sid:
            d["Sid"] = self.sid
        if self.principals is not None:
            d["Principal"] = {"AWS": list(self.principals)}
        if self.resources:
            d["Resource"] = list(self.resources)
        if self.conditions:
            d["Condition"] = self.conditions
        return d

    # -- evaluation -------------------------------------------------------

    def _match_action(self, action: str) -> bool:
        return any(wildcard_match(a, action) for a in self.actions)

    def _match_principal(self, account: str) -> bool:
        if self.principals is None:
            return True  # identity policy: principal implied
        who = account or "*"  # anonymous matches only "*"
        for p in self.principals:
            if p == "*" or p == who:
                return True
            # arn:aws:iam::<acct>:user/<name> form
            if p.rpartition("/")[2] == who:
                return True
        return False

    def _match_resource(self, resource: str) -> bool:
        if not self.resources:
            return True
        target = resource[len(ARN_PREFIX):] if resource.startswith(
            ARN_PREFIX
        ) else resource
        for r in self.resources:
            pat = r[len(ARN_PREFIX):] if r.startswith(ARN_PREFIX) else r
            if wildcard_match(pat, target):
                return True
        return False

    def _match_conditions(self, args: Args) -> bool:
        for op, kv in self.conditions.items():
            for key, values in kv.items():
                if not _eval_condition(op, key, _as_list(values), args):
                    return False
        return True

    def matches(self, args: Args) -> bool:
        return (
            self._match_action(args.action)
            and self._match_principal(args.account)
            and self._match_resource(args.resource)
            and self._match_conditions(args)
        )


@dataclasses.dataclass
class Policy:
    version: str = "2012-10-17"
    statements: list = dataclasses.field(default_factory=list)
    id: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "Policy":
        stmts = d.get("Statement")
        if stmts is None:
            raise PolicyError("policy missing Statement")
        return cls(
            version=d.get("Version", "2012-10-17"),
            statements=[Statement.from_dict(s) for s in _as_list(stmts)],
            id=d.get("Id", ""),
        )

    @classmethod
    def from_json(cls, raw: "str | bytes") -> "Policy":
        try:
            d = json.loads(raw)
        except (ValueError, TypeError):
            raise PolicyError("malformed policy JSON") from None
        if not isinstance(d, dict):
            raise PolicyError("policy must be a JSON object")
        return cls.from_dict(d)

    def to_dict(self) -> dict:
        d: dict = {
            "Version": self.version,
            "Statement": [s.to_dict() for s in self.statements],
        }
        if self.id:
            d["Id"] = self.id
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def is_allowed(self, args: Args) -> bool:
        """Deny overrides allow; default deny (policy.go IsAllowed)."""
        allowed = False
        for s in self.statements:
            if not s.matches(args):
                continue
            if s.effect == "Deny":
                return False
            allowed = True
        return allowed

    def validate_bucket(self, bucket: str) -> None:
        """Bucket policies must reference only their own bucket
        (PutBucketPolicyHandler validation)."""
        for s in self.statements:
            if s.principals is None:
                raise PolicyError("bucket policy requires Principal")
            for r in s.resources:
                pat = (
                    r[len(ARN_PREFIX):]
                    if r.startswith(ARN_PREFIX)
                    else r
                )
                b = pat.split("/", 1)[0]
                if not wildcard_match(b, bucket):
                    raise PolicyError(
                        f"resource {r!r} outside bucket {bucket!r}"
                    )


# ---------------------------------------------------------------------------
# canned policies (cmd/iam.go defaults)
# ---------------------------------------------------------------------------


def _canned(statements: list) -> Policy:
    return Policy(statements=[Statement.from_dict(s) for s in statements])


CANNED_POLICIES: "dict[str, Policy]" = {
    "readonly": _canned(
        [
            {
                "Effect": "Allow",
                "Action": ["s3:GetBucketLocation", "s3:GetObject"],
                "Resource": [ARN_PREFIX + "*"],
            }
        ]
    ),
    "readwrite": _canned(
        [
            {
                "Effect": "Allow",
                "Action": ["s3:*"],
                "Resource": [ARN_PREFIX + "*"],
            }
        ]
    ),
    "writeonly": _canned(
        [
            {
                "Effect": "Allow",
                "Action": ["s3:PutObject"],
                "Resource": [ARN_PREFIX + "*"],
            }
        ]
    ),
    "diagnostics": _canned(
        [
            {
                "Effect": "Allow",
                "Action": ["s3:ListAllMyBuckets"],
                "Resource": [ARN_PREFIX + "*"],
            }
        ]
    ),
}
