"""IAM: identity, named policies, and the policy evaluation engine
(cmd/iam.go + pkg/iam/policy)."""

from .policy import (  # noqa: F401
    ALL_ACTIONS,
    BUCKET_ACTIONS,
    CANNED_POLICIES,
    OBJECT_ACTIONS,
    Args,
    Policy,
    PolicyError,
    Statement,
)
from .sys import (  # noqa: F401
    IAMError,
    IAMSys,
    PolicyNotFound,
    UserNotFound,
    generate_credentials,
)
