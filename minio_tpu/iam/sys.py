"""IAMSys: users, service accounts, named policies + authorization
(cmd/iam.go:203 IAMSys, cmd/iam-object-store.go).

Identity documents persist as erasure-coded objects under the reserved
meta volume (``.sys/config/iam/...``, the .minio.sys analogue), so every
node sees the same IAM state through the object layer and a node restart
loads it back (iam.go:419 Init).  The in-memory maps are the serving
path; refresh() re-reads the store (the peer-invalidation stand-in until
a control plane exists).

Authorization: the root credential bypasses policy (owner); every other
account is evaluated against its attached named policy, with bucket
(resource) policies consulted for anonymous and cross-account access by
the caller (auth dispatch in server/http.py).
"""

from __future__ import annotations

import io
import json
import secrets as pysecrets
import threading

from ..objectlayer.api import META_BUCKET, ObjectNotFound
from .policy import CANNED_POLICIES, Args, Policy, PolicyError

IAM_PREFIX = "config/iam"


class IAMError(Exception):
    pass


class UserNotFound(IAMError):
    pass


class PolicyNotFound(IAMError):
    pass


def generate_credentials() -> "tuple[str, str]":
    """Access/secret key pair (pkg/auth GetNewCredentials shape)."""
    alphabet = "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
    ak = "".join(pysecrets.choice(alphabet) for _ in range(20))
    sk = pysecrets.token_urlsafe(30)[:40]
    return ak, sk


class IAMSys:
    """In-memory IAM maps + object-layer persistence."""

    def __init__(
        self,
        root_access_key: str,
        root_secret_key: str,
        object_layer=None,
    ):
        self.root_access_key = root_access_key
        self.root_secret_key = root_secret_key
        self._ol = object_layer
        self._mu = threading.RLock()
        # access_key -> {"secret": str, "policy": str, "status": str,
        #               "parent": str (service accounts)}
        self._users: "dict[str, dict]" = {}
        self._policies: "dict[str, Policy]" = dict(CANNED_POLICIES)
        # peer control plane: set in distributed mode so IAM edits
        # broadcast a reload to every node
        self.notifier = None
        if object_layer is not None:
            self.refresh()

    # -- persistence ------------------------------------------------------

    def _store_path(self, kind: str, name: str) -> str:
        return f"{IAM_PREFIX}/{kind}/{name}.json"

    def _save_doc(self, kind: str, name: str, doc: dict) -> None:
        if self._ol is None:
            return
        raw = json.dumps(doc).encode()
        self._ol.put_object(
            META_BUCKET,
            self._store_path(kind, name),
            io.BytesIO(raw),
            len(raw),
        )
        if self.notifier is not None:
            self.notifier.iam_changed()

    def _delete_doc(self, kind: str, name: str) -> None:
        if self._ol is None:
            return
        try:
            self._ol.delete_object(
                META_BUCKET, self._store_path(kind, name)
            )
        except ObjectNotFound:
            pass
        if self.notifier is not None:
            self.notifier.iam_changed()

    def _load_docs(self, kind: str) -> "dict[str, dict]":
        out: dict = {}
        if self._ol is None:
            return out
        prefix = f"{IAM_PREFIX}/{kind}/"
        marker = ""
        while True:
            res = self._ol.list_objects(
                META_BUCKET, prefix, marker, "", 1000
            )
            for obj in res.objects:
                name = obj.name[len(prefix):]
                if not name.endswith(".json"):
                    continue
                buf = io.BytesIO()
                try:
                    self._ol.get_object(META_BUCKET, obj.name, buf)
                    out[name[:-5]] = json.loads(buf.getvalue())
                except Exception:  # noqa: BLE001 - skip corrupt doc
                    continue
            if not res.is_truncated:
                return out
            marker = res.next_marker

    def start_refresher(self, interval_s: float = 120.0):
        """Periodic reload fallback (iam.go watch loop): peer
        notifications give immediate convergence; this catches any a
        down node missed.  Daemon thread; returns it."""
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.refresh()
                except Exception:  # noqa: BLE001
                    pass

        t = threading.Thread(target=loop, daemon=True, name="iam-refresh")
        t.stop = stop  # type: ignore[attr-defined]
        t.start()
        return t

    def refresh(self) -> None:
        """Reload users + policies from the store (iam.go Load)."""
        users = self._load_docs("users")
        policies = self._load_docs("policies")
        with self._mu:
            self._users = users
            self._policies = dict(CANNED_POLICIES)
            for name, doc in policies.items():
                try:
                    self._policies[name] = Policy.from_dict(doc)
                except PolicyError:
                    continue

    # -- credential lookup (SigV4Verifier seam) ---------------------------

    def lookup_secret(self, access_key: str) -> "str | None":
        if access_key == self.root_access_key:
            return self.root_secret_key
        with self._mu:
            u = self._users.get(access_key)
            if u is None or u.get("status") == "disabled":
                return None
            return u["secret"]

    def is_owner(self, access_key: str) -> bool:
        return access_key == self.root_access_key

    # -- user management (iam.go SetUser/DeleteUser/...) ------------------

    def add_user(
        self, access_key: str, secret_key: str, policy: str = ""
    ) -> None:
        if access_key == self.root_access_key:
            raise IAMError("cannot shadow the root credential")
        if policy:
            self.get_policy(policy)  # must exist
        doc = {"secret": secret_key, "policy": policy, "status": "enabled"}
        with self._mu:
            self._users[access_key] = doc
        self._save_doc("users", access_key, doc)

    def add_service_account(
        self, parent: str, access_key: str = "", secret_key: str = ""
    ) -> "tuple[str, str]":
        """Service account inheriting the parent user's policy
        (iam.go NewServiceAccount)."""
        if parent != self.root_access_key and parent not in self._users:
            raise UserNotFound(parent)
        if not access_key:
            access_key, secret_key = generate_credentials()
        doc = {
            "secret": secret_key,
            "policy": "",
            "status": "enabled",
            "parent": parent,
        }
        with self._mu:
            self._users[access_key] = doc
        self._save_doc("users", access_key, doc)
        return access_key, secret_key

    def remove_user(self, access_key: str) -> None:
        with self._mu:
            if access_key not in self._users:
                raise UserNotFound(access_key)
            del self._users[access_key]
            # drop the user's service accounts too
            orphans = [
                ak
                for ak, u in self._users.items()
                if u.get("parent") == access_key
            ]
            for ak in orphans:
                del self._users[ak]
        self._delete_doc("users", access_key)
        for ak in orphans:
            self._delete_doc("users", ak)

    def set_user_status(self, access_key: str, enabled: bool) -> None:
        with self._mu:
            u = self._users.get(access_key)
            if u is None:
                raise UserNotFound(access_key)
            u["status"] = "enabled" if enabled else "disabled"
            doc = dict(u)
        self._save_doc("users", access_key, doc)

    def set_user_policy(self, access_key: str, policy: str) -> None:
        if policy:
            self.get_policy(policy)
        with self._mu:
            u = self._users.get(access_key)
            if u is None:
                raise UserNotFound(access_key)
            u["policy"] = policy
            doc = dict(u)
        self._save_doc("users", access_key, doc)

    def list_users(self) -> "dict[str, dict]":
        with self._mu:
            return {
                ak: {"policy": u.get("policy", ""), "status": u.get("status")}
                for ak, u in self._users.items()
                if not u.get("parent")
            }

    # -- policy management ------------------------------------------------

    def set_policy(self, name: str, policy: Policy) -> None:
        with self._mu:
            self._policies[name] = policy
        self._save_doc("policies", name, policy.to_dict())

    def get_policy(self, name: str) -> Policy:
        with self._mu:
            p = self._policies.get(name)
        if p is None:
            raise PolicyNotFound(name)
        return p

    def remove_policy(self, name: str) -> None:
        with self._mu:
            if name not in self._policies:
                raise PolicyNotFound(name)
            del self._policies[name]
        if name not in CANNED_POLICIES:
            self._delete_doc("policies", name)

    def list_policies(self) -> list[str]:
        with self._mu:
            return sorted(self._policies)

    # -- authorization (iam.go IsAllowed) ---------------------------------

    def is_allowed(self, args: Args) -> bool:
        """Identity-policy decision for an authenticated account."""
        if self.is_owner(args.account):
            return True
        with self._mu:
            u = self._users.get(args.account)
            if u is None or u.get("status") == "disabled":
                return False
            # service accounts inherit the parent's policy
            parent = u.get("parent")
            if parent:
                if self.is_owner(parent):
                    return True
                u = self._users.get(parent)
                if u is None or u.get("status") == "disabled":
                    return False
            pname = u.get("policy", "")
            policy = self._policies.get(pname) if pname else None
        if policy is None:
            return False
        return policy.is_allowed(args)
