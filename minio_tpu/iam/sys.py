"""IAMSys: users, service accounts, named policies + authorization
(cmd/iam.go:203 IAMSys, cmd/iam-object-store.go).

Identity documents persist as erasure-coded objects under the reserved
meta volume (``.sys/config/iam/...``, the .minio.sys analogue), so every
node sees the same IAM state through the object layer and a node restart
loads it back (iam.go:419 Init).  The in-memory maps are the serving
path; refresh() re-reads the store (the peer-invalidation stand-in until
a control plane exists).

Authorization: the root credential bypasses policy (owner); every other
account is evaluated against its attached named policy, with bucket
(resource) policies consulted for anonymous and cross-account access by
the caller (auth dispatch in server/http.py).
"""

from __future__ import annotations

import io
import json
import secrets as pysecrets
import threading
import time

from ..objectlayer.api import META_BUCKET, ObjectNotFound
from .policy import CANNED_POLICIES, Args, Policy, PolicyError

from ..utils.log import kv, logger

_log = logger("iam")

IAM_PREFIX = "config/iam"

# STS AssumeRole duration bounds (sts-handlers.go parseDurationSeconds)
STS_MIN_DURATION_S = 900
STS_MAX_DURATION_S = 7 * 24 * 3600
STS_DEFAULT_DURATION_S = 3600


class IAMError(Exception):
    pass


class UserNotFound(IAMError):
    pass


class PolicyNotFound(IAMError):
    pass


class GroupNotFound(IAMError):
    pass


class InvalidToken(IAMError):
    """Temp-credential session token missing/mismatched/expired."""


def generate_credentials() -> "tuple[str, str]":
    """Access/secret key pair (pkg/auth GetNewCredentials shape)."""
    alphabet = "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
    ak = "".join(pysecrets.choice(alphabet) for _ in range(20))
    sk = pysecrets.token_urlsafe(30)[:40]
    return ak, sk


class IAMSys:
    """In-memory IAM maps + object-layer persistence."""

    def __init__(
        self,
        root_access_key: str,
        root_secret_key: str,
        object_layer=None,
    ):
        self.root_access_key = root_access_key
        self.root_secret_key = root_secret_key
        self._ol = object_layer
        self._mu = threading.RLock()
        # access_key -> {"secret": str, "policy": str, "status": str,
        #               "parent": str (service accounts),
        #               "sts": bool, "expiration": unix ts,
        #               "session_token": str, "session_policy": json}
        self._users: "dict[str, dict]" = {}
        self._policies: "dict[str, Policy]" = dict(CANNED_POLICIES)
        # group name -> {"members": [ak...], "policy": str, "status": str}
        self._groups: "dict[str, dict]" = {}
        # peer control plane: set in distributed mode so IAM edits
        # broadcast a reload to every node
        self.notifier = None
        if object_layer is not None:
            self.refresh()

    # -- persistence ------------------------------------------------------

    def _store_path(self, kind: str, name: str) -> str:
        return f"{IAM_PREFIX}/{kind}/{name}.json"

    def _save_doc(self, kind: str, name: str, doc: dict) -> None:
        if self._ol is None:
            return
        raw = json.dumps(doc).encode()
        self._ol.put_object(
            META_BUCKET,
            self._store_path(kind, name),
            io.BytesIO(raw),
            len(raw),
        )
        self._notify_peers(kind, name, deleted=False)

    def _delete_doc(self, kind: str, name: str) -> None:
        if self._ol is None:
            return
        try:
            self._ol.delete_object(
                META_BUCKET, self._store_path(kind, name)
            )
        except ObjectNotFound:
            pass
        self._notify_peers(kind, name, deleted=True)

    def _notify_peers(
        self, kind: str, name: str, deleted: bool
    ) -> None:
        if self.notifier is None:
            return
        # granular invalidation when the notifier supports it (one
        # entity reload on each peer); coarse full-reload otherwise
        entity = getattr(self.notifier, "iam_entity", None)
        if entity is not None:
            entity(kind, name, deleted=deleted)
        else:
            self.notifier.iam_changed()

    def _load_docs(self, kind: str) -> "dict[str, dict]":
        out: dict = {}
        if self._ol is None:
            return out
        prefix = f"{IAM_PREFIX}/{kind}/"
        marker = ""
        while True:
            res = self._ol.list_objects(
                META_BUCKET, prefix, marker, "", 1000
            )
            for obj in res.objects:
                name = obj.name[len(prefix):]
                if not name.endswith(".json"):
                    continue
                buf = io.BytesIO()
                try:
                    self._ol.get_object(META_BUCKET, obj.name, buf)
                    out[name[:-5]] = json.loads(buf.getvalue())
                except Exception:  # noqa: BLE001 - skip corrupt doc
                    continue
            if not res.is_truncated:
                return out
            marker = res.next_marker

    # sentinel distinguishing "the doc does not exist" (evict the
    # cached entity) from a transient read failure (KEEP the cached
    # entity - evicting a valid credential on a quorum blip would
    # lock a live user out until the periodic refresher runs)
    _ABSENT = object()

    def _load_one_doc(self, kind: str, name: str):
        """The doc dict, ``_ABSENT`` when it does not exist (or is
        corrupt), or None on a transient read failure."""
        buf = io.BytesIO()
        try:
            self._ol.get_object(
                META_BUCKET, self._store_path(kind, name), buf
            )
        except ObjectNotFound:
            return self._ABSENT
        except Exception:  # noqa: BLE001 - quorum blip etc.
            return None
        try:
            return json.loads(buf.getvalue())
        except ValueError:
            return self._ABSENT

    # -- granular peer invalidation (LoadUser/LoadPolicy/LoadGroup
    #    peer RPCs reload ONE entity instead of the whole store) ----------

    def load_user(self, access_key: str) -> bool:
        """Reload one user / service account / STS credential from its
        persisted doc; drops it only when the doc is truly gone."""
        if self._ol is None or not access_key:
            return False
        doc = self._load_one_doc("users", access_key)
        if doc is self._ABSENT:
            sts = self._load_one_doc("sts", access_key)
            if sts is None:
                return False  # transient failure: keep the cache
            if isinstance(sts, dict) and sts.get(
                "expiration", 0
            ) > time.time():
                doc = sts
        if doc is None:
            return False  # transient failure: keep the cache
        with self._mu:
            if doc is self._ABSENT:
                self._users.pop(access_key, None)
                return False
            self._users[access_key] = doc
        return True

    def drop_user(self, access_key: str) -> None:
        with self._mu:
            self._users.pop(access_key, None)

    def load_policy(self, name: str) -> bool:
        if self._ol is None or not name:
            return False
        doc = self._load_one_doc("policies", name)
        if doc is None:
            return False  # transient failure: keep the cache
        with self._mu:
            if doc is self._ABSENT:
                self._policies.pop(name, None)
                if name in CANNED_POLICIES:
                    self._policies[name] = CANNED_POLICIES[name]
                return False
            try:
                self._policies[name] = Policy.from_dict(doc)
            except PolicyError:
                return False
        return True

    def drop_policy(self, name: str) -> None:
        with self._mu:
            self._policies.pop(name, None)
            if name in CANNED_POLICIES:
                self._policies[name] = CANNED_POLICIES[name]

    def load_group(self, name: str) -> bool:
        if self._ol is None or not name:
            return False
        doc = self._load_one_doc("groups", name)
        if doc is None:
            return False  # transient failure: keep the cache
        with self._mu:
            if doc is self._ABSENT:
                self._groups.pop(name, None)
                return False
            self._groups[name] = doc
        return True

    def start_refresher(self, interval_s: float = 120.0):
        """Periodic reload fallback (iam.go watch loop): peer
        notifications give immediate convergence; this catches any a
        down node missed.  Daemon thread; returns it."""
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.refresh()
                except Exception as exc:
                    _log.warning("iam refresh failed", extra=kv(err=str(exc)))

        t = threading.Thread(target=loop, daemon=True, name="iam-refresh")
        t.stop = stop  # type: ignore[attr-defined]
        t.start()
        return t

    def refresh(self) -> None:
        """Reload users + groups + policies from the store (iam.go Load)."""
        if self._ol is None:
            # store-less IAM: in-memory maps ARE the source of truth;
            # "reloading" would wipe them
            return
        users = self._load_docs("users")
        policies = self._load_docs("policies")
        groups = self._load_docs("groups")
        now = time.time()
        # temp credentials persist under their own kind with a TTL
        # (iam-object-store stores STS creds so every node honors them)
        for ak, u in self._load_docs("sts").items():
            if u.get("expiration", 0) > now:
                users.setdefault(ak, u)
        with self._mu:
            # keep unexpired in-memory temp creds unconditionally: a
            # concurrent assume_role may have inserted one after this
            # refresh snapshotted the sts/ docs (and store-less IAM has
            # no docs at all) - dropping it would orphan a live token
            for ak, u in self._users.items():
                if u.get("sts") and u.get("expiration", 0) > now:
                    users.setdefault(ak, u)
            self._users = users
            self._groups = groups
            self._policies = dict(CANNED_POLICIES)
            for name, doc in policies.items():
                try:
                    self._policies[name] = Policy.from_dict(doc)
                except PolicyError:
                    continue

    # -- credential lookup (SigV4Verifier seam) ---------------------------

    def lookup_secret(self, access_key: str) -> "str | None":
        if access_key == self.root_access_key:
            return self.root_secret_key
        with self._mu:
            u = self._users.get(access_key)
            if u is None or u.get("status") == "disabled":
                return None
            if u.get("sts") and u.get("expiration", 0) <= time.time():
                return None  # expired temp credential
            return u["secret"]

    def validate_session_token(
        self, access_key: str, token: "str | None"
    ) -> None:
        """Temp credentials must present their session token on every
        request (x-amz-security-token); long-lived credentials must
        not carry a foreign token (checkClaimsFromToken)."""
        with self._mu:
            u = self._users.get(access_key)
        if u is None or not u.get("sts"):
            if token:
                raise InvalidToken(
                    "security token used with a non-temporary credential"
                )
            return
        if u.get("expiration", 0) <= time.time():
            raise InvalidToken("temporary credential expired")
        if not token or token != u.get("session_token"):
            raise InvalidToken("security token mismatch")

    def is_owner(self, access_key: str) -> bool:
        return access_key == self.root_access_key

    def is_temp_credential(self, access_key: str) -> bool:
        """Whether the key is an STS temporary credential (those are
        refused console login: their session rides the S3 plane with
        its own token, web-handlers.go authenticateWeb)."""
        with self._mu:
            u = self._users.get(access_key)
        return bool(u and u.get("sts"))

    # -- user management (iam.go SetUser/DeleteUser/...) ------------------

    def add_user(
        self, access_key: str, secret_key: str, policy: str = ""
    ) -> None:
        if access_key == self.root_access_key:
            raise IAMError("cannot shadow the root credential")
        if policy:
            self.get_policy(policy)  # must exist
        doc = {"secret": secret_key, "policy": policy, "status": "enabled"}
        with self._mu:
            self._users[access_key] = doc
        self._save_doc("users", access_key, doc)

    def add_service_account(
        self, parent: str, access_key: str = "", secret_key: str = ""
    ) -> "tuple[str, str]":
        """Service account inheriting the parent user's policy
        (iam.go NewServiceAccount)."""
        if parent != self.root_access_key and parent not in self._users:
            raise UserNotFound(parent)
        if not access_key:
            access_key, secret_key = generate_credentials()
        doc = {
            "secret": secret_key,
            "policy": "",
            "status": "enabled",
            "parent": parent,
        }
        with self._mu:
            self._users[access_key] = doc
        self._save_doc("users", access_key, doc)
        return access_key, secret_key

    def remove_user(self, access_key: str) -> None:
        with self._mu:
            if access_key not in self._users:
                raise UserNotFound(access_key)
            del self._users[access_key]
            # drop the user's service accounts too
            orphans = [
                ak
                for ak, u in self._users.items()
                if u.get("parent") == access_key
            ]
            for ak in orphans:
                del self._users[ak]
        self._delete_doc("users", access_key)
        for ak in orphans:
            self._delete_doc("users", ak)

    def set_user_status(self, access_key: str, enabled: bool) -> None:
        with self._mu:
            u = self._users.get(access_key)
            if u is None:
                raise UserNotFound(access_key)
            u["status"] = "enabled" if enabled else "disabled"
            doc = dict(u)
        self._save_doc("users", access_key, doc)

    def set_user_secret(self, access_key: str, secret_key: str) -> None:
        """Rotate a user's secret key in place (the console SetAuth
        path, web-handlers.go:850); policy/status are untouched."""
        with self._mu:
            u = self._users.get(access_key)
            if u is None:
                raise UserNotFound(access_key)
            u["secret"] = secret_key
            doc = dict(u)
        self._save_doc("users", access_key, doc)

    def set_user_policy(self, access_key: str, policy: str) -> None:
        if policy:
            self.get_policy(policy)
        with self._mu:
            u = self._users.get(access_key)
            if u is None:
                raise UserNotFound(access_key)
            u["policy"] = policy
            doc = dict(u)
        self._save_doc("users", access_key, doc)

    def list_users(self) -> "dict[str, dict]":
        with self._mu:
            return {
                ak: {"policy": u.get("policy", ""), "status": u.get("status")}
                for ak, u in self._users.items()
                if not u.get("parent") and not u.get("sts")
            }

    # -- STS temp credentials (cmd/sts-handlers.go AssumeRole) ------------

    def assume_role(
        self,
        caller: str,
        duration_s: "int | None" = None,
        session_policy: "str | None" = None,
    ) -> dict:
        """Issue temp credentials bound to the caller's permissions.

        The effective policy of the temp credential is the caller's
        policy intersected with the optional session policy (both must
        allow).  Returns the credential document incl. the session
        token and expiration (unix seconds).
        """
        if duration_s is None:
            duration_s = STS_DEFAULT_DURATION_S
        if not (STS_MIN_DURATION_S <= duration_s <= STS_MAX_DURATION_S):
            raise IAMError(
                f"DurationSeconds {duration_s} out of range "
                f"[{STS_MIN_DURATION_S}, {STS_MAX_DURATION_S}]"
            )
        if session_policy:
            try:
                Policy.from_json(session_policy)
            except PolicyError as e:
                raise IAMError(f"bad session policy: {e}") from None
        with self._mu:
            if caller != self.root_access_key:
                u = self._users.get(caller)
                if u is None or u.get("status") == "disabled":
                    raise UserNotFound(caller)
                if u.get("sts"):
                    raise IAMError(
                        "temporary credentials cannot assume roles"
                    )
                if u.get("parent"):
                    # the reference refuses AssumeRole for service
                    # accounts (sts-handlers.go IsServiceAccount check)
                    raise IAMError(
                        "service accounts cannot assume roles"
                    )
        ak, sk = generate_credentials()
        token = pysecrets.token_urlsafe(48)
        doc = {
            "secret": sk,
            "policy": "",
            "status": "enabled",
            "parent": caller,
            "sts": True,
            "expiration": time.time() + duration_s,
            "session_token": token,
            "session_policy": session_policy or "",
        }
        with self._mu:
            self._users[ak] = doc
        self._save_doc("sts", ak, doc)
        return {"access_key": ak, **doc}

    def assume_role_with_token(
        self,
        policy: str,
        duration_s: "int | None" = None,
        subject: str = "",
    ) -> dict:
        """Temp credential for a federated identity: carries its OWN
        policy attachment instead of a parent user (the OpenID STS
        path, sts-handlers.go:293-443).  Every named policy must
        exist; multiple arrive comma-joined and any allow wins."""
        if duration_s is None:
            duration_s = STS_DEFAULT_DURATION_S
        if not (STS_MIN_DURATION_S <= duration_s <= STS_MAX_DURATION_S):
            raise IAMError(
                f"DurationSeconds {duration_s} out of range "
                f"[{STS_MIN_DURATION_S}, {STS_MAX_DURATION_S}]"
            )
        if not policy:
            raise IAMError("federated credential needs a policy claim")
        for name in policy.split(","):
            self.get_policy(name)  # must exist (PolicyNotFound)
        ak, sk = generate_credentials()
        token = pysecrets.token_urlsafe(48)
        doc = {
            "secret": sk,
            "policy": policy,
            "status": "enabled",
            "parent": "",
            "sts": True,
            "expiration": time.time() + duration_s,
            "session_token": token,
            "session_policy": "",
            "oidc_sub": subject,
        }
        with self._mu:
            self._users[ak] = doc
        self._save_doc("sts", ak, doc)
        return {"access_key": ak, **doc}

    def purge_expired_sts(self) -> int:
        """Drop expired temp credentials (lazy GC; returns count)."""
        now = time.time()
        with self._mu:
            dead = [
                ak
                for ak, u in self._users.items()
                if u.get("sts") and u.get("expiration", 0) <= now
            ]
            for ak in dead:
                del self._users[ak]
        for ak in dead:
            self._delete_doc("sts", ak)
        return len(dead)

    # -- groups (iam.go AddUsersToGroup / SetGroupStatus / ...) -----------

    def add_group_members(
        self, group: str, members: "list[str]"
    ) -> None:
        """Create the group if needed and add members (AddUsersToGroup)."""
        with self._mu:
            for ak in members:
                if ak not in self._users:
                    raise UserNotFound(ak)
            g = self._groups.setdefault(
                group, {"members": [], "policy": "", "status": "enabled"}
            )
            for ak in members:
                if ak not in g["members"]:
                    g["members"].append(ak)
            doc = dict(g)
        self._save_doc("groups", group, doc)

    def remove_group_members(
        self, group: str, members: "list[str]"
    ) -> None:
        """Remove members; an emptied member list with no members arg
        deletes the group (RemoveUsersFromGroup semantics)."""
        with self._mu:
            g = self._groups.get(group)
            if g is None:
                raise GroupNotFound(group)
            if not members:
                if g["members"]:
                    raise IAMError("group not empty")
                del self._groups[group]
                doc = None
            else:
                g["members"] = [
                    ak for ak in g["members"] if ak not in members
                ]
                doc = dict(g)
        if doc is None:
            self._delete_doc("groups", group)
        else:
            self._save_doc("groups", group, doc)

    def set_group_policy(self, group: str, policy: str) -> None:
        if policy:
            self.get_policy(policy)
        with self._mu:
            g = self._groups.get(group)
            if g is None:
                raise GroupNotFound(group)
            g["policy"] = policy
            doc = dict(g)
        self._save_doc("groups", group, doc)

    def set_group_status(self, group: str, enabled: bool) -> None:
        with self._mu:
            g = self._groups.get(group)
            if g is None:
                raise GroupNotFound(group)
            g["status"] = "enabled" if enabled else "disabled"
            doc = dict(g)
        self._save_doc("groups", group, doc)

    def group_info(self, group: str) -> dict:
        with self._mu:
            g = self._groups.get(group)
            if g is None:
                raise GroupNotFound(group)
            return dict(g)

    def list_groups(self) -> "list[str]":
        with self._mu:
            return sorted(self._groups)

    # -- policy management ------------------------------------------------

    def set_policy(self, name: str, policy: Policy) -> None:
        with self._mu:
            self._policies[name] = policy
        self._save_doc("policies", name, policy.to_dict())

    def get_policy(self, name: str) -> Policy:
        with self._mu:
            p = self._policies.get(name)
        if p is None:
            raise PolicyNotFound(name)
        return p

    def remove_policy(self, name: str) -> None:
        with self._mu:
            if name not in self._policies:
                raise PolicyNotFound(name)
            del self._policies[name]
        if name not in CANNED_POLICIES:
            self._delete_doc("policies", name)

    def list_policies(self) -> list[str]:
        with self._mu:
            return sorted(self._policies)

    # -- authorization (iam.go IsAllowed) ---------------------------------

    def _base_allowed(self, account: str, args: Args) -> bool:
        """Combined identity decision: the account's attached policy OR
        any enabled group's policy (iam.go policyDBGet aggregates user +
        group policies; any allow wins)."""
        with self._mu:
            u = self._users.get(account)
            if u is None or u.get("status") == "disabled":
                return False
            pnames = []
            if u.get("policy"):
                # federated creds may carry several comma-joined names
                pnames.extend(
                    p for p in u["policy"].split(",") if p
                )
            for g in self._groups.values():
                if (
                    account in g.get("members", ())
                    and g.get("status") != "disabled"
                    and g.get("policy")
                ):
                    pnames.append(g["policy"])
            policies = [
                self._policies[p] for p in pnames if p in self._policies
            ]
        return any(p.is_allowed(args) for p in policies)

    def is_allowed(self, args: Args) -> bool:
        """Identity-policy decision for an authenticated account."""
        if self.is_owner(args.account):
            return True
        with self._mu:
            u = self._users.get(args.account)
        if u is None or u.get("status") == "disabled":
            return False
        if u.get("sts"):
            if u.get("expiration", 0) <= time.time():
                return False
            # temp creds: parent's permissions INTERSECTED with the
            # session policy (both must allow; sts-handlers.go claims)
            sp = u.get("session_policy", "")
            if sp:
                try:
                    if not Policy.from_json(sp).is_allowed(args):
                        return False
                except PolicyError:
                    return False
            parent = u.get("parent", "")
            if parent:
                if self.is_owner(parent):
                    return True
                return self._base_allowed(parent, args)
            # parentless federated credential (OpenID STS): its own
            # attached policy IS the whole identity
            return self._base_allowed(args.account, args)
        # service accounts inherit the parent's effective policy
        parent = u.get("parent")
        if parent:
            if self.is_owner(parent):
                return True
            return self._base_allowed(parent, args)
        return self._base_allowed(args.account, args)
