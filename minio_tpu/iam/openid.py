"""OpenID Connect token validation for the STS federation variants
(cmd/sts-handlers.go:293-443 AssumeRoleWithWebIdentity/ClientGrants;
pkg/iam/openid/jwt.go validator).

The validator fetches the provider's discovery document, caches its
JWKS, and verifies RS256 ID tokens with a pure-Python PKCS#1 v1.5
check (modular exponentiation + EMSA-PKCS1-v1_5 comparison) - no
external crypto dependency, same wire behavior as the reference's
coreos/go-oidc verification: signature, exp/nbf, issuer, audience.

Config (env or KV config, like the reference's identity_openid
subsystem):
  MINIO_TPU_IDENTITY_OPENID_CONFIG_URL  discovery document URL
  MINIO_TPU_IDENTITY_OPENID_CLIENT_ID   expected audience (optional)
  MINIO_TPU_IDENTITY_OPENID_CLAIM_NAME  policy claim (default "policy")
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import threading
import time
import urllib.request

# SHA-256 DigestInfo prefix (RFC 8017 EMSA-PKCS1-v1_5 encoding)
_SHA256_PREFIX = bytes.fromhex(
    "3031300d060960864801650304020105000420"
)

ENV_CONFIG_URL = "MINIO_TPU_IDENTITY_OPENID_CONFIG_URL"
ENV_CLIENT_ID = "MINIO_TPU_IDENTITY_OPENID_CLIENT_ID"
ENV_CLAIM_NAME = "MINIO_TPU_IDENTITY_OPENID_CLAIM_NAME"

DEFAULT_CLAIM = "policy"
_JWKS_TTL_S = 300.0


class OpenIDError(Exception):
    pass


def _b64u(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    try:
        return base64.urlsafe_b64decode(data + pad)
    except (ValueError, TypeError) as e:
        raise OpenIDError(f"bad base64url: {e}") from None


def _b64u_int(data: str) -> int:
    return int.from_bytes(_b64u(data), "big")


def rsa_verify_sha256(n: int, e: int, msg: bytes, sig: bytes) -> bool:
    """RSASSA-PKCS1-v1_5 with SHA-256, from first principles: one
    modular exponentiation and a constant-time padding comparison."""
    k = (n.bit_length() + 7) // 8
    if len(sig) != k:
        return False
    s = int.from_bytes(sig, "big")
    if s >= n:
        return False
    em = pow(s, e, n).to_bytes(k, "big")
    ps_len = k - 3 - len(_SHA256_PREFIX) - 32
    if ps_len < 8:
        return False
    expected = (
        b"\x00\x01"
        + b"\xff" * ps_len
        + b"\x00"
        + _SHA256_PREFIX
        + hashlib.sha256(msg).digest()
    )
    return hmac.compare_digest(em, expected)


class OpenIDValidator:
    """Validates ID tokens from one OIDC provider."""

    def __init__(
        self,
        config_url: str,
        client_id: str = "",
        claim_name: str = DEFAULT_CLAIM,
        fetch=None,
    ):
        self.config_url = config_url
        self.client_id = client_id
        self.claim_name = claim_name or DEFAULT_CLAIM
        self._fetch = fetch or self._http_get
        self._mu = threading.Lock()
        self._issuer = ""
        self._keys: "dict[str, tuple[int, int]]" = {}
        self._keys_ts = 0.0

    @staticmethod
    def _http_get(url: str) -> dict:
        with urllib.request.urlopen(url, timeout=10) as r:
            return json.loads(r.read())

    def _refresh_keys(self, force: bool = False) -> None:
        with self._mu:
            if (
                not force
                and self._keys
                and time.monotonic() - self._keys_ts < _JWKS_TTL_S
            ):
                return
            try:
                disc = self._fetch(self.config_url)
                jwks = self._fetch(disc["jwks_uri"])
            except (OSError, KeyError, ValueError) as e:
                raise OpenIDError(
                    f"OpenID discovery failed: {e}"
                ) from None
            self._issuer = disc.get("issuer", "")
            keys = {}
            for k in jwks.get("keys", []):
                if k.get("kty") != "RSA":
                    continue
                try:
                    keys[k.get("kid", "")] = (
                        _b64u_int(k["n"]),
                        _b64u_int(k["e"]),
                    )
                except (KeyError, OpenIDError):
                    continue
            if not keys:
                raise OpenIDError("provider JWKS has no RSA keys")
            self._keys = keys
            self._keys_ts = time.monotonic()

    def _key_for(self, kid: str) -> "tuple[int, int]":
        self._refresh_keys()
        with self._mu:
            key = self._keys.get(kid)
        if key is None:
            # unknown kid: the provider may have rotated - refetch once
            self._refresh_keys(force=True)
            with self._mu:
                key = self._keys.get(kid)
                if key is None and len(self._keys) == 1:
                    # tokens commonly omit kid when one key exists
                    key = next(iter(self._keys.values()))
        if key is None:
            raise OpenIDError(f"no JWKS key for kid {kid!r}")
        return key

    def validate(self, token: str) -> dict:
        """Claims of a valid token; raises OpenIDError otherwise."""
        parts = token.split(".")
        if len(parts) != 3:
            raise OpenIDError("token is not a JWS")
        try:
            header = json.loads(_b64u(parts[0]))
            claims = json.loads(_b64u(parts[1]))
        except ValueError as e:
            raise OpenIDError(f"bad token JSON: {e}") from None
        if not isinstance(header, dict) or not isinstance(
            claims, dict
        ):
            raise OpenIDError("token segments are not JSON objects")
        if header.get("alg") != "RS256":
            raise OpenIDError(
                f"algorithm {header.get('alg')!r} not allowed"
            )
        n, e = self._key_for(header.get("kid", ""))
        signing_input = f"{parts[0]}.{parts[1]}".encode()
        if not rsa_verify_sha256(
            n, e, signing_input, _b64u(parts[2])
        ):
            raise OpenIDError("signature verification failed")
        now = time.time()
        exp = claims.get("exp")
        if not isinstance(exp, (int, float)) or exp <= now:
            raise OpenIDError("token expired")
        nbf = claims.get("nbf")
        if isinstance(nbf, (int, float)) and nbf > now + 60:
            raise OpenIDError("token not yet valid")
        if self._issuer and claims.get("iss") != self._issuer:
            raise OpenIDError("issuer mismatch")
        if self.client_id:
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self.client_id not in auds and claims.get(
                "azp"
            ) != self.client_id:
                raise OpenIDError("audience mismatch")
        return claims

    def policy_claim(self, claims: dict) -> str:
        """The policy name(s) carried in the configured claim
        (pkg/iam/openid GetDefaultExpClaims policy extraction).
        Multiple policies arrive comma-separated or as a list; the
        normalized comma-joined form is stored on the credential."""
        v = claims.get(self.claim_name)
        if v is None:
            raise OpenIDError(
                f"token carries no {self.claim_name!r} claim"
            )
        if isinstance(v, (list, tuple)):
            names = [str(x).strip() for x in v if str(x).strip()]
        else:
            names = [s.strip() for s in str(v).split(",") if s.strip()]
        if not names:
            raise OpenIDError(f"empty {self.claim_name!r} claim")
        return ",".join(names)


_validator: "OpenIDValidator | None" = None
_validator_url = ""


def get_validator() -> "OpenIDValidator | None":
    """Process validator from env config; None when unconfigured."""
    global _validator, _validator_url
    url = os.environ.get(ENV_CONFIG_URL, "")
    if not url:
        _validator = None
        _validator_url = ""
        return None
    if _validator is None or _validator_url != url:
        _validator = OpenIDValidator(
            url,
            client_id=os.environ.get(ENV_CLIENT_ID, ""),
            claim_name=os.environ.get(ENV_CLAIM_NAME, DEFAULT_CLAIM),
        )
        _validator_url = url
    return _validator


def reset_validator_cache() -> None:
    """Testing aid: drop the cached validator (env changed)."""
    global _validator, _validator_url
    _validator = None
    _validator_url = ""
