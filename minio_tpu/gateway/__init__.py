"""Gateway mode: serve the S3 API over non-erasure backends
(cmd/gateway/).

Two gateways, matching the reference's production pair:

- **nas** - a shared filesystem served with the full S3 front
  (cmd/gateway/nas/): rides :class:`minio_tpu.objectlayer.fs.FSObjects`.
- **s3** - proxy to an upstream S3-compatible store
  (cmd/gateway/s3/): :class:`minio_tpu.gateway.s3.S3Objects`
  implements the ObjectLayer over SigV4 HTTP calls.

The azure/gcs/hdfs gateways of the reference need SDKs this image
does not carry; their seam is the same ObjectLayer contract S3Objects
implements.
"""

from .s3 import S3Objects  # noqa: F401
