"""Minimal SigV4 S3 client for the gateway's upstream calls
(the role of minio-go inside cmd/gateway/s3/gateway-s3.go).

Streams bodies both ways: PUT sends from a reader without buffering
the object, GET hands back the raw HTTP response for the caller to
drain into its writer.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import threading
import urllib.parse
import xml.etree.ElementTree as ET

from ..utils.log import kv, logger

_log = logger("gateway")


class UpstreamError(Exception):
    def __init__(self, status: int, code: str, message: str = ""):
        super().__init__(f"{status} {code}: {message}")
        self.status = status
        self.code = code


def _sign_key(secret: str, date: str, region: str) -> bytes:
    k = hmac.new(
        f"AWS4{secret}".encode(), date.encode(), hashlib.sha256
    ).digest()
    for part in (region, "s3", "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    return k


class S3UpstreamClient:
    def __init__(self, endpoint: str, access_key: str, secret_key: str,
                 region: str = "us-east-1", timeout_s: float = 60.0):
        u = urllib.parse.urlsplit(endpoint)
        if u.scheme not in ("http", "https") or not u.hostname:
            raise ValueError(f"bad upstream endpoint {endpoint!r}")
        self.tls = u.scheme == "https"
        self.host = u.hostname
        self.port = u.port or (443 if self.tls else 80)
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self._timeout = timeout_s
        self._local = threading.local()

    def _conn(self) -> http.client.HTTPConnection:
        c = getattr(self._local, "conn", None)
        if c is None:
            cls = (
                http.client.HTTPSConnection
                if self.tls
                else http.client.HTTPConnection
            )
            kwargs = {"timeout": self._timeout}
            if self.tls:
                import os
                import ssl

                ctx = ssl.create_default_context()
                if os.environ.get("MINIO_TPU_GATEWAY_INSECURE") == "1":
                    ctx.check_hostname = False
                    ctx.verify_mode = ssl.CERT_NONE
                kwargs["context"] = ctx
            c = cls(self.host, self.port, **kwargs)
            self._local.conn = c
        return c

    def _drop(self) -> None:
        c = getattr(self._local, "conn", None)
        if c is not None:
            try:
                c.close()
            except Exception as exc:
                _log.debug("upstream connection close failed", extra=kv(err=str(exc)))
        self._local.conn = None

    def request(
        self,
        method: str,
        path: str,
        query: "dict[str, str] | None" = None,
        body: "bytes | None" = b"",
        headers: "dict[str, str] | None" = None,
        reader=None,
        content_length: int = -1,
        stream_response: bool = False,
    ):
        """One signed request.  ``path`` is the RAW (unencoded)
        object path - it is percent-encoded exactly once, and the
        same encoding feeds both the canonical request and the wire
        URL so the upstream verifier recomputes an identical
        signature.  Exactly one of ``body`` or
        ``reader``+``content_length`` supplies the payload.  Returns
        (status, headers, body_bytes) - or the live HTTPResponse when
        ``stream_response`` (caller must ``.read()`` it fully)."""
        query = dict(query or {})
        headers = {k.lower(): v for k, v in (headers or {}).items()}
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date = amz_date[:8]
        if reader is not None:
            # streamed payload: sign UNSIGNED-PAYLOAD (minio-go does
            # the same for streaming PUTs over TLS; over HTTP the
            # upstream still authenticates the headers)
            phash = "UNSIGNED-PAYLOAD"
        else:
            phash = hashlib.sha256(body or b"").hexdigest()
        headers["host"] = f"{self.host}:{self.port}"
        headers["x-amz-date"] = amz_date
        headers["x-amz-content-sha256"] = phash
        signed = sorted(headers)
        canonical_q = "&".join(
            f"{urllib.parse.quote(k, safe='-_.~')}="
            f"{urllib.parse.quote(v, safe='-_.~')}"
            for k, v in sorted(query.items())
        )
        enc_path = urllib.parse.quote(path, safe="/-_.~")
        canonical = "\n".join(
            [
                method,
                enc_path,
                canonical_q,
                "".join(f"{h}:{headers[h].strip()}\n" for h in signed),
                ";".join(signed),
                phash,
            ]
        )
        scope = f"{date}/{self.region}/s3/aws4_request"
        sts = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical.encode()).hexdigest(),
            ]
        )
        sig = hmac.new(
            _sign_key(self.secret_key, date, self.region),
            sts.encode(),
            hashlib.sha256,
        ).hexdigest()
        headers["authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}"
        )
        # wire query must be byte-identical to canonical_q: urlencode's
        # quote_plus ('+' for space) would break verifiers that
        # canonicalize from the raw query string (ADVICE r4)
        url = enc_path + (f"?{canonical_q}" if query else "")
        for attempt in (0, 1):
            conn = self._conn()
            try:
                if reader is not None:
                    headers["content-length"] = str(content_length)
                    conn.putrequest(method, url, skip_host=True)
                    for k, v in headers.items():
                        conn.putheader(k, v)
                    conn.endheaders()
                    sent = 0
                    while sent < content_length:
                        chunk = reader.read(
                            min(1 << 20, content_length - sent)
                        )
                        if not chunk:
                            break
                        conn.send(chunk)
                        sent += len(chunk)
                else:
                    conn.request(method, url, body=body, headers=headers)
                resp = conn.getresponse()
                break
            except (OSError, http.client.HTTPException):
                self._drop()
                if attempt or reader is not None:
                    # a half-sent streamed body is not retryable
                    raise UpstreamError(
                        0, "UpstreamUnreachable",
                        f"{self.host}:{self.port}",
                    ) from None
        if stream_response and resp.status < 300:
            return resp
        payload = resp.read()
        return resp.status, dict(resp.getheaders()), payload

    @staticmethod
    def error_code(payload: bytes) -> "tuple[str, str]":
        try:
            root = ET.fromstring(payload)
            code = root.findtext("Code") or ""
            msg = root.findtext("Message") or ""
            return code, msg
        except ET.ParseError:
            return "", ""

    def raise_for(self, status: int, payload: bytes) -> None:
        code, msg = self.error_code(payload)
        raise UpstreamError(status, code or "UpstreamError", msg)
