"""S3 gateway: ObjectLayer over an upstream S3-compatible store
(cmd/gateway/s3/gateway-s3.go).

Every ObjectLayer call maps to one upstream S3 request; bodies stream
both ways.  Versioning/heal surfaces raise NotImplementedError - the
reference's S3 gateway advertises the same reduced capability set
(gateway-s3.go IsCompressionSupported/IsEncryptionSupported gating).
"""

from __future__ import annotations

import email.utils
import threading
import urllib.parse
import xml.etree.ElementTree as ET

from ..objectlayer import api
from ..objectlayer.api import (
    BucketInfo,
    CompletePart,
    ListObjectsInfo,
    MultipartInfo,
    ObjectInfo,
    PartInfo,
    check_bucket_name,
    check_object_name,
    prepare_copy_meta,
)
from .client import S3UpstreamClient, UpstreamError

_ERR_MAP = {
    "NoSuchBucket": api.BucketNotFound,
    "NoSuchKey": api.ObjectNotFound,
    "NoSuchVersion": api.VersionNotFound,
    "BucketAlreadyOwnedByYou": api.BucketExists,
    "BucketAlreadyExists": api.BucketExists,
    "BucketNotEmpty": api.BucketNotEmpty,
    "InvalidBucketName": api.InvalidBucketName,
    "NoSuchUpload": api.InvalidUploadID,
    "InvalidPart": api.InvalidPart,
    "InvalidPartOrder": api.InvalidPartOrder,
    "EntityTooSmall": api.EntityTooSmall,
    "InvalidRange": api.InvalidRange,
    "PreconditionFailed": api.PreconditionFailed,
}


def _ns(tag: str) -> str:
    return tag.split("}")[-1]


def _find(el, name, default=""):
    for c in el:
        if _ns(c.tag) == name:
            return c.text or default
    return default


def _parse_http_date(raw: str) -> int:
    try:
        return int(
            email.utils.parsedate_to_datetime(raw).timestamp() * 1e9
        )
    except (TypeError, ValueError):
        return 0


def _parse_iso(raw: str) -> int:
    import datetime

    try:
        return int(
            datetime.datetime.fromisoformat(
                raw.replace("Z", "+00:00")
            ).timestamp()
            * 1e9
        )
    except ValueError:
        return 0


class S3Objects(api.ObjectLayer):
    # the fronting server forwards customer keys instead of running
    # its own SSE guards: the upstream owns encryption (_read_info_
    # and_sse in server/http.py keys off this)
    sse_passthrough = True

    def __init__(self, endpoint: str, access_key: str, secret_key: str,
                 region: str = "us-east-1"):
        self._c = S3UpstreamClient(
            endpoint, access_key, secret_key, region
        )
        # the reserved meta volume cannot live upstream (the upstream
        # S3 router refuses its own reserved namespace), so bucket
        # config documents are node-local and ephemeral here - the
        # reference's S3 gateway keeps bucket config similarly
        # reduced (gateway-s3.go unsupported config surfaces)
        self._meta_store: "dict[str, bytes]" = {}
        self._meta_mu = threading.Lock()

    # -- error translation -------------------------------------------------

    def _raise(self, status: int, payload: bytes, what: str):
        code, msg = self._c.error_code(payload)
        exc = _ERR_MAP.get(code)
        if exc is not None:
            raise exc(msg or what)
        raise UpstreamError(status, code or "UpstreamError", msg or what)

    # -- buckets -----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        check_bucket_name(bucket)
        if bucket == api.META_BUCKET:
            return
        st, _h, body = self._c.request("PUT", f"/{bucket}")
        if st not in (200, 204):
            self._raise(st, body, bucket)

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        if bucket == api.META_BUCKET:
            return BucketInfo(name=bucket, created_ns=0)
        st, _h, body = self._c.request("HEAD", f"/{bucket}")
        if st == 404:
            raise api.BucketNotFound(bucket)
        if st >= 300:
            raise UpstreamError(st, "UpstreamError", bucket)
        return BucketInfo(name=bucket, created_ns=0)

    def list_buckets(self) -> "list[BucketInfo]":
        st, _h, body = self._c.request("GET", "/")
        if st != 200:
            self._raise(st, body, "list buckets")
        out = []
        root = ET.fromstring(body)
        for b in root.iter():
            if _ns(b.tag) == "Bucket":
                out.append(
                    BucketInfo(
                        name=_find(b, "Name"),
                        created_ns=_parse_iso(_find(b, "CreationDate")),
                    )
                )
        return out

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        if force:
            # upstream S3 has no force-delete: drain it first
            while True:
                res = self.list_objects(bucket, max_keys=1000)
                if not res.objects:
                    break
                for oi in res.objects:
                    self.delete_object(bucket, oi.name)
        st, _h, body = self._c.request("DELETE", f"/{bucket}")
        if st not in (200, 204):
            self._raise(st, body, bucket)

    # -- objects -----------------------------------------------------------

    @staticmethod
    def _sse_headers(sse, copy_source: bool = False) -> dict:
        """SSE passthrough headers for the upstream (the reference's
        gateway-s3-sse.go forwards customer keys verbatim; SSE-S3 is
        one algorithm header - the UPSTREAM owns the encryption)."""
        if sse is None:
            return {}
        if getattr(sse, "mode", "") == "C":
            import base64 as _b64

            from ..codec import sse as ssemod

            prefix = (
                "x-amz-copy-source-server-side-encryption-customer"
                if copy_source
                else "x-amz-server-side-encryption-customer"
            )
            return {
                f"{prefix}-algorithm": "AES256",
                f"{prefix}-key": _b64.b64encode(sse.key).decode(),
                f"{prefix}-key-MD5": ssemod.key_md5_b64(sse.key),
            }
        if copy_source:
            # an SSE-S3 SOURCE needs no request header (the upstream
            # decrypts transparently); emitting the destination
            # header here would silently encrypt the destination
            return {}
        return {"x-amz-server-side-encryption": "AES256"}

    @staticmethod
    def _meta_headers(metadata: "dict | None") -> dict:
        headers = {}
        for k, v in (metadata or {}).items():
            lk = k.lower()
            if lk == "content-type":
                headers["content-type"] = v
            elif lk.startswith("x-amz-meta-") or lk == "x-amz-tagging":
                headers[lk] = v
        return headers

    def put_object(self, bucket, object_name, reader, size=-1,
                   metadata=None, versioned=False, compress=None,
                   sse=None):
        check_object_name(object_name)
        if bucket == api.META_BUCKET:
            data = reader.read() if size < 0 else reader.read(size)
            with self._meta_mu:
                self._meta_store[object_name] = data
            return ObjectInfo(
                bucket=bucket, name=object_name, size=len(data)
            )
        if size < 0:
            raise NotImplementedError(
                "unsized streams through the S3 gateway"
            )
        headers = self._meta_headers(metadata)
        headers.update(self._sse_headers(sse))
        st, h, body = self._c.request(
            "PUT",
            f"/{bucket}/{object_name}",
            headers=headers,
            reader=reader,
            content_length=size,
        )
        if st != 200:
            self._raise(st, body, f"{bucket}/{object_name}")
        hl = {k.lower(): v for k, v in h.items()}
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            size=size,
            etag=hl.get("etag", "").strip('"'),
            version_id=hl.get("x-amz-version-id", ""),
            user_defined=dict(metadata or {}),
        )

    def _head(
        self, bucket, object_name, version_id="", sse=None
    ) -> "tuple[int, dict]":
        st, h, _b = self._c.request(
            "HEAD",
            f"/{bucket}/{object_name}",
            query={"versionId": version_id} if version_id else None,
            headers=self._sse_headers(sse) or None,
        )
        return st, {k.lower(): v for k, v in h.items()}

    def get_object_info(
        self, bucket, object_name, version_id="", sse=None
    ):
        check_object_name(object_name)
        if bucket == api.META_BUCKET:
            with self._meta_mu:
                data = self._meta_store.get(object_name)
            if data is None:
                raise api.ObjectNotFound(f"{bucket}/{object_name}")
            return ObjectInfo(
                bucket=bucket, name=object_name, size=len(data)
            )
        st, h = self._head(bucket, object_name, version_id, sse)
        if st == 404:
            if version_id:
                raise api.VersionNotFound(
                    f"{bucket}/{object_name}@{version_id}"
                )
            raise api.ObjectNotFound(f"{bucket}/{object_name}")
        if st >= 300:
            raise UpstreamError(st, "UpstreamError", object_name)
        meta = {
            k: v for k, v in h.items() if k.startswith("x-amz-meta-")
        }
        if "x-amz-tagging" in h:
            meta["x-amz-tagging"] = h["x-amz-tagging"]
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            size=int(h.get("content-length", 0)),
            mod_time_ns=_parse_http_date(h.get("last-modified", "")),
            etag=h.get("etag", "").strip('"'),
            content_type=h.get("content-type", ""),
            version_id=h.get("x-amz-version-id", ""),
            user_defined=meta,
        )

    def get_object(self, bucket, object_name, writer, offset=0,
                   length=-1, version_id="", sse=None):
        check_object_name(object_name)
        if bucket == api.META_BUCKET:
            with self._meta_mu:
                data = self._meta_store.get(object_name)
            if data is None:
                raise api.ObjectNotFound(f"{bucket}/{object_name}")
            end = offset + length if length >= 0 else len(data)
            writer.write(data[offset:end])
            return ObjectInfo(
                bucket=bucket, name=object_name, size=len(data)
            )
        headers = self._sse_headers(sse)
        if offset or length >= 0:
            end = f"{offset + length - 1}" if length >= 0 else ""
            headers["range"] = f"bytes={offset}-{end}"
        resp = self._c.request(
            "GET",
            f"/{bucket}/{object_name}",
            query={"versionId": version_id} if version_id else None,
            headers=headers,
            stream_response=True,
        )
        if isinstance(resp, tuple):  # error path: (st, h, body)
            st, _h, body = resp
            if st == 404 and version_id:
                raise api.VersionNotFound(
                    f"{bucket}/{object_name}@{version_id}"
                )
            self._raise(st, body, f"{bucket}/{object_name}")
        try:
            while True:
                chunk = resp.read(1 << 20)
                if not chunk:
                    break
                writer.write(chunk)
        finally:
            resp.close()
        return self.get_object_info(
            bucket, object_name, version_id, sse
        )

    def delete_object(self, bucket, object_name, version_id="",
                      versioned=False, version_suspended=False):
        check_object_name(object_name)
        if bucket == api.META_BUCKET:
            with self._meta_mu:
                if self._meta_store.pop(object_name, None) is None:
                    raise api.ObjectNotFound(
                        f"{bucket}/{object_name}"
                    )
            return ObjectInfo(bucket=bucket, name=object_name)
        st, h, body = self._c.request(
            "DELETE",
            f"/{bucket}/{object_name}",
            query={"versionId": version_id} if version_id else None,
        )
        if st not in (200, 204):
            self._raise(st, body, f"{bucket}/{object_name}")
        hl = {k.lower(): v for k, v in h.items()}
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            version_id=hl.get("x-amz-version-id", version_id),
            delete_marker=hl.get("x-amz-delete-marker") == "true",
        )

    def copy_object(self, src_bucket, src_object, dst_bucket,
                    dst_object, metadata=None, versioned=False,
                    sse_src=None, sse=None):
        src_info = self.get_object_info(
            src_bucket, src_object, sse=sse_src
        )
        headers = {
            "x-amz-copy-source": urllib.parse.quote(
                f"/{src_bucket}/{src_object}"
            ),
        }
        headers.update(self._sse_headers(sse_src, copy_source=True))
        headers.update(self._sse_headers(sse))
        if metadata is not None:
            headers["x-amz-metadata-directive"] = "REPLACE"
            headers.update(
                self._meta_headers(
                    prepare_copy_meta(src_info, metadata)
                )
            )
        st, _h, body = self._c.request(
            "PUT",
            f"/{dst_bucket}/{dst_object}",
            headers=headers,
        )
        if st != 200:
            self._raise(st, body, f"{dst_bucket}/{dst_object}")
        root = ET.fromstring(body)
        return ObjectInfo(
            bucket=dst_bucket,
            name=dst_object,
            size=src_info.size,
            etag=_find(root, "ETag").strip('"'),
        )

    def update_object_meta(self, bucket, object_name, updates,
                           version_id=""):
        info = self.get_object_info(bucket, object_name)
        meta = dict(info.user_defined)
        for k, v in updates.items():
            if v is None:
                meta.pop(k, None)
            else:
                meta[k] = v
        return self.copy_object(
            bucket, object_name, bucket, object_name, meta
        )

    # -- listing -----------------------------------------------------------

    def list_objects(self, bucket, prefix="", marker="", delimiter="",
                     max_keys=1000) -> ListObjectsInfo:
        q = {"max-keys": str(max_keys)}
        if prefix:
            q["prefix"] = prefix
        if marker:
            q["marker"] = marker
        if delimiter:
            q["delimiter"] = delimiter
        st, _h, body = self._c.request("GET", f"/{bucket}", query=q)
        if st != 200:
            self._raise(st, body, bucket)
        root = ET.fromstring(body)
        out = ListObjectsInfo()
        for el in root:
            tag = _ns(el.tag)
            if tag == "Contents":
                out.objects.append(
                    ObjectInfo(
                        bucket=bucket,
                        name=_find(el, "Key"),
                        size=int(_find(el, "Size", "0") or 0),
                        etag=_find(el, "ETag").strip('"'),
                        mod_time_ns=_parse_iso(
                            _find(el, "LastModified")
                        ),
                    )
                )
            elif tag == "CommonPrefixes":
                out.prefixes.append(_find(el, "Prefix"))
            elif tag == "IsTruncated":
                out.is_truncated = (el.text or "") == "true"
            elif tag == "NextMarker":
                out.next_marker = el.text or ""
        if out.is_truncated and not out.next_marker and out.objects:
            out.next_marker = out.objects[-1].name
        return out

    def has_object_versions(self, bucket, object_name) -> bool:
        res = self.list_object_versions(
            bucket, prefix=object_name, max_keys=2
        )
        return any(
            v.name == object_name and (
                v.version_id or v.delete_marker
            )
            for v in res.versions
        )

    def list_object_versions(
        self, bucket, prefix="", key_marker="", version_id_marker="",
        delimiter="", max_keys=1000,
    ):
        """Pass-through ListObjectVersions (?versions) with the
        upstream's XML mapped onto the layer's result shape."""
        q = {"versions": "", "max-keys": str(max_keys)}
        if prefix:
            q["prefix"] = prefix
        if key_marker:
            q["key-marker"] = key_marker
        if version_id_marker:
            q["version-id-marker"] = version_id_marker
        if delimiter:
            q["delimiter"] = delimiter
        st, _h, body = self._c.request("GET", f"/{bucket}", query=q)
        if st != 200:
            self._raise(st, body, bucket)
        root = ET.fromstring(body)
        out = api.ListObjectVersionsInfo(
            is_truncated=_find(root, "IsTruncated") == "true",
            next_key_marker=_find(root, "NextKeyMarker"),
            next_version_id_marker=_find(
                root, "NextVersionIdMarker"
            ),
        )
        for el in root:
            tag = el.tag.rsplit("}", 1)[-1]
            if tag == "CommonPrefixes":
                out.prefixes.append(_find(el, "Prefix"))
                continue
            if tag not in ("Version", "DeleteMarker"):
                continue
            vid = _find(el, "VersionId")
            out.versions.append(
                ObjectInfo(
                    bucket=bucket,
                    name=_find(el, "Key"),
                    size=int(_find(el, "Size") or 0),
                    etag=_find(el, "ETag").strip('"'),
                    mod_time_ns=_parse_iso(
                        _find(el, "LastModified")
                    ),
                    version_id="" if vid == "null" else vid,
                    is_latest=_find(el, "IsLatest") == "true",
                    delete_marker=tag == "DeleteMarker",
                )
            )
        return out

    # -- multipart ---------------------------------------------------------

    def new_multipart_upload(self, bucket, object_name, metadata=None,
                             sse=None):
        headers = self._meta_headers(metadata)
        headers.update(self._sse_headers(sse))
        st, _h, body = self._c.request(
            "POST",
            f"/{bucket}/{object_name}",
            query={"uploads": ""},
            headers=headers,
        )
        if st != 200:
            self._raise(st, body, f"{bucket}/{object_name}")
        return _find(ET.fromstring(body), "UploadId")

    def put_object_part(self, bucket, object_name, upload_id,
                        part_number, reader, size=-1, sse=None):
        if size < 0:
            raise NotImplementedError(
                "unsized parts through the S3 gateway"
            )
        st, h, body = self._c.request(
            "PUT",
            f"/{bucket}/{object_name}",
            query={
                "uploadId": upload_id,
                "partNumber": str(part_number),
            },
            headers=self._sse_headers(sse) or None,
            reader=reader,
            content_length=size,
        )
        if st != 200:
            self._raise(st, body, upload_id)
        hl = {k.lower(): v for k, v in h.items()}
        return PartInfo(
            part_number=part_number,
            etag=hl.get("etag", "").strip('"'),
            size=size,
            actual_size=size,
        )

    def list_object_parts(self, bucket, object_name, upload_id,
                          part_marker=0, max_parts=1000):
        st, _h, body = self._c.request(
            "GET",
            f"/{bucket}/{object_name}",
            query={
                "uploadId": upload_id,
                "part-number-marker": str(part_marker),
                "max-parts": str(max_parts),
            },
        )
        if st != 200:
            self._raise(st, body, upload_id)
        parts = []
        for el in ET.fromstring(body):
            if _ns(el.tag) == "Part":
                parts.append(
                    PartInfo(
                        part_number=int(_find(el, "PartNumber", "0")),
                        etag=_find(el, "ETag").strip('"'),
                        size=int(_find(el, "Size", "0") or 0),
                    )
                )
        return parts

    def list_multipart_uploads(self, bucket, prefix=""):
        st, _h, body = self._c.request(
            "GET", f"/{bucket}",
            query={"uploads": "", "prefix": prefix},
        )
        if st != 200:
            self._raise(st, body, bucket)
        out = []
        for el in ET.fromstring(body):
            if _ns(el.tag) == "Upload":
                out.append(
                    MultipartInfo(
                        bucket=bucket,
                        object=_find(el, "Key"),
                        upload_id=_find(el, "UploadId"),
                        initiated_ns=_parse_iso(
                            _find(el, "Initiated")
                        ),
                    )
                )
        return out

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        st, _h, body = self._c.request(
            "DELETE",
            f"/{bucket}/{object_name}",
            query={"uploadId": upload_id},
        )
        if st not in (200, 204):
            self._raise(st, body, upload_id)

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts: "list[CompletePart]",
                                  versioned=False, **kw):
        root = ET.Element("CompleteMultipartUpload")
        for cp in parts:
            pe = ET.SubElement(root, "Part")
            ET.SubElement(pe, "PartNumber").text = str(cp.part_number)
            ET.SubElement(pe, "ETag").text = cp.etag
        st, _h, body = self._c.request(
            "POST",
            f"/{bucket}/{object_name}",
            query={"uploadId": upload_id},
            body=ET.tostring(root),
        )
        if st != 200:
            self._raise(st, body, upload_id)
        etag = _find(ET.fromstring(body), "ETag").strip('"')
        info = self.get_object_info(bucket, object_name)
        info.etag = etag or info.etag
        return info

    # -- heal / info -------------------------------------------------------

    def heal_bucket(self, bucket, dry_run=False):
        raise NotImplementedError("heal through the S3 gateway")

    def heal_object(self, bucket, object_name, version_id="",
                    dry_run=False):
        raise NotImplementedError("heal through the S3 gateway")

    def storage_info(self) -> dict:
        return {
            "mode": "gateway-s3",
            "upstream": f"{self._c.host}:{self._c.port}",
        }
