"""Declarative chaos-scenario grid over the multi-process cluster
harness (minio_tpu/cluster/harness.py).

A Scenario names a cluster shape, a seeded workload, a fault schedule
(delivered to REMOTE nodes over the admin fault endpoint), and the
invariants that must hold afterwards: objects bit-identical at quorum
or cleanly absent, no torn xl.meta on any drive, breakers tripping on
the faulted node and recovering half-open.  The grid itself lives in
scenarios.py; the interpreter in engine.py.
"""

from .engine import Fault, Scenario, run_scenario
from .scenarios import GRID, scenario_by_name

__all__ = [
    "Fault",
    "Scenario",
    "run_scenario",
    "GRID",
    "scenario_by_name",
]
