"""Scenario interpreter: steps -> harness actions -> invariant checks.

A scenario is data, not code: a tuple of small step verbs executed in
order against a live ClusterHarness, followed by the shared invariant
sweep.  The verbs deliberately mirror what an operator can do to a real
pool (degrade a drive, kill a process, restart it, keep client load
running) - nothing reaches into a node's memory; every interaction
crosses the wire.

Quorum invariants checked after every scenario:

- **readable-at-quorum**: every tracked object GETs bit-identical bytes
  from EVERY live node, and the bytes match one of the payloads a
  client successfully wrote (or plausibly wrote: a failed overwrite may
  have landed before the error) - or every node agrees it is cleanly
  absent.  Split answers between nodes are a violation.
- **no-torn-meta**: every xl.meta on every drive of every node still
  decodes (XLMeta.from_bytes); a torn or half-written journal fails.
- **breaker-cycle** (opt-in per scenario via await_breaker steps): the
  observer node's circuit breaker for the faulted node's drives must
  reach TRIPPED while the fault holds and return to HEALTHY after it
  lifts (half-open probe recovery).
"""

from __future__ import annotations

import dataclasses
import json
import random
import threading
import time

from ..cluster.harness import ClusterHarness
from ..utils.log import kv, logger

_log = logger("testgrid")

BUCKET = "grid"


@dataclasses.dataclass(frozen=True)
class Fault:
    """One remote FaultDisk rule, addressed to a node."""

    node: int
    api: str
    disk: str = "*"
    delay_s: float = 0.0
    hang_s: float = 0.0
    error: bool = False
    corrupt: bool = False
    prob: float = 1.0
    calls: "tuple | None" = None


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One grid cell: cluster shape + seeded data + step script."""

    name: str
    title: str
    nodes: int = 3
    drives_per_node: int = 2
    seed_objects: int = 4
    object_size: int = 48_000
    steps: tuple = ()
    # extra environment for every node process, as a tuple of
    # (name, value) pairs (frozen dataclasses need hashable fields);
    # the driver's env still wins on conflicts
    env: tuple = ()
    # invariant toggles (the sweep itself is shared)
    check_meta: bool = True
    check_reads: bool = True


def payload(n: int, seed: int) -> bytes:
    return random.Random(seed).randbytes(n)


class _Ctx:
    """Mutable scenario state: which payloads a key may legally hold."""

    def __init__(self, harness: ClusterHarness):
        self.h = harness
        # key -> list of acceptable payloads (last confirmed write
        # first; failed overwrites appended - a 5xx PUT may still have
        # reached quorum before the client saw the error)
        self.objects: "dict[str, list[bytes]]" = {}
        self.threads: "list[threading.Thread]" = []
        self.errors: "list[str]" = []
        self.breaker_log: "list[str]" = []
        # cross-step measurements (latency percentiles, counter marks)
        self.marks: "dict[str, float]" = {}
        # (key, expr) -> row-engine oracle Records bytes, computed once
        self.select_oracles: "dict[tuple, bytes]" = {}

    def confirm(self, key: str, body: bytes) -> None:
        self.objects[key] = [body]

    def attempt(self, key: str, body: bytes) -> None:
        self.objects.setdefault(key, []).append(body)


def _put(ctx: _Ctx, node: int, key: str, body: bytes) -> int:
    status, _, _ = ctx.h.client(node).request(
        "PUT", f"/{BUCKET}/{key}", body=body
    )
    if status == 200:
        ctx.confirm(key, body)
    else:
        ctx.attempt(key, body)
    return status


def _get(ctx: _Ctx, node: int, key: str):
    return ctx.h.client(node).request("GET", f"/{BUCKET}/{key}")


# -- step verbs ------------------------------------------------------------


def _step_fault(ctx: _Ctx, f: Fault) -> None:
    ctx.h.inject_fault(
        f.node,
        f.api,
        disk=f.disk,
        delay_s=f.delay_s,
        hang_s=f.hang_s,
        error=f.error,
        corrupt=f.corrupt,
        prob=f.prob,
        calls=None if f.calls is None else list(f.calls),
    )


def _step_clear(ctx: _Ctx, node: int) -> None:
    ctx.h.clear_faults(node)


def _step_put(ctx: _Ctx, node: int, key: str, size: int, seed: int) -> None:
    status = _put(ctx, node, key, payload(size, seed))
    if status not in (200, 503):
        raise AssertionError(f"PUT {key} via n{node + 1}: HTTP {status}")


def _step_churn(
    ctx: _Ctx, node: int, keys: int, rounds: int, size: int, seed: int
) -> None:
    """Background writer: overwrite a keyset round-robin until joined.
    Failures are tolerated (that is the point of churn under faults)
    but recorded as attempts so the final sweep accepts either body."""

    def run() -> None:
        s = seed
        for r in range(rounds):
            for k in range(keys):
                s += 1
                try:
                    _put(ctx, node, f"churn{k}", payload(size, s))
                except OSError:
                    # node restarting mid-request: retry next round
                    time.sleep(0.2)

    t = threading.Thread(target=run, name="grid-churn", daemon=True)
    t.start()
    ctx.threads.append(t)


def _step_join(ctx: _Ctx, timeout_s: float = 120.0) -> None:
    for t in ctx.threads:
        t.join(timeout=timeout_s)
        if t.is_alive():
            raise AssertionError(f"workload thread {t.name} hung")
    ctx.threads.clear()


def _flood(
    ctx: _Ctx, key: str, count: int, threads: int
) -> "list[float]":
    """Hot-key read storm from every node; every reply must be 200 and
    bit-identical to an acceptable payload.  Returns the per-request
    wall latencies of the successful reads."""
    ok_bodies = ctx.objects[key]
    fails: list[str] = []
    latencies: list[float] = []

    import http.client as _hc

    def run(worker: int) -> None:
        for j in range(count):
            node = (worker + j) % len(ctx.h.nodes)
            if not ctx.h.nodes[node].alive():
                continue
            # a dropped connection under fault load is a transport
            # hiccup, not a correctness violation: one retry on a
            # fresh connection; only a persistent failure counts
            for attempt in (0, 1):
                t0 = time.monotonic()
                try:
                    status, _, body = _get(ctx, node, key)
                except (OSError, _hc.HTTPException):
                    if attempt:
                        fails.append(f"n{node + 1}#{j}: transport")
                    continue
                if status != 200 or body not in ok_bodies:
                    fails.append(f"n{node + 1}#{j}: HTTP {status}")
                else:
                    latencies.append(time.monotonic() - t0)
                break

    ts = [
        threading.Thread(target=run, args=(w,), daemon=True)
        for w in range(threads)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    if fails:
        raise AssertionError(
            f"get flood on {key}: {len(fails)} bad reads "
            f"(first: {fails[0]})"
        )
    return latencies


def _step_get_flood(
    ctx: _Ctx, key: str, count: int, threads: int = 4
) -> None:
    _flood(ctx, key, count, threads)


def _p99(samples: "list[float]") -> float:
    if not samples:
        raise AssertionError("no latency samples collected")
    s = sorted(samples)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def _step_timed_get_flood(
    ctx: _Ctx, key: str, count: int, threads: int, mark: str
) -> None:
    """get_flood + record the p99 latency under ``mark``."""
    ctx.marks[mark] = _p99(_flood(ctx, key, count, threads))


def _step_assert_p99_within(
    ctx: _Ctx,
    mark: str,
    baseline: str,
    factor: float,
    slack_s: float = 0.0,
) -> None:
    """The marked p99 must stay within factor x baseline (plus an
    absolute slack floor so millisecond-scale noise cannot flake).

    On a single-core host the background load and the timed flood
    time-slice one CPU, so latency inflation measures the scheduler,
    not the isolation property under test — the factor falls back to
    a coarse starvation-only bound there (a stalled flood behind a
    scan/heal storm still overshoots it by an order of magnitude)."""
    import os

    hot, base = ctx.marks[mark], ctx.marks[baseline]
    if (os.cpu_count() or 1) < 2:
        factor = max(factor, 8.0)
    limit = max(base * factor, base + slack_s)
    if hot > limit:
        raise AssertionError(
            f"p99 regressed: {mark}={hot * 1e3:.1f}ms vs "
            f"{baseline}={base * 1e3:.1f}ms (limit {limit * 1e3:.1f}ms)"
        )


# the data-plane shard-read API: one call per shard stream a GET opens.
# A full-cache-hit GET opens zero (the codec's reader bank is lazy), so
# the hot-key cache cell can assert the counter does not move at all.
DATA_READ_API = "read_file_stream"


def _data_reads_total(ctx: _Ctx) -> float:
    from ..cluster.harness import parse_prometheus

    total = 0.0
    for n in ctx.h.nodes:
        if not n.alive():
            continue
        try:
            rows = parse_prometheus(ctx.h.scrape(n.index))
        except OSError:
            continue
        for name, labels, value in rows:
            if (
                name == "miniotpu_disk_api_calls_total"
                and labels.get("api") == DATA_READ_API
            ):
                total += value
    return total


def _step_mark_data_reads(ctx: _Ctx, mark: str = "data_reads") -> None:
    ctx.marks[mark] = _data_reads_total(ctx)


def _step_assert_data_reads_flat(
    ctx: _Ctx, mark: str = "data_reads"
) -> None:
    before = ctx.marks[mark]
    now = _data_reads_total(ctx)
    if now != before:
        raise AssertionError(
            f"cache-hit flood touched the data plane: "
            f"{DATA_READ_API} calls moved {before:.0f} -> {now:.0f}"
        )


# -- S3-Select verbs -------------------------------------------------------
#
# The select cells treat every node's SELECT response as a claim about
# the object's bytes: the Records payload must be BIT-IDENTICAL to the
# row engine run locally in the driver process over the payload the
# client wrote.  Whatever engine a node picks (device screen, host
# vector, row) and however degraded its disks are, the answer may not
# drift.


def csv_payload(rows: int, seed: int) -> bytes:
    """Deterministic CSV table (same shape for driver and cluster)."""
    lines = ["id,name,qty,price"]
    for i in range(rows):
        j = i + seed
        lines.append(f"{i},item{j % 13},{j % 11},{(j % 7) * 0.75}")
    return ("\n".join(lines) + "\n").encode()


def _select_xml(expr: str) -> bytes:
    return (
        "<SelectObjectContentRequest>"
        f"<Expression>{expr.replace('<', '&lt;')}</Expression>"
        "<ExpressionType>SQL</ExpressionType>"
        "<InputSerialization><CSV><FileHeaderInfo>USE"
        "</FileHeaderInfo></CSV></InputSerialization>"
        "<OutputSerialization><CSV/></OutputSerialization>"
        "</SelectObjectContentRequest>"
    ).encode()


def _select_records(stream: bytes) -> bytes:
    from ..s3select.message import decode_all

    return b"".join(
        m["payload"]
        for m in decode_all(stream)
        if m["headers"].get(":event-type") == "Records"
    )


def _select_oracle(ctx: _Ctx, key: str, expr: str) -> bytes:
    """Row-engine answer computed in the driver process (no cluster
    involvement), cached per (key, expr)."""
    cached = ctx.select_oracles.get((key, expr))
    if cached is not None:
        return cached
    import io
    import os

    from ..s3select.engine import S3Select, SelectRequest

    data = ctx.objects[key][0]
    saved = os.environ.get("MINIO_TPU_SELECT")
    os.environ["MINIO_TPU_SELECT"] = "row"
    try:
        out = bytearray()
        sel = S3Select(SelectRequest.from_xml(_select_xml(expr)))
        sel.evaluate(io.BytesIO(data), len(data), out.extend)
    finally:
        if saved is None:
            os.environ.pop("MINIO_TPU_SELECT", None)
        else:
            os.environ["MINIO_TPU_SELECT"] = saved
    oracle = _select_records(bytes(out))
    ctx.select_oracles[(key, expr)] = oracle
    return oracle


def _select_once(ctx: _Ctx, node: int, key: str, expr: str):
    return ctx.h.client(node).request(
        "POST",
        f"/{BUCKET}/{key}",
        query={"select": "", "select-type": "2"},
        body=_select_xml(expr),
    )


def _select_flood(
    ctx: _Ctx, key: str, expr: str, count: int, threads: int
) -> "list[float]":
    """SELECT storm from every node; every reply must be 200 with a
    Records payload bit-identical to the local row-engine oracle."""
    oracle = _select_oracle(ctx, key, expr)
    fails: list[str] = []
    latencies: list[float] = []

    import http.client as _hc

    def run(worker: int) -> None:
        for j in range(count):
            node = (worker + j) % len(ctx.h.nodes)
            if not ctx.h.nodes[node].alive():
                continue
            for attempt in (0, 1):
                t0 = time.monotonic()
                try:
                    status, _, body = _select_once(ctx, node, key, expr)
                except (OSError, _hc.HTTPException):
                    if attempt:
                        fails.append(f"n{node + 1}#{j}: transport")
                    continue
                if status != 200:
                    fails.append(f"n{node + 1}#{j}: HTTP {status}")
                elif _select_records(body) != oracle:
                    fails.append(f"n{node + 1}#{j}: records diverged")
                else:
                    latencies.append(time.monotonic() - t0)
                break

    ts = [
        threading.Thread(target=run, args=(w,), daemon=True)
        for w in range(threads)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    if fails:
        raise AssertionError(
            f"select flood on {key}: {len(fails)} bad answers "
            f"(first: {fails[0]})"
        )
    return latencies


def _step_put_csv(
    ctx: _Ctx, node: int, key: str, rows: int, seed: int
) -> None:
    status = _put(ctx, node, key, csv_payload(rows, seed))
    if status != 200:
        raise AssertionError(f"PUT {key} via n{node + 1}: HTTP {status}")


def _step_select_flood(
    ctx: _Ctx, key: str, expr: str, count: int, threads: int = 4
) -> None:
    _select_flood(ctx, key, expr, count, threads)


def _step_timed_select_flood(
    ctx: _Ctx, key: str, expr: str, count: int, threads: int, mark: str
) -> None:
    """select_flood + record the p99 latency under ``mark``."""
    ctx.marks[mark] = _p99(_select_flood(ctx, key, expr, count, threads))


def _step_select_churn(
    ctx: _Ctx, key: str, expr: str, rounds: int, threads: int = 2
) -> None:
    """Background scan load: keep SELECTing until joined.  Transport
    hiccups are tolerated; a wrong ANSWER is recorded and fails the
    scenario at the end (bit-identity holds even for background load)."""
    oracle = _select_oracle(ctx, key, expr)

    def run(worker: int) -> None:
        for r in range(rounds):
            node = (worker + r) % len(ctx.h.nodes)
            if not ctx.h.nodes[node].alive():
                continue
            try:
                status, _, body = _select_once(ctx, node, key, expr)
            except OSError:
                time.sleep(0.1)
                continue
            if status == 200 and _select_records(body) != oracle:
                ctx.errors.append(
                    f"select churn n{node + 1}#{r}: records diverged"
                )

    for w in range(threads):
        t = threading.Thread(
            target=run, args=(w,), name="grid-select", daemon=True
        )
        t.start()
        ctx.threads.append(t)


def _step_make_bucket(ctx: _Ctx, node: int, name: str) -> None:
    status, _, _ = ctx.h.client(node).request("PUT", f"/{name}")
    if status != 200:
        raise AssertionError(f"make_bucket {name}: HTTP {status}")


_REPL_XML = (
    b"<ReplicationConfiguration>"
    b"<Rule><Status>Enabled</Status><Priority>1</Priority>"
    b"<Prefix></Prefix>"
    b"<Destination><Bucket>%s</Bucket></Destination></Rule>"
    b"</ReplicationConfiguration>"
)


def _step_enable_replication(ctx: _Ctx, node: int, dst: str) -> None:
    """Versioning + a catch-all replication rule on the grid bucket,
    targeting a local destination bucket."""
    c = ctx.h.client(node)
    status, _, body = c.request(
        "PUT", f"/{BUCKET}", query={"versioning": ""},
        body=b"<VersioningConfiguration><Status>Enabled</Status>"
        b"</VersioningConfiguration>",
    )
    if status != 200:
        raise AssertionError(f"enable versioning: HTTP {status}")
    status, _, body = c.request(
        "PUT", f"/{BUCKET}", query={"replication": ""},
        body=_REPL_XML % dst.encode(),
    )
    if status != 200:
        raise AssertionError(
            f"replication config: HTTP {status}: {body[:200]!r}"
        )


def _step_await_replication(
    ctx: _Ctx,
    node: int,
    dst: str,
    keys: tuple,
    timeout_s: float = 90.0,
) -> None:
    """Poll the destination bucket until every key reads back one of
    its acceptable payloads — the async queue plus the crawler's
    PENDING/FAILED catch-up must converge with no manual kick."""
    deadline = time.monotonic() + timeout_s
    lagging: "dict[str, object]" = {}
    while time.monotonic() < deadline:
        lagging = {}
        for key in keys:
            ok_bodies = ctx.objects.get(key, [])
            try:
                status, _, body = ctx.h.client(node).request(
                    "GET", f"/{dst}/{key}"
                )
            except OSError:
                lagging[key] = "transport"
                continue
            if status != 200:
                lagging[key] = f"HTTP {status}"
            elif body not in ok_bodies:
                lagging[key] = f"stale body ({len(body)} bytes)"
        if not lagging:
            return
        time.sleep(0.5)
    raise AssertionError(
        f"replication to {dst} never converged: {lagging}"
    )


def _step_kill(ctx: _Ctx, node: int) -> None:
    ctx.h.kill(node)


def _step_terminate(ctx: _Ctx, node: int) -> None:
    rc = ctx.h.terminate(node)
    if rc != 0:
        raise AssertionError(
            f"n{node + 1} SIGTERM exit rc={rc}:\n"
            + ctx.h.nodes[node].log_tail()
        )


def _step_restart(ctx: _Ctx, node: int, graceful: bool = False) -> None:
    ctx.h.restart(node, graceful=graceful)


def _step_wipe_drive(ctx: _Ctx, node: int, drive: int) -> None:
    """Empty one drive dir while its node is down (drive swap)."""
    import shutil

    root = ctx.h.nodes[node].drive_dirs[drive]
    for entry in list(root.iterdir()):
        shutil.rmtree(entry, ignore_errors=True)


def _step_sleep(ctx: _Ctx, seconds: float) -> None:
    time.sleep(seconds)


def _step_await_breaker(
    ctx: _Ctx,
    observer: int,
    target: int,
    state: int,
    timeout_s: float = 60.0,
) -> None:
    """Poll the observer's miniotpu_disk_state for the target node's
    drives until one reaches ``state`` (2=TRIPPED) or, for state 0,
    until ALL are healthy again.  Reads are issued each poll so the
    breaker sees traffic (half-open needs a probe request)."""
    port_tag = f":{ctx.h.nodes[target].port}"
    probe_keys = list(ctx.objects) or [""]
    deadline = time.monotonic() + timeout_s
    last: dict = {}
    i = 0
    while time.monotonic() < deadline:
        if probe_keys[0]:
            try:
                _get(ctx, observer, probe_keys[i % len(probe_keys)])
            except OSError:
                pass
            i += 1
        states = {
            ep: st
            for ep, st in ctx.h.disk_states(observer).items()
            if port_tag in ep
        }
        last = states
        if states:
            if state == 0 and all(st == 0 for st in states.values()):
                ctx.breaker_log.append(f"n{target + 1}:recovered")
                return
            if state > 0 and any(
                st >= state for st in states.values()
            ):
                ctx.breaker_log.append(f"n{target + 1}:state{state}")
                return
        time.sleep(0.25)
    raise AssertionError(
        f"breaker on n{observer + 1} never reached state {state} for "
        f"n{target + 1} drives; last={last}"
    )


def _step_await_heal(
    ctx: _Ctx,
    node: int,
    drive: int,
    want_objects: "tuple",
    timeout_s: float = 90.0,
) -> None:
    """Wait until every named object has a shard back on the wiped
    drive - convergence with NO manual heal call (fresh-disk monitor
    plus heal routine)."""
    root = ctx.h.nodes[node].drive_dirs[drive]
    want = set(want_objects)
    deadline = time.monotonic() + timeout_s
    healed: set = set()
    while time.monotonic() < deadline:
        healed = {
            p.parent.parent.name
            for p in root.glob(f"{BUCKET}/*/*/part.1")
        }
        if want <= healed:
            return
        time.sleep(0.5)
    raise AssertionError(
        f"heal never converged on n{node + 1} drive{drive + 1}: "
        f"healed={sorted(healed)} want={sorted(want)}"
    )


def _step_await_locks_drained(
    ctx: _Ctx, node: int, timeout_s: float = 30.0
) -> None:
    """top-locks on a node must drain to empty: a graceful peer
    restart may not leave orphaned dsync entries behind."""
    deadline = time.monotonic() + timeout_s
    doc: dict = {}
    while time.monotonic() < deadline:
        status, doc = ctx.h.admin(node, "GET", "top-locks")
        locks = doc.get("locks", doc if isinstance(doc, list) else [])
        if status == 200 and not locks:
            return
        time.sleep(0.5)
    raise AssertionError(
        f"n{node + 1} still holds lock entries: {doc}"
    )


def _step_expect_put(
    ctx: _Ctx, node: int, key: str, size: int, seed: int, status: int
) -> None:
    got = _put(ctx, node, key, payload(size, seed))
    if got != status:
        raise AssertionError(
            f"PUT {key} via n{node + 1}: wanted HTTP {status}, "
            f"got {got}"
        )


def _step_wedge_loop(
    ctx: _Ctx, node: int, loop_ix: int, seconds: float
) -> None:
    """Busy-spin one server loop's thread on node ``node`` via the
    admin control plane (gated on MINIO_TPU_FAULT_INJECTION, like disk
    faults).  Returns as soon as the wedge is scheduled."""
    body = json.dumps({"loop": loop_ix, "seconds": seconds}).encode()
    status, out = ctx.h.admin(node, "POST", "loops/wedge", body=body)
    if status != 200:
        raise AssertionError(
            f"loops/wedge loop{loop_ix} on n{node + 1}: "
            f"HTTP {status} {out}"
        )


def _step_assert_loops_serving(
    ctx: _Ctx, node: int, count: int
) -> None:
    """Node ``node`` reports exactly ``count`` event loops, all in
    state=serving."""
    status, out = ctx.h.admin(node, "GET", "loops/status")
    if status != 200:
        raise AssertionError(
            f"loops/status on n{node + 1}: HTTP {status} {out}"
        )
    states = [row.get("state") for row in out.get("per_loop", [])]
    if out.get("count") != count or states != ["serving"] * count:
        raise AssertionError(
            f"n{node + 1} loops not all serving: "
            f"count={out.get('count')} states={states}"
        )


def _step_probe_health_during_wedge(
    ctx: _Ctx, node: int, within_s: float, probes: int = 3
) -> None:
    """While a wedge holds on one of node's loops, concurrent fresh
    connections must still reach the control plane fast: at least one
    of ``probes`` parallel loops/status calls answers within
    ``within_s`` (in handoff mode consecutive accepts round-robin over
    loops, so some probe always lands on a healthy loop)."""
    time.sleep(0.5)  # let the wedge's scheduling grace elapse first
    results: "list[tuple[int, float]]" = []
    mu = threading.Lock()

    def probe() -> None:
        t0 = time.monotonic()
        try:
            status, _ = ctx.h.admin(node, "GET", "loops/status")
        except OSError:
            status = -1
        with mu:
            results.append((status, time.monotonic() - t0))

    threads = [
        threading.Thread(target=probe) for _ in range(probes)
    ]
    for t in threads:
        t.start()
        # sequential connects so handoff round-robin spreads the
        # probes across loops deterministically
        time.sleep(0.05)
    for t in threads:
        t.join(within_s + 30.0)
    fast = [
        el for st, el in results if st == 200 and el < within_s
    ]
    if not fast:
        raise AssertionError(
            f"no health probe on n{node + 1} answered within "
            f"{within_s}s during the wedge: {results}"
        )


_VERBS = {
    "fault": _step_fault,
    "clear": _step_clear,
    "put": _step_put,
    "expect_put": _step_expect_put,
    "churn": _step_churn,
    "join": _step_join,
    "get_flood": _step_get_flood,
    "timed_get_flood": _step_timed_get_flood,
    "assert_p99_within": _step_assert_p99_within,
    "mark_data_reads": _step_mark_data_reads,
    "assert_data_reads_flat": _step_assert_data_reads_flat,
    "put_csv": _step_put_csv,
    "select_flood": _step_select_flood,
    "timed_select_flood": _step_timed_select_flood,
    "select_churn": _step_select_churn,
    "make_bucket": _step_make_bucket,
    "enable_replication": _step_enable_replication,
    "await_replication": _step_await_replication,
    "kill": _step_kill,
    "terminate": _step_terminate,
    "restart": _step_restart,
    "wipe_drive": _step_wipe_drive,
    "sleep": _step_sleep,
    "await_breaker": _step_await_breaker,
    "await_heal": _step_await_heal,
    "await_locks_drained": _step_await_locks_drained,
    "wedge_loop": _step_wedge_loop,
    "assert_loops_serving": _step_assert_loops_serving,
    "probe_health_during_wedge": _step_probe_health_during_wedge,
}


# -- invariant sweep -------------------------------------------------------


def check_quorum_reads(ctx: _Ctx) -> int:
    """Every tracked key: all live nodes agree on one acceptable
    payload, or all agree it is absent.  Returns keys verified."""
    live = [n.index for n in ctx.h.nodes if n.alive()]
    if not live:
        raise AssertionError("no live nodes to verify reads against")
    for key, bodies in sorted(ctx.objects.items()):
        answers: "dict[int, tuple]" = {}
        for node in live:
            status, _, body = _get(ctx, node, key)
            answers[node] = (status, body)
        statuses = {s for s, _ in answers.values()}
        if statuses == {404}:
            continue  # cleanly absent everywhere
        if statuses != {200}:
            raise AssertionError(
                f"{key}: split availability across nodes: "
                f"{ {f'n{n + 1}': s for n, (s, _) in answers.items()} }"
            )
        distinct = {body for _, body in answers.values()}
        if len(distinct) != 1:
            raise AssertionError(
                f"{key}: nodes disagree on content "
                f"({len(distinct)} distinct payloads)"
            )
        got = next(iter(distinct))
        if got not in bodies:
            raise AssertionError(
                f"{key}: stored payload matches NO client write "
                f"({len(got)} bytes, {len(bodies)} candidates)"
            )
    return len(ctx.objects)


def check_no_torn_meta(ctx: _Ctx) -> int:
    """Every xl.meta on every drive must decode; torn journals fail.
    Returns files checked."""
    from ..storage.meta import XLMeta

    checked = 0
    for n in ctx.h.nodes:
        for root in n.drive_dirs:
            for p in root.rglob("xl.meta"):
                raw = p.read_bytes()
                try:
                    XLMeta.from_bytes(raw)
                except Exception as e:
                    raise AssertionError(
                        f"torn xl.meta on n{n.index + 1} at "
                        f"{p.relative_to(root)}: {e}"
                    ) from None
                checked += 1
    return checked


def run_scenario(
    sc: Scenario, base_dir, env: "dict | None" = None
) -> dict:
    """Execute one grid cell; returns a small report for assertions
    and logging.  Raises AssertionError on any invariant violation."""
    merged_env = dict(sc.env)
    merged_env.update(env or {})
    h = ClusterHarness(
        base_dir,
        nodes=sc.nodes,
        drives_per_node=sc.drives_per_node,
        env=merged_env,
    )
    with h:
        ctx = _Ctx(h)
        status, _, _ = h.client(0).request("PUT", f"/{BUCKET}")
        if status != 200:
            raise AssertionError(f"make_bucket: HTTP {status}")
        for i in range(sc.seed_objects):
            body = payload(sc.object_size, 7_000 + i)
            st = _put(ctx, i % sc.nodes, f"seed{i}", body)
            if st != 200:
                raise AssertionError(f"seed{i}: HTTP {st}")
        for step in sc.steps:
            verb, args = step[0], step[1:]
            _log.debug(
                "step", extra=kv(scenario=sc.name, verb=verb)
            )
            _VERBS[verb](ctx, *args)
        # safety net: no scenario may leak schedules into the sweep
        for n in h.nodes:
            if n.alive():
                try:
                    h.clear_faults(n.index)
                except RuntimeError as exc:
                    _log.debug(
                        "final fault clear failed",
                        extra=kv(node=n.index, err=str(exc)),
                    )
        report = {
            "scenario": sc.name,
            "objects": 0,
            "meta_files": 0,
            "breaker_events": list(ctx.breaker_log),
        }
        if ctx.errors:
            raise AssertionError(
                f"{sc.name}: workload errors: {ctx.errors[:3]}"
            )
        if sc.check_reads:
            report["objects"] = check_quorum_reads(ctx)
        if sc.check_meta:
            report["meta_files"] = check_no_torn_meta(ctx)
    return report
