"""The chaos grid: one Scenario per failure archetype.

Every cell runs on a 3-node x 2-drive loopback cluster (6-disk erasure
set, data=3 / parity=3, write quorum 4) so ONE fully-degraded node
still leaves both read and write quorum intact - scenarios can assert
availability under faults, not just clean failure.

Fault delivery is always remote: the driver process schedules
FaultDisk rules inside another OS process via the authenticated admin
fault endpoint, exactly how the harness would degrade a node it cannot
reach into.
"""

from __future__ import annotations

from .engine import Fault, Scenario

SEEDS = ("seed0", "seed1", "seed2", "seed3")


# A remote node's drives serve errors on every storage op: reads must
# degrade (5 live disks >= data quorum), writes must still commit
# (4 healthy drives = write quorum), the OBSERVER's breaker for the
# faulted node must trip, and lifting the fault must recover it through
# the half-open probe.
DEAD_REMOTE_DISKS = Scenario(
    name="dead_remote_disks",
    title="dead remote disks: degraded IO + breaker trip/recover",
    steps=(
        ("fault", Fault(node=1, api="*", error=True)),
        ("await_breaker", 0, 1, 2),
        ("put", 0, "during-fault", 30_000, 101),
        ("get_flood", "seed0", 5, 2),
        ("clear", 1),
        ("await_breaker", 0, 1, 0),
        ("put", 0, "after-clear", 30_000, 102),
    ),
)

# One node answers shard reads slowly; a hot-key read storm across all
# nodes must stay bit-identical (hedged reads may race the slow disk,
# but correctness never depends on who wins).
SLOW_REMOTE_DISKS = Scenario(
    name="slow_remote_disks",
    title="slow remote disks: hot reads stay correct under hedging",
    steps=(
        ("fault", Fault(node=1, api="read_at", delay_s=0.2)),
        ("get_flood", "seed1", 8, 4),
        ("clear", 1),
    ),
)

# Shard writes and the metadata-commit rename hang on one node while a
# client PUTs: the write either commits at quorum or fails cleanly -
# the sweep proves no torn xl.meta and no split availability.
PARTITION_MID_PUT = Scenario(
    name="partition_mid_put",
    title="network partition mid-PUT: commit-or-clean, never torn",
    steps=(
        ("fault", Fault(node=1, api="write", hang_s=2.0)),
        ("fault", Fault(node=1, api="rename_file", hang_s=2.0)),
        ("put", 0, "torn-candidate", 60_000, 201),
        ("put", 2, "torn-candidate", 60_000, 202),
        ("clear", 1),
        ("sleep", 0.5),
    ),
)

# Rolling graceful restarts under live write load: every node cycles
# while a writer churns; SIGTERM must drain + unwind dsync grants, so
# after the roll no node holds orphaned lock entries and churned keys
# read back consistent.
ROLLING_RESTART = Scenario(
    name="rolling_restart",
    title="rolling restarts under load: drains, lock unwind, no orphans",
    steps=(
        ("churn", 0, 3, 30, 20_000, 300),
        ("sleep", 0.5),
        ("restart", 1, True),
        ("restart", 2, True),
        ("join",),
        ("await_locks_drained", 0),
        ("await_locks_drained", 1),
        ("await_locks_drained", 2),
    ),
)

# Heal storm racing live writes: a node dies, loses a drive's contents
# (swap), and rejoins while a writer keeps churning - the fresh-disk
# monitor + heal routine must reconstruct every seed shard with no
# manual heal call, without corrupting the racing writes.
HEAL_STORM = Scenario(
    name="heal_storm",
    title="heal storm vs live writes: wiped drive reconverges",
    steps=(
        ("kill", 2),
        ("wipe_drive", 2, 0),
        ("churn", 0, 2, 10, 20_000, 400),
        ("restart", 2, False),
        ("join",),
        ("await_heal", 2, 0, SEEDS),
    ),
)

# Hot-key GET flood across every node with one mildly slow drive set:
# high fan-in reads on a single object stay bit-identical everywhere.
HOT_KEY_FLOOD = Scenario(
    name="hot_key_flood",
    title="hot-key GET flood: fan-in reads bit-identical on all nodes",
    steps=(
        ("fault", Fault(node=2, api="read_at", delay_s=0.05, prob=0.5)),
        ("get_flood", "seed2", 12, 6),
        ("clear", 2),
        ("get_flood", "seed2", 5, 2),
    ),
)

# Hot-key GET flood with the tiered read cache on and one node's
# drives erroring every shard read: after the warm-up floods, every
# GET is a full cache hit, so (a) the data-plane shard-read counter
# must not move AT ALL during the degraded flood — zero disk calls on
# hit — and (b) GET p99 stays flat against the healthy hot baseline
# (1.5x plus an absolute noise floor).  Bit-identity holds throughout:
# the flood compares every body, and the final sweep re-reads every
# object on every node.
HOT_KEY_CACHE_FLOOD = Scenario(
    name="hot_key_cache_flood",
    title="hot-key flood vs tripped disk: cache hits keep p99 flat",
    env=(("MINIO_TPU_READ_CACHE", "host"),),
    steps=(
        ("get_flood", "seed3", 6, 3),  # warm every node's cache
        ("timed_get_flood", "seed3", 20, 4, "healthy_p99"),
        ("mark_data_reads", "flood"),
        ("fault", Fault(node=1, api="read_file_stream", error=True)),
        ("fault", Fault(node=1, api="read_at", error=True)),
        ("timed_get_flood", "seed3", 20, 4, "degraded_p99"),
        ("assert_data_reads_flat", "flood"),
        ("assert_p99_within", "degraded_p99", "healthy_p99", 1.5, 0.15),
        ("clear", 1),
    ),
)

# Replication lag under churn: a catch-all rule replicates the grid
# bucket into a local destination while a writer churns a keyset and
# one node's shard writes stutter.  After the churn joins and the
# fault lifts, the destination must converge to an acceptable payload
# for every churned key — the async queue plus the crawler's
# PENDING/FAILED catch-up, no manual kick.
REPLICATION_LAG_CHURN = Scenario(
    name="replication_lag_churn",
    title="replication lag under churn: destination converges",
    steps=(
        ("make_bucket", 0, "replica"),
        ("enable_replication", 0, "replica"),
        ("fault", Fault(node=2, api="write", delay_s=0.05, prob=0.3)),
        ("churn", 0, 3, 8, 20_000, 500),
        ("join",),
        ("clear", 2),
        ("await_replication", 0, "replica", ("churn0", "churn1", "churn2")),
    ),
)

# Heavy SELECT mix: an analytics scan flood shares the cluster with
# the hot-key GET path.  Every SELECT response — whatever engine the
# node picks (device screen, host vector, row) — must be BIT-IDENTICAL
# to the row engine run locally by the driver over the payload the
# client wrote, including while one node's shard reads error (degraded
# erasure reads feed the scan plane); and GET p99 under concurrent
# scan load stays within 1.5x of the healthy baseline (single-core
# hosts fall back to the engine's coarse starvation-only bound: the
# scan threads time-slice the only CPU with the timed flood).
_SELECT_EXPR = "SELECT s.id, s.name FROM S3Object s WHERE s.qty > 6"

SELECT_HEAVY_MIX = Scenario(
    name="select_heavy_mix",
    title="select flood vs GET mix: bit-identical answers under faults",
    steps=(
        ("put_csv", 0, "table.csv", 4000, 17),
        ("select_flood", "table.csv", _SELECT_EXPR, 3, 2),  # warm engines
        ("timed_get_flood", "seed0", 20, 4, "healthy_p99"),
        ("select_churn", "table.csv", _SELECT_EXPR, 10, 2),
        ("timed_get_flood", "seed0", 20, 4, "mixed_p99"),
        ("join",),
        ("assert_p99_within", "mixed_p99", "healthy_p99", 1.5, 0.15),
        ("fault", Fault(node=1, api="read_file_stream", error=True)),
        ("select_flood", "table.csv", _SELECT_EXPR, 6, 3),
        ("clear", 1),
        ("select_flood", "table.csv", _SELECT_EXPR, 3, 2),
    ),
)

# One event loop of a multi-loop node is artificially wedged (admin
# loops/wedge busy-spins the loop thread, gated on fault injection
# like disk faults): the blast radius must be that loop's shard ONLY.
# The control plane keeps answering on fresh connections while the
# wedge holds (handoff mode round-robins consecutive accepts over
# loops, so probes deterministically reach a healthy loop), the rest
# of the grid serves reads and writes throughout, and once the spin
# releases every loop reports serving again.  The standard sweep then
# proves no request was lost or torn behind the stall.
WEDGED_LOOP = Scenario(
    name="wedged_loop",
    title="wedged event loop: one stalled loop degrades only its shard",
    env=(
        ("MINIO_TPU_SERVER", "async"),
        ("MINIO_TPU_SERVER_LOOPS", "2"),
        ("MINIO_TPU_SERVER_REUSEPORT", "off"),
    ),
    steps=(
        ("assert_loops_serving", 0, 2),
        ("assert_loops_serving", 1, 2),
        ("assert_loops_serving", 2, 2),
        # wedge the non-acceptor loop on n2: accepts keep flowing
        ("wedge_loop", 1, 1, 4.0),
        ("probe_health_during_wedge", 1, 2.5),
        ("get_flood", "seed0", 6, 3),
        ("put", 0, "during-wedge", 30_000, 201),
        ("sleep", 1.0),
        ("assert_loops_serving", 1, 2),
        ("get_flood", "seed1", 3, 2),
    ),
)

GRID = (
    DEAD_REMOTE_DISKS,
    SLOW_REMOTE_DISKS,
    PARTITION_MID_PUT,
    ROLLING_RESTART,
    HEAL_STORM,
    HOT_KEY_FLOOD,
    HOT_KEY_CACHE_FLOOD,
    REPLICATION_LAG_CHURN,
    SELECT_HEAVY_MIX,
    WEDGED_LOOP,
)


def scenario_by_name(name: str) -> Scenario:
    for sc in GRID:
        if sc.name == name:
            return sc
    raise KeyError(name)
