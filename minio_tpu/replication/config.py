"""ReplicationConfiguration document model
(pkg/bucket/replication/replication.go).

Rules select objects by prefix; each rule names a destination bucket
ARN.  The mid-2020 reference replicates to one remote target per
bucket, asynchronously, and repairs missed replications on crawler
passes (data-crawler.go:756 healReplication).
"""

from __future__ import annotations

import dataclasses
import xml.etree.ElementTree as ET

from ..utils.xmlutil import child as _child, child_text as _child_text, strip_ns as _strip_ns

_S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"

# minio-style target ARN: arn:minio:replication:<region>:<id>:<bucket>
ARN_PREFIX = "arn:minio:replication:"


class ReplicationError(Exception):
    pass


@dataclasses.dataclass
class ReplicationRule:
    rule_id: str = ""
    status: str = "Enabled"  # Enabled | Disabled
    prefix: str = ""
    priority: int = 0
    destination_arn: str = ""  # arn:...:bucket or plain bucket name

    @property
    def enabled(self) -> bool:
        return self.status == "Enabled"

    @property
    def target_bucket(self) -> str:
        """Destination bucket from the ARN (or the raw name)."""
        arn = self.destination_arn
        for prefix in (ARN_PREFIX, "arn:aws:s3:::"):
            if arn.startswith(prefix):
                return arn[len(prefix):].rpartition(":")[2]
        return arn

    def matches(self, key: str) -> bool:
        return self.enabled and key.startswith(self.prefix)


@dataclasses.dataclass
class ReplicationConfig:
    role: str = ""
    rules: "list[ReplicationRule]" = dataclasses.field(
        default_factory=list
    )

    @classmethod
    def from_xml(cls, body: bytes) -> "ReplicationConfig":
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise ReplicationError("malformed XML") from None
        if _strip_ns(root.tag) != "ReplicationConfiguration":
            raise ReplicationError("not a ReplicationConfiguration")
        cfg = cls()
        for el in root:
            name = _strip_ns(el.tag)
            if name == "Role":
                cfg.role = (el.text or "").strip()
            elif name == "Rule":
                # direct children only: Rule/Status must not be read
                # from e.g. DeleteMarkerReplication/Status
                status = _child_text(el, "Status") or "Enabled"
                if status not in ("Enabled", "Disabled"):
                    raise ReplicationError(f"invalid Status {status!r}")
                dest_el = _child(el, "Destination")
                dest = (
                    _child_text(dest_el, "Bucket")
                    if dest_el is not None
                    else ""
                )
                if not dest:
                    raise ReplicationError("Rule missing Destination Bucket")
                try:
                    priority = int(_child_text(el, "Priority") or "0")
                except ValueError:
                    raise ReplicationError("bad Priority") from None
                # prefix may be rule-level (legacy) or inside
                # Filter / Filter/And (current schema)
                prefix = _child_text(el, "Prefix")
                if not prefix:
                    f = _child(el, "Filter")
                    if f is not None:
                        prefix = _child_text(f, "Prefix")
                        if not prefix:
                            a = _child(f, "And")
                            if a is not None:
                                prefix = _child_text(a, "Prefix")
                cfg.rules.append(
                    ReplicationRule(
                        rule_id=_child_text(el, "ID"),
                        status=status,
                        prefix=prefix,
                        priority=priority,
                        destination_arn=dest,
                    )
                )
        if not cfg.rules:
            raise ReplicationError("at least one Rule is required")
        return cfg

    def to_xml(self) -> bytes:
        import xml.sax.saxutils as sx

        parts = [
            '<?xml version="1.0" encoding="UTF-8"?>\n',
            f'<ReplicationConfiguration xmlns="{_S3_NS}">',
        ]
        if self.role:
            parts.append(f"<Role>{sx.escape(self.role)}</Role>")
        for r in sorted(self.rules, key=lambda x: -x.priority):
            parts.append(
                "<Rule>"
                + (f"<ID>{sx.escape(r.rule_id)}</ID>" if r.rule_id else "")
                + f"<Status>{r.status}</Status>"
                + f"<Priority>{r.priority}</Priority>"
                + f"<Prefix>{sx.escape(r.prefix)}</Prefix>"
                + "<Destination><Bucket>"
                + sx.escape(r.destination_arn)
                + "</Bucket></Destination></Rule>"
            )
        parts.append("</ReplicationConfiguration>")
        return "".join(parts).encode()

    def rule_for(self, key: str) -> "ReplicationRule | None":
        """Highest-priority enabled rule matching the key
        (replication.Config.FilterActionableRules)."""
        best = None
        for r in self.rules:
            if r.matches(key) and (
                best is None or r.priority > best.priority
            ):
                best = r
        return best
