"""Bucket replication (cmd/bucket-replication.go + pkg/bucket/replication).

``config`` holds the ReplicationConfiguration document model;
``engine`` (see replicate.py) applies it: async replicate-on-write with
crawler catch-up for missed operations.
"""

from .config import ReplicationConfig, ReplicationError, ReplicationRule

__all__ = ["ReplicationConfig", "ReplicationError", "ReplicationRule"]
