"""Async bucket replication engine (cmd/bucket-replication.go +
crawler catch-up at data-crawler.go:756 healReplication).

Objects PUT into a bucket with a replication config are stamped
``x-amz-replication-status: PENDING`` and queued; a worker copies them
to the rule's destination and flips the status to COMPLETED (or FAILED,
which the crawler's catch-up pass re-queues).  Destinations resolve
through a target registry: the destination bucket name maps either to a
bucket on this same cluster (LocalTarget) or to a remote S3 endpoint
(HTTPTarget, SigV4-signed PUTs over the wire).
"""

from __future__ import annotations

import hashlib
import http.client
import queue
import threading
import urllib.parse

from .config import ReplicationConfig, ReplicationError

from ..utils.log import kv, logger

_log = logger("replication")

META_REPLICATION_STATUS = "x-amz-replication-status"

# object metadata that must not be copied onto the destination object
_INTERNAL_META = (
    "etag",
    META_REPLICATION_STATUS,
    "x-internal-compression",
    "x-internal-actual-size",
)


def _clean_meta(meta: dict) -> dict:
    return {
        k: v
        for k, v in meta.items()
        if k not in _INTERNAL_META and not k.startswith("x-internal-sse")
    }


class LocalTarget:
    """Destination bucket on this same cluster (in-cluster tiering)."""

    def __init__(self, object_layer, bucket: str):
        self._ol = object_layer
        self.bucket = bucket

    def put(self, key: str, reader, size: int, metadata: dict) -> None:
        self._ol.get_bucket_info(self.bucket)  # must exist
        self._ol.put_object(
            self.bucket, key, reader, size, _clean_meta(metadata)
        )


class HTTPTarget:
    """Remote S3 endpoint target (bucket-targets.go TargetClient):
    SigV4-signed PUTs straight over http.client - no SDK in-image."""

    def __init__(
        self,
        endpoint: str,
        access_key: str,
        secret_key: str,
        bucket: str,
        region: str = "us-east-1",
        timeout: float = 30.0,
    ):
        parsed = urllib.parse.urlsplit(endpoint)
        self.host = parsed.hostname or ""
        self.tls = parsed.scheme == "https"
        self.port = parsed.port or (443 if self.tls else 80)
        self.access_key = access_key
        self.secret_key = secret_key
        self.bucket = bucket
        self.region = region
        self.timeout = timeout

    def put(self, key: str, reader, size: int, metadata: dict) -> None:
        import datetime

        from ..server import auth as authmod

        path = f"/{self.bucket}/{key}"
        amz_date = datetime.datetime.now(
            datetime.timezone.utc
        ).strftime("%Y%m%dT%H%M%SZ")
        # hash pass over the (seekable) spool, then rewind to send -
        # the object is never held in memory whole
        h = hashlib.sha256()
        while True:
            chunk = reader.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
        phash = h.hexdigest()
        reader.seek(0)
        headers = {
            "host": f"{self.host}:{self.port}",
            "x-amz-date": amz_date,
            "x-amz-content-sha256": phash,
            "content-length": str(size),
        }
        for k, v in _clean_meta(metadata).items():
            if k.startswith("x-amz-meta-") or k == "content-type":
                headers[k] = v
        signed = sorted(k for k in headers if k != "content-length")
        sig = authmod.sign_v4(
            "PUT", path, {}, headers, signed, phash,
            self.access_key, self.secret_key, amz_date, self.region,
        )
        scope = f"{amz_date[:8]}/{self.region}/s3/aws4_request"
        headers["authorization"] = (
            f"{authmod.SIGN_V4_ALGORITHM} "
            f"Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}"
        )
        if self.tls:
            import ssl

            conn = http.client.HTTPSConnection(
                self.host, self.port, timeout=self.timeout,
                context=ssl.create_default_context(),
            )
        else:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout
            )
        try:
            conn.putrequest(
                "PUT", urllib.parse.quote(path),
                skip_host=True, skip_accept_encoding=True,
            )
            for k, v in headers.items():
                conn.putheader(k, v)
            conn.endheaders()
            while True:
                chunk = reader.read(1 << 20)
                if not chunk:
                    break
                conn.send(chunk)
            resp = conn.getresponse()
            resp.read()
            if resp.status not in (200, 204):
                raise OSError(
                    f"replication target HTTP {resp.status}"
                )
        finally:
            conn.close()


class ReplicationPool:
    """Queue + worker copying matched objects to their destinations
    (the replicateObject goroutine pool)."""

    def __init__(self, server, workers: int = 2):
        self.s3 = server
        self._q: "queue.Queue[tuple[str, str, str] | None]" = queue.Queue()
        # bucket -> explicit target (from the admin remote-target
        # registry); default is a LocalTarget on the rule's bucket name
        self.targets: "dict[str, object]" = {}
        # bucket -> (raw_xml, parsed ReplicationConfig)
        self._cfg_cache: "dict[str, tuple[str, object]]" = {}
        self._threads = [
            threading.Thread(
                target=self._work, name=f"replicate-{i}", daemon=True
            )
            for i in range(workers)
        ]
        self._started = False

    def start(self) -> "ReplicationPool":
        if not self._started:
            self._started = True
            for t in self._threads:
                t.start()
        return self

    def stop(self) -> None:
        for _ in self._threads:
            self._q.put(None)

    def drain(self, timeout: float = 30.0) -> None:
        """Testing aid: block until every queued item is PROCESSED
        (queue emptiness alone races the in-flight copy)."""
        t = threading.Thread(target=self._q.join, daemon=True)
        t.start()
        t.join(timeout)

    # -- enqueue side -----------------------------------------------------

    def config_for(self, bucket: str) -> "ReplicationConfig | None":
        try:
            raw = self.s3.bucket_meta.get(bucket).replication_xml
        except Exception:  # noqa: BLE001
            return None
        if not raw:
            return None
        # parse once per document: PUT ingress checks this per request
        cached = self._cfg_cache.get(bucket)
        if cached is not None and cached[0] == raw:
            return cached[1]
        try:
            cfg = ReplicationConfig.from_xml(raw.encode())
        except ReplicationError:
            return None
        self._cfg_cache[bucket] = (raw, cfg)
        return cfg

    def should_replicate(self, bucket: str, key: str) -> bool:
        cfg = self.config_for(bucket)
        return cfg is not None and cfg.rule_for(key) is not None

    def queue(self, bucket: str, key: str, version_id: str = "") -> None:
        if self._started:
            self._q.put((bucket, key, version_id))

    # -- worker side ------------------------------------------------------

    def _target_for(self, bucket: str, rule) -> object:
        t = self.targets.get(bucket)
        if t is not None:
            return t
        # admin-registered remote targets persist in bucket metadata
        import json

        try:
            raw = self.s3.bucket_meta.get(bucket).replication_targets_json
        except Exception:  # noqa: BLE001
            raw = ""
        if raw:
            try:
                docs = json.loads(raw)
            except ValueError:
                docs = []
            match = next(
                (
                    d
                    for d in docs
                    if d.get("target_bucket") == rule.target_bucket
                ),
                docs[0] if docs else None,
            )
            if match is not None:
                return HTTPTarget(
                    match["endpoint"],
                    match["access_key"],
                    match["secret_key"],
                    match.get("target_bucket", rule.target_bucket),
                    match.get("region", "us-east-1"),
                )
        return LocalTarget(self.s3.object_layer, rule.target_bucket)

    def _work(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            bucket, key, version_id = item
            try:
                self._replicate_one(bucket, key, version_id)
            except Exception as e:  # noqa: BLE001 - status stays FAILED
                from ..utils import log

                log.logger("replication").warning(
                    "replicate failed",
                    extra=log.kv(
                        bucket=bucket, key=key,
                        error=f"{type(e).__name__}: {e}",
                    ),
                )
            finally:
                self._q.task_done()

    def _replicate_one(self, bucket, key, version_id) -> None:
        ol = self.s3.object_layer
        cfg = self.config_for(bucket)
        rule = cfg.rule_for(key) if cfg else None
        if rule is None:
            return
        info = ol.get_object_info(bucket, key, version_id)
        status = "COMPLETED"
        try:
            # spool through memory up to 16 MiB, disk beyond - a
            # multi-GB object must not live in worker RAM
            import tempfile

            with tempfile.SpooledTemporaryFile(max_size=16 << 20) as sp:
                ol.get_object(bucket, key, sp, version_id=version_id)
                size = sp.tell()
                sp.seek(0)
                self._target_for(bucket, rule).put(
                    key, sp, size, dict(info.user_defined)
                )
        except Exception:  # noqa: BLE001
            status = "FAILED"
        try:
            ol.update_object_meta(
                bucket, key, {META_REPLICATION_STATUS: status},
                info.version_id,
            )
        except Exception as exc:
            _log.debug("replication status meta update failed", extra=kv(err=str(exc)))

