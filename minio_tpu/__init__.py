"""minio_tpu: a TPU-native S3-compatible distributed object storage framework.

A ground-up re-design of the capabilities of the reference implementation
(MinIO, mounted at /root/reference): S3 API + IAM control plane in Python,
with the byte-crunching data plane - GF(2^8) Reed-Solomon erasure coding,
bitrot hashing - executed on TPU via JAX/Pallas, batched across requests.

Layer map (mirrors SURVEY.md section 1):
  server/       L6-L8: HTTP server, S3/Admin/STS routers, handlers
  iam/          L5: signatures, IAM, policy
  objectlayer/  L3: erasure object layer (objects/sets/zones), FS backend
  codec/        L2: Erasure wrapper, bitrot framing   <- TPU hot path
  ops/          L2: device kernels (RS codec, hashes)
  storage/      L1: StorageAPI, local xl storage, storage REST
  dsync/        L0: distributed quorum locks
  parallel/     device mesh / sharding strategy
"""

__version__ = "0.1.0"
