"""StorageAPI: the disk abstraction (cmd/storage-interface.go:25-79).

Implementations: xl.XLStorage (local POSIX), rest_client.StorageRESTClient
(remote disk over the storage REST plane), and the naughty test double.
The object layer only ever talks to this interface, so local and remote
disks are interchangeable - the seam the reference uses to make a
distributed cluster look like a big JBOD.
"""

from __future__ import annotations

import dataclasses

from .meta import FileInfo


@dataclasses.dataclass
class VolInfo:
    name: str
    created_ns: int


@dataclasses.dataclass
class DiskInfo:
    total: int
    free: int
    used: int
    root_disk: bool
    endpoint: str
    mount_path: str
    disk_id: str
    error: str = ""


@dataclasses.dataclass
class StatInfo:
    size: int
    mod_time_ns: int
    is_dir: bool = False


class ShardWriter:
    """Streaming shard-file writer handle (CreateFile stream)."""

    def write(self, data: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class ShardReader:
    """Random-access shard-file reader handle (ReadFileStream)."""

    # local readers are preferred by the decode path so healthy GETs
    # avoid network RTTs (erasure-decode.go prefer[] semantics)
    is_local = True

    def read_at(self, offset: int, length: int) -> bytes:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class StorageAPI:
    """Abstract disk; all paths are (volume, slash-separated path)."""

    # ---- identity / health ----------------------------------------------
    def is_online(self) -> bool:
        raise NotImplementedError

    def endpoint(self) -> str:
        raise NotImplementedError

    def is_local(self) -> bool:
        raise NotImplementedError

    def disk_info(self) -> DiskInfo:
        raise NotImplementedError

    def get_disk_id(self) -> str:
        raise NotImplementedError

    def set_disk_id(self, disk_id: str) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    # ---- volumes --------------------------------------------------------
    def make_vol(self, volume: str) -> None:
        raise NotImplementedError

    def list_vols(self) -> list[VolInfo]:
        raise NotImplementedError

    def stat_vol(self, volume: str) -> VolInfo:
        raise NotImplementedError

    def delete_vol(self, volume: str, force: bool = False) -> None:
        raise NotImplementedError

    # ---- raw files ------------------------------------------------------
    def list_dir(self, volume: str, dir_path: str, count: int = -1) -> list[str]:
        raise NotImplementedError

    def read_all(self, volume: str, path: str) -> bytes:
        raise NotImplementedError

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        raise NotImplementedError

    def delete_file(self, volume: str, path: str, recursive: bool = False) -> None:
        raise NotImplementedError

    def rename_file(
        self, src_volume: str, src_path: str, dst_volume: str, dst_path: str
    ) -> None:
        raise NotImplementedError

    def stat_file(self, volume: str, path: str) -> StatInfo:
        raise NotImplementedError

    # ---- shard streams --------------------------------------------------
    def create_file(self, volume: str, path: str) -> ShardWriter:
        raise NotImplementedError

    def append_file(
        self,
        volume: str,
        path: str,
        data: bytes,
        truncate: bool = False,
        offset: "int | None" = None,
    ) -> None:
        """Append a chunk to a shard file (the storage REST plane's
        bounded-memory CreateFile stream; truncate=True on the first
        chunk creates/resets the file).  ``offset`` declares where the
        chunk starts, making retried appends idempotent (the file is
        truncated back to it before writing)."""
        raise NotImplementedError

    def walk_sorted(
        self,
        volume: str,
        prefix: str = "",
        marker: str = "",
        recursive: bool = True,
        inclusive: bool = False,
    ):
        """Yield (name, is_prefix) lazily in lexical order, pruning
        subtrees outside prefix/after marker (tree-walk.go)."""
        raise NotImplementedError

    def read_file_stream(self, volume: str, path: str) -> ShardReader:
        raise NotImplementedError

    # ---- object metadata (xl.meta journal) ------------------------------
    def read_version(
        self, volume: str, path: str, version_id: str = ""
    ) -> FileInfo:
        raise NotImplementedError

    def read_xl(self, volume: str, path: str):
        raise NotImplementedError

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        raise NotImplementedError

    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        raise NotImplementedError

    def delete_version(
        self, volume: str, path: str, fi: FileInfo
    ) -> None:
        raise NotImplementedError

    def rename_data(
        self,
        src_volume: str,
        src_path: str,
        fi: FileInfo,
        dst_volume: str,
        dst_path: str,
    ) -> None:
        """Atomically move a staged object dir into place and commit its
        xl.meta version (the RenameData crash-consistency point,
        xl-storage.go:2000)."""
        raise NotImplementedError

    # ---- maintenance ----------------------------------------------------
    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        """Deep bitrot scan of all shard blocks (VerifyFile,
        xl-storage.go:2369); raises errors.FileCorrupt on damage."""
        raise NotImplementedError

    def walk(self, volume: str, prefix: str = ""):
        """Yield object paths (those having xl.meta) under prefix."""
        raise NotImplementedError
