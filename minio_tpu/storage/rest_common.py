"""Storage REST plane: wire protocol shared by server and client.

The reference exposes every StorageAPI method at
/minio/storage/v20/<method> (storage-rest-common.go:20-54) as HTTP POSTs
with query args + streaming bodies.  Same shape here under
/minio-tpu/storage/v1/, with msgpack payloads and a typed-error envelope
so client-side exceptions match local disks exactly.
"""

from __future__ import annotations

import msgpack

from . import errors

PREFIX = "/minio-tpu/storage/v1"

# error class name <-> exception type (travels as the X-Storage-Error
# header / error payload; reduceErrs needs real types on the client)
_ERRORS = {
    cls.__name__: cls
    for cls in (
        errors.DiskNotFound,
        errors.VolumeNotFound,
        errors.VolumeExists,
        errors.VolumeNotEmpty,
        errors.FileNotFound,
        errors.VersionNotFound,
        errors.FileAccessDenied,
        errors.FileCorrupt,
        errors.DiskFull,
        errors.IsNotRegular,
        errors.UnformattedDisk,
        errors.CorruptedFormat,
        errors.InconsistentDisk,
        errors.FaultyDisk,
    )
}


def encode_error(e: Exception) -> tuple[str, str]:
    name = type(e).__name__
    if name not in _ERRORS:
        name = "FaultyDisk"
    return name, str(e)


def decode_error(name: str, message: str) -> Exception:
    return _ERRORS.get(name, errors.FaultyDisk)(message)


def pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(raw: bytes):
    return msgpack.unpackb(raw, raw=False)


def fileinfo_to_wire(fi) -> dict:
    from .meta import FileInfo

    d = fi.to_dict()
    d["volume"] = fi.volume
    d["name"] = fi.name
    return d


def fileinfo_from_wire(d: dict):
    from .meta import FileInfo

    volume = d.pop("volume", "")
    name = d.pop("name", "")
    return FileInfo.from_dict(d, volume, name)
