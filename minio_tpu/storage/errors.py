"""Storage error model (the typed errors of cmd/storage-errors.go).

The object layer's quorum logic counts these per-disk error types
(reduceErrs semantics in cmd/erasure-metadata-utils.go), so they are real
exception classes rather than errno checks.
"""

from __future__ import annotations


class StorageError(Exception):
    pass


class DiskNotFound(StorageError):
    """errDiskNotFound: disk offline/unreachable."""


class VolumeNotFound(StorageError):
    """errVolumeNotFound."""


class VolumeExists(StorageError):
    """errVolumeExists."""


class VolumeNotEmpty(StorageError):
    """errVolumeNotEmpty."""


class FileNotFound(StorageError):
    """errFileNotFound."""


class VersionNotFound(StorageError):
    """errFileVersionNotFound."""


class FileAccessDenied(StorageError):
    """errFileAccessDenied."""


class FileCorrupt(StorageError):
    """errFileCorrupt: bitrot or truncated metadata."""


class DiskFull(StorageError):
    """errDiskFull."""


class IsNotRegular(StorageError):
    """errIsNotRegular: path is a directory where a file was expected."""


class UnformattedDisk(StorageError):
    """errUnformattedDisk: format.json missing (fresh disk)."""


class CorruptedFormat(StorageError):
    """errCorruptedFormat."""


class InconsistentDisk(StorageError):
    """errInconsistentDisk: disk ID mismatch (swapped drive)."""


class FaultyDisk(StorageError):
    """errFaultyDisk: unexpected I/O error."""
