"""Storage REST server: exposes local disks to peers (storage-rest-server.go).

Mounted inside the node's single HTTP listener (like registerDistErasure-
Routers, routers.go:25-38): requests under /minio-tpu/storage/v1/ carry an
internode JWT and name a local disk by its endpoint path.  Method handlers
are thin translations onto the local XLStorage instances.
"""

from __future__ import annotations

import urllib.parse

from ..utils import jwt
from . import rest_common as wire
from .api import ShardReader, ShardWriter


class StorageRESTServer:
    """Dispatches storage-plane requests for a set of local disks."""

    def __init__(self, disks: list, secret: str):
        # key disks by their root path (the 'disk' query arg)
        self._disks = {d.root: d for d in disks}
        self._secret = secret

    def guard_disks(self, guarded: dict) -> None:
        """Swap served disks for their DiskIDCheck wrappers once the
        format is known (peer I/O must not bypass the per-op identity
        validation; code-review r4).  ``guarded`` maps root -> wrapper."""
        for root, wrapper in guarded.items():
            if root in self._disks:
                self._disks[root] = wrapper

    def authenticate(self, headers: dict) -> None:
        authz = headers.get("authorization", "")
        if not authz.startswith("Bearer "):
            raise jwt.JWTError("missing bearer token")
        jwt.verify(authz[len("Bearer "):], self._secret)

    def _preamble(self, query: dict, headers: "dict | None"):
        """Shared auth + disk-lookup front half for both dispatch
        paths.  Returns (disk, q, error_response_or_None)."""
        try:
            self.authenticate(
                {k.lower(): v for k, v in (headers or {}).items()}
            )
        except Exception as e:  # noqa: BLE001
            name, msg = wire.encode_error(e)
            return None, {}, (
                401, wire.pack({"error": name, "message": msg}), {}
            )
        q = {k: v[0] for k, v in query.items()}
        disk = self._disks.get(q.get("disk", ""))
        if disk is None:
            from .errors import DiskNotFound

            name, msg = wire.encode_error(DiskNotFound(q.get("disk", "")))
            return None, q, (
                400, wire.pack({"error": name, "message": msg}), {}
            )
        return disk, q, None

    def handle(
        self,
        method_name: str,
        query: dict,
        body: bytes,
        headers: "dict | None" = None,
    ) -> tuple[int, bytes, dict]:
        """Returns (status, body, headers).  Errors use a typed envelope.

        Authentication happens HERE, on the dispatch path, so no wiring
        can mount the storage plane unauthenticated (advisor finding r1).
        """
        disk, q, err = self._preamble(query, headers)
        if err is not None:
            return err
        try:
            out = self._dispatch(disk, method_name, q, body)
            return 200, out, {}
        except Exception as e:  # noqa: BLE001 - typed envelope
            name, msg = wire.encode_error(e)
            return 400, wire.pack({"error": name, "message": msg}), {}

    def handle_stream(
        self,
        method_name: str,
        query: dict,
        reader,
        headers: "dict | None" = None,
    ) -> tuple[int, bytes, dict]:
        """Streaming-body dispatch (chunked TE): CreateFile shard bytes
        flow straight from the socket into the disk writer in bounded
        chunks - neither side holds a whole shard
        (storage-rest-server.go CreateFileHandler)."""
        disk, q, err = self._preamble(query, headers)
        if err is not None:
            return err
        if method_name != "createfile":
            return 400, wire.pack(
                {"error": "ValueError", "message": "not streamable"}
            ), {}
        try:
            w = disk.create_file(q.get("vol", ""), q.get("path", ""))
            try:
                while True:
                    chunk = reader.read(1 << 20)
                    if not chunk:
                        break
                    w.write(chunk)
            finally:
                w.close()
            return 200, b"", {}
        except Exception as e:  # noqa: BLE001
            name, msg = wire.encode_error(e)
            return 400, wire.pack({"error": name, "message": msg}), {}

    def _dispatch(self, disk, m: str, q: dict, body: bytes) -> bytes:
        vol = q.get("vol", "")
        path = q.get("path", "")
        if m == "diskinfo":
            info = disk.disk_info()
            return wire.pack(info.__dict__)
        if m == "getdiskid":
            return wire.pack(disk.get_disk_id())
        if m == "setdiskid":
            disk.set_disk_id(wire.unpack(body))
            return b""
        if m == "makevol":
            disk.make_vol(vol)
            return b""
        if m == "listvols":
            return wire.pack(
                [[v.name, v.created_ns] for v in disk.list_vols()]
            )
        if m == "statvol":
            v = disk.stat_vol(vol)
            return wire.pack([v.name, v.created_ns])
        if m == "deletevol":
            disk.delete_vol(vol, force=q.get("force") == "1")
            return b""
        if m == "listdir":
            return wire.pack(
                disk.list_dir(vol, path, int(q.get("count", -1)))
            )
        if m == "readall":
            return disk.read_all(vol, path)
        if m == "writeall":
            disk.write_all(vol, path, body)
            return b""
        if m == "deletefile":
            disk.delete_file(vol, path, recursive=q.get("recursive") == "1")
            return b""
        if m == "renamefile":
            disk.rename_file(vol, path, q["dstvol"], q["dstpath"])
            return b""
        if m == "statfile":
            st = disk.stat_file(vol, path)
            return wire.pack([st.size, st.mod_time_ns, st.is_dir])
        if m == "appendfile":
            disk.append_file(
                vol,
                path,
                body,
                truncate=q.get("truncate") == "1",
                offset=int(q["off"]) if "off" in q else None,
            )
            return b""
        if m == "createfile":
            # whole shard body in one request (streamed chunked client-side)
            w = disk.create_file(vol, path)
            try:
                w.write(body)
            finally:
                w.close()
            return b""
        if m == "readfilestream":
            r = disk.read_file_stream(vol, path)
            try:
                return r.read_at(
                    int(q.get("offset", 0)), int(q.get("length", -1))
                )
            finally:
                r.close()
        if m == "readversion":
            fi = disk.read_version(vol, path, q.get("versionid", ""))
            return wire.pack(wire.fileinfo_to_wire(fi))
        if m == "readxl":
            xl = disk.read_xl(vol, path)
            return wire.pack(
                [wire.fileinfo_to_wire(v) for v in xl.versions]
            )
        if m == "writemetadata":
            disk.write_metadata(
                vol, path, wire.fileinfo_from_wire(wire.unpack(body))
            )
            return b""
        if m == "updatemetadata":
            disk.update_metadata(
                vol, path, wire.fileinfo_from_wire(wire.unpack(body))
            )
            return b""
        if m == "deleteversion":
            disk.delete_version(
                vol, path, wire.fileinfo_from_wire(wire.unpack(body))
            )
            return b""
        if m == "renamedata":
            disk.rename_data(
                vol,
                path,
                wire.fileinfo_from_wire(wire.unpack(body)),
                q["dstvol"],
                q["dstpath"],
            )
            return b""
        if m == "verifyfile":
            disk.verify_file(
                vol, path, wire.fileinfo_from_wire(wire.unpack(body))
            )
            return b""
        if m == "walk":
            return wire.pack(list(disk.walk(vol, path)))
        if m == "walksorted":
            # bounded batch of the ordered walk; the client re-requests
            # with an advanced marker (tree-walk continuation)
            count = int(q.get("count", 1000))
            out = []
            it = disk.walk_sorted(
                vol,
                q.get("prefix", ""),
                q.get("marker", ""),
                recursive=q.get("recursive", "1") == "1",
                inclusive=q.get("inclusive") == "1",
            )
            for name, is_prefix in it:
                out.append([name, is_prefix])
                if len(out) >= count:
                    break
            return wire.pack(out)
        raise ValueError(f"unknown storage method {m!r}")
