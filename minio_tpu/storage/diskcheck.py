"""Per-operation disk-ID validation (cmd/xl-storage-disk-id-check.go).

Wraps any StorageAPI so every I/O first confirms the drive still holds
the format document this slot was admitted with:

- format.json unreadable -> the drive was wiped/replaced with an empty
  one: ops fail DiskNotFound until the fresh-disk monitor re-stamps and
  heals it (heal/background.py FreshDiskMonitor);
- disk uuid mismatch -> a DIFFERENT formatted drive was mounted into
  this slot (cabling/mount mixups): ops fail immediately instead of
  scribbling one cluster's shards onto another's drive.

The on-disk read is rate-limited (default 1s); in between, ops pass
straight through.  Reconnect notes: remote disks already lazily
re-probe (storage/rest_client.py is_online backoff), and local disks
report offline while their root dir is missing - together with the
fresh-disk monitor this covers the reference's connectDisks loop
(erasure-sets.go:200-295) without a dedicated thread.
"""

from __future__ import annotations

import threading
import time

from . import errors


class DiskIDCheck:
    """StorageAPI decorator validating the slot's disk identity."""

    # every method that touches the drive contents
    _CHECKED = frozenset(
        {
            "make_vol", "list_vols", "stat_vol", "delete_vol",
            "list_dir", "read_all", "write_all", "delete_file",
            "rename_file", "stat_file", "create_file", "append_file",
            "walk", "walk_sorted", "read_file_stream", "read_version",
            "read_xl", "write_metadata", "update_metadata",
            "delete_version", "rename_data", "verify_file",
        }
    )

    def __init__(self, disk, expected_id: str, check_interval_s: float = 1.0):
        self.unwrapped = disk
        self._expected = expected_id
        self._interval = check_interval_s
        self._mu = threading.Lock()
        self._last_check = 0.0
        self._last_err: "Exception | None" = None

    def _check(self) -> None:
        now = time.monotonic()
        with self._mu:
            if now - self._last_check < self._interval:
                if self._last_err is not None:
                    raise self._last_err
                return
            self._last_check = now
            err: "Exception | None" = None
            try:
                from ..objectlayer.format import read_format

                fmt = read_format(self.unwrapped)
            except Exception:  # noqa: BLE001
                err = errors.DiskNotFound(
                    "unformatted or unreadable disk (awaiting heal)"
                )
            else:
                if fmt is None:
                    err = errors.DiskNotFound(
                        "unformatted disk (awaiting heal)"
                    )
                elif fmt.this != self._expected:
                    err = errors.DiskNotFound(
                        f"disk ID mismatch: expected {self._expected}, "
                        f"found {fmt.this} - wrong drive mounted?"
                    )
            self._last_err = err
            if err is not None:
                raise err

    def is_online(self) -> bool:
        if not self.unwrapped.is_online():
            return False
        try:
            self._check()
        except Exception:  # noqa: BLE001
            return False
        return True

    def __getattr__(self, name: str):
        attr = getattr(self.unwrapped, name)
        if name in self._CHECKED and callable(attr):
            def wrapped(*a, **k):
                self._check()
                return attr(*a, **k)

            wrapped.__name__ = name
            return wrapped
        return attr
