"""Remote StorageAPI over the storage REST plane
(cmd/storage-rest-client.go:671, cmd/rest/client.go).

Every method is one HTTP POST to the peer's
``/minio-tpu/storage/v1/<method>`` with query args and a msgpack or raw
body, authenticated by a short-lived internode JWT.  Typed errors travel
in a msgpack envelope and are re-raised as the same exception classes a
local disk raises, so quorum accounting (reduce_errs) cannot tell local
and remote disks apart.

Connection failures mark the disk offline; is_online() re-probes after a
backoff, mirroring the lazy reconnect of storage-rest-client.go:677.
"""

from __future__ import annotations

import http.client
import threading
import time
import urllib.parse

from ..utils import jwt
from . import rest_common as wire
from .api import (
    DiskInfo,
    ShardReader,
    ShardWriter,
    StatInfo,
    StorageAPI,
    VolInfo,
)
from .errors import DiskNotFound
from .meta import FileInfo, XLMeta

_RECONNECT_S = 3.0  # defaultRetryUnit-ish probe backoff
_TOKEN_TTL_S = 900
_WRITE_BUF = 4 << 20  # shard bytes buffered before an appendfile POST


class RemoteShardWriter(ShardWriter):
    """Buffers shard bytes and appends them to the remote file in
    bounded flushes (the CreateFile streaming POST analogue)."""

    def __init__(self, client: "StorageRESTClient", volume: str, path: str):
        self._c = client
        self._vol = volume
        self._path = path
        self._buf = bytearray()
        self._first = True
        self._off = 0  # bytes acknowledged by the server

    def _flush(self) -> None:
        # the declared offset makes a retried flush idempotent: the
        # server truncates back to `off` before appending, so a lost
        # response cannot duplicate shard bytes (advisor finding r2)
        q = {"vol": self._vol, "path": self._path, "off": str(self._off)}
        if self._first:
            q["truncate"] = "1"
            self._first = False
        self._c._call("appendfile", q, bytes(self._buf))
        self._off += len(self._buf)
        del self._buf[:]

    def write(self, data: bytes) -> None:
        self._buf += data
        if len(self._buf) >= _WRITE_BUF:
            self._flush()

    def close(self) -> None:
        if self._buf or self._first:
            self._flush()


class RemoteShardReader(ShardReader):
    def __init__(self, client: "StorageRESTClient", volume: str, path: str):
        self._c = client
        self._vol = volume
        self._path = path
        # fail fast like the local open() does
        self._c._call(
            "statfile", {"vol": volume, "path": path}
        )

    def read_at(self, offset: int, length: int) -> bytes:
        return self._c._call(
            "readfilestream",
            {
                "vol": self._vol,
                "path": self._path,
                "offset": str(offset),
                "length": str(length),
            },
        )

    def close(self) -> None:
        pass


class StorageRESTClient(StorageAPI):
    """StorageAPI for one remote drive served by a peer node."""

    def __init__(
        self,
        host: str,
        port: int,
        disk_path: str,
        secret: str,
        access_key: str = "minio-tpu-node",
        timeout: float = 30.0,
    ):
        self.host = host
        self.port = port
        self.disk_path = disk_path
        self.root = disk_path  # REST server keys disks by root path
        self._secret = secret
        self._access_key = access_key
        self._timeout = timeout
        self._endpoint = f"http://{host}:{port}{disk_path}"
        self._local = threading.local()
        self._token = ""
        self._token_exp = 0.0
        self._online = True
        self._last_probe = 0.0
        self._disk_id = ""

    # ---- transport ------------------------------------------------------

    def _bearer(self) -> str:
        now = time.time()
        if now > self._token_exp - 60:
            self._token = jwt.sign(
                {"sub": self._access_key}, self._secret, _TOKEN_TTL_S
            )
            self._token_exp = now + _TOKEN_TTL_S
        return self._token

    def _conn(self) -> http.client.HTTPConnection:
        c = getattr(self._local, "conn", None)
        if c is None:
            c = http.client.HTTPConnection(
                self.host, self.port, timeout=self._timeout
            )
            self._local.conn = c
        return c

    def _drop_conn(self) -> None:
        c = getattr(self._local, "conn", None)
        if c is not None:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
            self._local.conn = None

    def _call(
        self, method: str, q: "dict | None" = None, body: bytes = b""
    ) -> bytes:
        if not self._online and not self._should_probe():
            raise DiskNotFound(f"{self._endpoint} offline")
        query = {"disk": self.disk_path}
        query.update(q or {})
        url = f"{wire.PREFIX}/{method}?" + urllib.parse.urlencode(query)
        headers = {
            "Authorization": f"Bearer {self._bearer()}",
            "Content-Length": str(len(body)),
        }
        for attempt in (0, 1):
            conn = self._conn()
            try:
                conn.request("POST", url, body=body or None, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
                break
            except (OSError, http.client.HTTPException):
                # one retry on a fresh connection (stale keep-alive)
                self._drop_conn()
                if attempt:
                    self._online = False
                    self._last_probe = time.time()
                    raise DiskNotFound(
                        f"{self._endpoint} unreachable"
                    ) from None
        self._online = True
        if resp.status == 200:
            return payload
        if resp.status in (400, 401):
            try:
                env = wire.unpack(payload)
                raise wire.decode_error(env["error"], env["message"])
            except (ValueError, KeyError, TypeError):
                raise DiskNotFound(
                    f"{self._endpoint}: bad error envelope"
                ) from None
        raise DiskNotFound(f"{self._endpoint}: HTTP {resp.status}")

    def _should_probe(self) -> bool:
        if time.time() - self._last_probe >= _RECONNECT_S:
            self._online = True  # optimistic; next _call settles it
            return True
        return False

    # ---- identity / health ----------------------------------------------

    def is_online(self) -> bool:
        if self._online:
            return True
        if not self._should_probe():
            return False
        try:
            self._call("diskinfo")
            return True
        except Exception:  # noqa: BLE001
            return False

    def endpoint(self) -> str:
        return self._endpoint

    def is_local(self) -> bool:
        return False

    def disk_info(self) -> DiskInfo:
        d = wire.unpack(self._call("diskinfo"))
        return DiskInfo(**d)

    def get_disk_id(self) -> str:
        return wire.unpack(self._call("getdiskid"))

    def set_disk_id(self, disk_id: str) -> None:
        self._disk_id = disk_id
        self._call("setdiskid", body=wire.pack(disk_id))

    def close(self) -> None:
        self._drop_conn()

    # ---- volumes --------------------------------------------------------

    def make_vol(self, volume: str) -> None:
        self._call("makevol", {"vol": volume})

    def list_vols(self) -> list[VolInfo]:
        return [
            VolInfo(n, c)
            for n, c in wire.unpack(self._call("listvols"))
        ]

    def stat_vol(self, volume: str) -> VolInfo:
        n, c = wire.unpack(self._call("statvol", {"vol": volume}))
        return VolInfo(n, c)

    def delete_vol(self, volume: str, force: bool = False) -> None:
        self._call(
            "deletevol", {"vol": volume, "force": "1" if force else "0"}
        )

    # ---- raw files ------------------------------------------------------

    def list_dir(self, volume: str, dir_path: str, count: int = -1) -> list[str]:
        return wire.unpack(
            self._call(
                "listdir",
                {"vol": volume, "path": dir_path, "count": str(count)},
            )
        )

    def read_all(self, volume: str, path: str) -> bytes:
        return self._call("readall", {"vol": volume, "path": path})

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        self._call("writeall", {"vol": volume, "path": path}, data)

    def delete_file(self, volume: str, path: str, recursive: bool = False) -> None:
        self._call(
            "deletefile",
            {
                "vol": volume,
                "path": path,
                "recursive": "1" if recursive else "0",
            },
        )

    def rename_file(
        self, src_volume: str, src_path: str, dst_volume: str, dst_path: str
    ) -> None:
        self._call(
            "renamefile",
            {
                "vol": src_volume,
                "path": src_path,
                "dstvol": dst_volume,
                "dstpath": dst_path,
            },
        )

    def stat_file(self, volume: str, path: str) -> StatInfo:
        size, mt, is_dir = wire.unpack(
            self._call("statfile", {"vol": volume, "path": path})
        )
        return StatInfo(size, mt, is_dir)

    # ---- shard streams --------------------------------------------------

    def create_file(self, volume: str, path: str) -> ShardWriter:
        return RemoteShardWriter(self, volume, path)

    def read_file_stream(self, volume: str, path: str) -> ShardReader:
        return RemoteShardReader(self, volume, path)

    # ---- object metadata ------------------------------------------------

    def read_version(
        self, volume: str, path: str, version_id: str = ""
    ) -> FileInfo:
        raw = self._call(
            "readversion",
            {"vol": volume, "path": path, "versionid": version_id},
        )
        return wire.fileinfo_from_wire(wire.unpack(raw))

    def read_xl(self, volume: str, path: str) -> XLMeta:
        raw = self._call("readxl", {"vol": volume, "path": path})
        xl = XLMeta()
        for d in wire.unpack(raw):
            xl.versions.append(wire.fileinfo_from_wire(d))
        return xl

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call(
            "writemetadata",
            {"vol": volume, "path": path},
            wire.pack(wire.fileinfo_to_wire(fi)),
        )

    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call(
            "updatemetadata",
            {"vol": volume, "path": path},
            wire.pack(wire.fileinfo_to_wire(fi)),
        )

    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call(
            "deleteversion",
            {"vol": volume, "path": path},
            wire.pack(wire.fileinfo_to_wire(fi)),
        )

    def rename_data(
        self,
        src_volume: str,
        src_path: str,
        fi: FileInfo,
        dst_volume: str,
        dst_path: str,
    ) -> None:
        self._call(
            "renamedata",
            {
                "vol": src_volume,
                "path": src_path,
                "dstvol": dst_volume,
                "dstpath": dst_path,
            },
            wire.pack(wire.fileinfo_to_wire(fi)),
        )

    # ---- maintenance ----------------------------------------------------

    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call(
            "verifyfile",
            {"vol": volume, "path": path},
            wire.pack(wire.fileinfo_to_wire(fi)),
        )

    def walk(self, volume: str, prefix: str = ""):
        yield from wire.unpack(
            self._call("walk", {"vol": volume, "path": prefix})
        )

    def walk_sorted(
        self,
        volume: str,
        prefix: str = "",
        marker: str = "",
        recursive: bool = True,
        inclusive: bool = False,
        batch: int = 1000,
    ):
        """Ordered walk over the wire: bounded batches, marker-advanced
        continuation (the remote half of tree-walk)."""
        while True:
            rows = wire.unpack(
                self._call(
                    "walksorted",
                    {
                        "vol": volume,
                        "prefix": prefix,
                        "marker": marker,
                        "recursive": "1" if recursive else "0",
                        "inclusive": "1" if inclusive else "0",
                        "count": str(batch),
                    },
                )
            )
            for name, is_prefix in rows:
                yield (name, is_prefix)
            if len(rows) < batch:
                return
            marker = rows[-1][0]
            inclusive = False  # continuation is strictly after marker
