"""Remote StorageAPI over the storage REST plane
(cmd/storage-rest-client.go:671, cmd/rest/client.go).

Every method is one HTTP POST to the peer's
``/minio-tpu/storage/v1/<method>`` with query args and a msgpack or raw
body, authenticated by a short-lived internode JWT.  Typed errors travel
in a msgpack envelope and are re-raised as the same exception classes a
local disk raises, so quorum accounting (reduce_errs) cannot tell local
and remote disks apart.

Connection failures mark the disk offline; is_online() re-probes after a
backoff, mirroring the lazy reconnect of storage-rest-client.go:677.
"""

from __future__ import annotations

import http.client
import random
import threading
import time
import urllib.parse

from ..utils import jwt
from . import rest_common as wire
from .api import (
    DiskInfo,
    ShardReader,
    ShardWriter,
    StatInfo,
    StorageAPI,
    VolInfo,
)
from .errors import DiskNotFound
from .meta import FileInfo, XLMeta

from ..utils.log import kv, logger

_log = logger("storage")

_RECONNECT_S = 3.0  # defaultRetryUnit-ish probe backoff
_TOKEN_TTL_S = 900


class RemoteShardWriter(ShardWriter):
    """One streaming chunked POST per shard file: write() feeds a
    bounded StreamPipe drained by a sender thread, so shard bytes flow
    to the peer as they are produced - no per-shard buffering and no
    per-flush round trips (storage-rest-client.go CreateFile)."""

    def __init__(self, client: "StorageRESTClient", volume: str, path: str):
        from ..utils.pipe import StreamPipe

        self._c = client
        # respect the shared offline tracking: a dead peer fast-fails
        # instead of stalling a socket timeout per shard stream
        if not client._online and not client._should_probe():
            raise DiskNotFound(f"{client._endpoint} offline")
        self._pipe = StreamPipe(depth=8)
        self._err: "Exception | None" = None
        q = {"disk": client.disk_path, "vol": volume, "path": path}
        self._url = (
            f"{wire.PREFIX}/createfile?" + urllib.parse.urlencode(q)
        )
        self._thread = threading.Thread(
            target=self._run, name="shard-stream", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        from ..utils import tlsconf

        conn = None
        try:
            conn = tlsconf.client_connection(
                self._c.host, self._c.port, self._c._timeout
            )
            conn.putrequest("POST", self._url)
            conn.putheader("Authorization", f"Bearer {self._c._bearer()}")
            conn.putheader("Transfer-Encoding", "chunked")
            conn.endheaders()
            while True:
                chunk = self._pipe.read(1 << 20)
                if not chunk:
                    break
                conn.send(f"{len(chunk):x}\r\n".encode())
                conn.send(chunk)
                conn.send(b"\r\n")
            conn.send(b"0\r\n\r\n")
            resp = conn.getresponse()
            payload = resp.read()
            self._c._online = True
            if resp.status != 200:
                try:
                    env = wire.unpack(payload)
                    self._err = wire.decode_error(
                        env["error"], env["message"]
                    )
                except Exception:  # noqa: BLE001
                    self._err = OSError(
                        f"createfile: HTTP {resp.status}"
                    )
        except Exception as e:  # noqa: BLE001
            self._err = e if isinstance(e, OSError) else OSError(str(e))
            # transport failure: mark the disk offline like _call does
            self._c._online = False
            self._c._last_probe = time.time()
        finally:
            if self._err is not None:
                # unblock a producer stuck on the full pipe
                self._pipe.close_read()
            if conn is not None:
                try:
                    conn.close()
                except Exception as exc:
                    _log.debug("storage REST connection close failed", extra=kv(err=str(exc)))

    def _raise_err(self) -> None:
        # shard-writer callers tolerate OSError (quorum accounting);
        # wrap typed server errors so they are not silently fatal
        e = self._err or OSError("shard stream failed")
        if isinstance(e, OSError):
            raise e
        raise OSError(f"{type(e).__name__}: {e}") from e

    def write(self, data: bytes) -> None:
        from ..utils.pipe import PipeClosed

        try:
            self._pipe.write(data)
        except PipeClosed:
            self._raise_err()

    def close(self) -> None:
        self._pipe.close_write()
        self._thread.join(timeout=self._c._timeout + 5)
        if self._thread.is_alive():
            # the server never acknowledged the stream: reporting
            # success here would commit an unconfirmed shard
            self._err = self._err or OSError(
                "createfile response timed out"
            )
        if self._err is not None:
            self._raise_err()


class RemoteShardReader(ShardReader):
    is_local = False

    def __init__(self, client: "StorageRESTClient", volume: str, path: str):
        self._c = client
        self._vol = volume
        self._path = path
        # fail fast like the local open() does
        self._c._call(
            "statfile", {"vol": volume, "path": path}
        )

    def read_at(self, offset: int, length: int) -> bytes:
        return self._c._call(
            "readfilestream",
            {
                "vol": self._vol,
                "path": self._path,
                "offset": str(offset),
                "length": str(length),
            },
        )

    def close(self) -> None:
        pass


class StorageRESTClient(StorageAPI):
    """StorageAPI for one remote drive served by a peer node."""

    def __init__(
        self,
        host: str,
        port: int,
        disk_path: str,
        secret: str,
        access_key: str = "minio-tpu-node",
        timeout: float = 30.0,
    ):
        self.host = host
        self.port = port
        self.disk_path = disk_path
        self.root = disk_path  # REST server keys disks by root path
        self._secret = secret
        self._access_key = access_key
        self._timeout = timeout
        self._endpoint = f"http://{host}:{port}{disk_path}"
        self._local = threading.local()
        self._token = ""
        self._token_exp = 0.0
        self._online = True
        self._last_probe = 0.0
        self._disk_id = ""
        # storage-op deadlines self-tune from observed durations, the
        # same adaptation the namespace locks use (dynamic-timeouts.go
        # applied to storage RPCs, not just locking).  One budget PER
        # OPERATION CLASS: cheap metadata ops must not shrink the
        # deadline under a large shard stream (the reference keeps
        # separate dynamic timeouts for the same reason)
        from ..utils.dyntimeout import DynamicTimeout

        self._dyn_meta = DynamicTimeout(timeout, max(1.0, timeout / 10))
        self._dyn_bulk = DynamicTimeout(timeout, max(5.0, timeout / 4))

    # data-bearing RPCs whose duration scales with payload/namespace
    _BULK_METHODS = frozenset(
        {
            "createfile", "appendfile", "readfilestream", "readall",
            "writeall", "walk", "listdir", "deletevol", "renamefile",
        }
    )

    # ---- transport ------------------------------------------------------

    def _bearer(self) -> str:
        now = time.time()
        if now > self._token_exp - 60:
            self._token = jwt.sign(
                {"sub": self._access_key}, self._secret, _TOKEN_TTL_S
            )
            self._token_exp = now + _TOKEN_TTL_S
        return self._token

    def _conn(self) -> http.client.HTTPConnection:
        c = getattr(self._local, "conn", None)
        if c is None:
            from ..utils import tlsconf

            c = tlsconf.client_connection(
                self.host, self.port, self._timeout
            )
            self._local.conn = c
        return c

    def _drop_conn(self) -> None:
        c = getattr(self._local, "conn", None)
        if c is not None:
            try:
                c.close()
            except Exception as exc:
                _log.debug("storage REST connection close failed", extra=kv(err=str(exc)))
            self._local.conn = None

    def _call(
        self, method: str, q: "dict | None" = None, body: bytes = b""
    ) -> bytes:
        if not self._online and not self._should_probe():
            raise DiskNotFound(f"{self._endpoint} offline")
        query = {"disk": self.disk_path}
        query.update(q or {})
        url = f"{wire.PREFIX}/{method}?" + urllib.parse.urlencode(query)
        headers = {
            "Authorization": f"Bearer {self._bearer()}",
            "Content-Length": str(len(body)),
        }
        dyn = (
            self._dyn_bulk
            if method in self._BULK_METHODS
            else self._dyn_meta
        )
        op_deadline = dyn.timeout
        t0 = time.monotonic()
        for attempt in (0, 1):
            conn = self._conn()
            conn.timeout = op_deadline
            if getattr(conn, "sock", None) is not None:
                conn.sock.settimeout(op_deadline)
            try:
                conn.request("POST", url, body=body or None, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
                break
            except TimeoutError:
                # the adaptive deadline fired: grow the budget
                dyn.log_failure()
                self._drop_conn()
                if attempt:
                    self._online = False
                    self._last_probe = time.time()
                    raise DiskNotFound(
                        f"{self._endpoint} timed out"
                    ) from None
            except (OSError, http.client.HTTPException) as e:
                # one retry on a fresh connection (stale keep-alive)
                self._drop_conn()
                if attempt:
                    self._online = False
                    self._last_probe = time.time()
                    raise DiskNotFound(
                        f"{self._endpoint} unreachable"
                    ) from None
                if isinstance(
                    e,
                    (
                        ConnectionRefusedError,
                        ConnectionResetError,
                        BrokenPipeError,
                    ),
                ):
                    # refused/reset is the peer-restart signature: a
                    # jittered backoff before the single retry bridges
                    # the listener-rebind window instead of surfacing a
                    # transient DiskNotFound to the quorum path
                    time.sleep(0.05 + random.random() * 0.15)
        dyn.log_success(time.monotonic() - t0)
        self._online = True
        if resp.status == 200:
            return payload
        if resp.status in (400, 401):
            try:
                env = wire.unpack(payload)
                raise wire.decode_error(env["error"], env["message"])
            except (ValueError, KeyError, TypeError):
                raise DiskNotFound(
                    f"{self._endpoint}: bad error envelope"
                ) from None
        raise DiskNotFound(f"{self._endpoint}: HTTP {resp.status}")

    def _should_probe(self) -> bool:
        if time.time() - self._last_probe >= _RECONNECT_S:
            self._online = True  # optimistic; next _call settles it
            return True
        return False

    # ---- identity / health ----------------------------------------------

    def is_online(self) -> bool:
        if self._online:
            return True
        if not self._should_probe():
            return False
        try:
            self._call("diskinfo")
            return True
        except Exception:  # noqa: BLE001
            return False

    def endpoint(self) -> str:
        return self._endpoint

    def is_local(self) -> bool:
        return False

    def disk_info(self) -> DiskInfo:
        d = wire.unpack(self._call("diskinfo"))
        return DiskInfo(**d)

    def get_disk_id(self) -> str:
        return wire.unpack(self._call("getdiskid"))

    def set_disk_id(self, disk_id: str) -> None:
        self._disk_id = disk_id
        self._call("setdiskid", body=wire.pack(disk_id))

    def close(self) -> None:
        self._drop_conn()

    # ---- volumes --------------------------------------------------------

    def make_vol(self, volume: str) -> None:
        self._call("makevol", {"vol": volume})

    def list_vols(self) -> list[VolInfo]:
        return [
            VolInfo(n, c)
            for n, c in wire.unpack(self._call("listvols"))
        ]

    def stat_vol(self, volume: str) -> VolInfo:
        n, c = wire.unpack(self._call("statvol", {"vol": volume}))
        return VolInfo(n, c)

    def delete_vol(self, volume: str, force: bool = False) -> None:
        self._call(
            "deletevol", {"vol": volume, "force": "1" if force else "0"}
        )

    # ---- raw files ------------------------------------------------------

    def list_dir(self, volume: str, dir_path: str, count: int = -1) -> list[str]:
        return wire.unpack(
            self._call(
                "listdir",
                {"vol": volume, "path": dir_path, "count": str(count)},
            )
        )

    def read_all(self, volume: str, path: str) -> bytes:
        return self._call("readall", {"vol": volume, "path": path})

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        self._call("writeall", {"vol": volume, "path": path}, data)

    def delete_file(self, volume: str, path: str, recursive: bool = False) -> None:
        self._call(
            "deletefile",
            {
                "vol": volume,
                "path": path,
                "recursive": "1" if recursive else "0",
            },
        )

    def rename_file(
        self, src_volume: str, src_path: str, dst_volume: str, dst_path: str
    ) -> None:
        self._call(
            "renamefile",
            {
                "vol": src_volume,
                "path": src_path,
                "dstvol": dst_volume,
                "dstpath": dst_path,
            },
        )

    def stat_file(self, volume: str, path: str) -> StatInfo:
        size, mt, is_dir = wire.unpack(
            self._call("statfile", {"vol": volume, "path": path})
        )
        return StatInfo(size, mt, is_dir)

    # ---- shard streams --------------------------------------------------

    def create_file(self, volume: str, path: str) -> ShardWriter:
        return RemoteShardWriter(self, volume, path)

    def read_file_stream(self, volume: str, path: str) -> ShardReader:
        return RemoteShardReader(self, volume, path)

    # ---- object metadata ------------------------------------------------

    def read_version(
        self, volume: str, path: str, version_id: str = ""
    ) -> FileInfo:
        raw = self._call(
            "readversion",
            {"vol": volume, "path": path, "versionid": version_id},
        )
        return wire.fileinfo_from_wire(wire.unpack(raw))

    def read_xl(self, volume: str, path: str) -> XLMeta:
        raw = self._call("readxl", {"vol": volume, "path": path})
        xl = XLMeta()
        for d in wire.unpack(raw):
            xl.versions.append(wire.fileinfo_from_wire(d))
        return xl

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call(
            "writemetadata",
            {"vol": volume, "path": path},
            wire.pack(wire.fileinfo_to_wire(fi)),
        )

    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call(
            "updatemetadata",
            {"vol": volume, "path": path},
            wire.pack(wire.fileinfo_to_wire(fi)),
        )

    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call(
            "deleteversion",
            {"vol": volume, "path": path},
            wire.pack(wire.fileinfo_to_wire(fi)),
        )

    def rename_data(
        self,
        src_volume: str,
        src_path: str,
        fi: FileInfo,
        dst_volume: str,
        dst_path: str,
    ) -> None:
        self._call(
            "renamedata",
            {
                "vol": src_volume,
                "path": src_path,
                "dstvol": dst_volume,
                "dstpath": dst_path,
            },
            wire.pack(wire.fileinfo_to_wire(fi)),
        )

    # ---- maintenance ----------------------------------------------------

    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call(
            "verifyfile",
            {"vol": volume, "path": path},
            wire.pack(wire.fileinfo_to_wire(fi)),
        )

    def walk(self, volume: str, prefix: str = ""):
        yield from wire.unpack(
            self._call("walk", {"vol": volume, "path": prefix})
        )

    def walk_sorted(
        self,
        volume: str,
        prefix: str = "",
        marker: str = "",
        recursive: bool = True,
        inclusive: bool = False,
        batch: int = 1000,
    ):
        """Ordered walk over the wire: bounded batches, marker-advanced
        continuation (the remote half of tree-walk)."""
        while True:
            rows = wire.unpack(
                self._call(
                    "walksorted",
                    {
                        "vol": volume,
                        "prefix": prefix,
                        "marker": marker,
                        "recursive": "1" if recursive else "0",
                        "inclusive": "1" if inclusive else "0",
                        "count": str(batch),
                    },
                )
            )
            for name, is_prefix in rows:
                yield (name, is_prefix)
            if len(rows) < batch:
                return
            marker = rows[-1][0]
            inclusive = False  # continuation is strictly after marker
