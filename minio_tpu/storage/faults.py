"""Deterministic fault injection for the StorageAPI wrap chain.

``FaultDisk`` decorates any StorageAPI and executes programmable fault
schedules — delays, errors, corruption, hangs — keyed by API name, with
a seeded RNG so a chaos scenario replays byte-for-byte identically.  It
composes under the standard stack::

    DiskIDCheck(MeteredDisk(FaultDisk(XLStorage(...))))

so injected latency and errors flow through the *real* metering ledger
and circuit breaker exactly as a degraded drive's would — the chaos
suite (tests/test_chaos.py) is exercising the production path, not a
mock of it.

Schedule DSL::

    fd = FaultDisk(raw, seed=7)
    fd.inject("read_at", delay_s=0.05)              # every stream read
    fd.inject("*", error=True, calls=[3, 4])        # 3rd+4th call/API
    fd.inject("read_at", corrupt=True, prob=0.25)   # seeded coin flip
    fd.inject("stat_file", hang_s=30.0)             # parks until clear()
    fd.clear()                                      # lift everything

* ``api`` is a disk API name (``DiskIDCheck._CHECKED``), the
  stream-level ``"read_at"`` / ``"write"`` (shards move through
  ``read_file_stream``/``create_file`` handles, not API calls), or
  ``"*"`` for every disk API.
* ``calls`` filters on the per-API 1-based call number; ``prob`` draws
  from the seeded RNG (both evaluated under the schedule lock so the
  replay is deterministic; sleeps and raises happen OUTSIDE it).
* ``error`` raises ``serrors.FaultyDisk``; ``corrupt`` flips one
  seeded-random byte of the payload; ``hang_s`` parks the call on an
  event that ``clear()`` releases early, so wedged-disk tests tear
  down fast.

All locks come from the module-global ``threading`` so the MTPU3xx
lock-order auditor can swap in its audited primitives.
"""

from __future__ import annotations

import random
import threading
import time

from ..utils.log import kv, logger
from . import errors as serrors
from .diskcheck import DiskIDCheck

_log = logger("faults")

# stream-level ops: applied by the _FaultStream wrappers, not __getattr__
_STREAM_OPS = ("read_at", "write")


class FaultDisk:
    """StorageAPI decorator executing a deterministic fault schedule."""

    _FAULTED = DiskIDCheck._CHECKED

    def __init__(self, disk, seed: int = 0):
        self.unwrapped = disk
        self._mu = threading.Lock()
        self._rng = random.Random(seed)
        self._rules: "list[dict]" = []
        self._calls: "dict[str, int]" = {}
        # injected-action tally: kind -> count (test assertions)
        self._injected: "dict[str, int]" = {}
        self._release = threading.Event()  # set by clear(): ends hangs

    # -- schedule DSL -----------------------------------------------------

    def inject(
        self,
        api: str,
        delay_s: float = 0.0,
        hang_s: float = 0.0,
        error: bool = False,
        corrupt: bool = False,
        prob: float = 1.0,
        calls: "list[int] | None" = None,
    ) -> "FaultDisk":
        """Add one schedule rule (chainable)."""
        with self._mu:
            self._rules.append(
                {
                    "api": api,
                    "delay_s": float(delay_s),
                    "hang_s": float(hang_s),
                    "error": bool(error),
                    "corrupt": bool(corrupt),
                    "prob": float(prob),
                    "calls": None if calls is None else set(calls),
                }
            )
        return self

    def clear(self) -> None:
        """Lift every rule, release parked hangs, reset call counters."""
        with self._mu:
            self._rules = []
            self._calls.clear()
        self._release.set()
        self._release = threading.Event()
        _log.debug(
            "fault schedule cleared",
            extra=kv(disk=str(getattr(self.unwrapped, "root", "?"))),
        )

    def injected(self) -> "dict[str, int]":
        """Tally of executed fault actions: kind -> count."""
        with self._mu:
            return dict(self._injected)

    def rule_count(self) -> int:
        """Number of active schedule rules (admin fault/status)."""
        with self._mu:
            return len(self._rules)

    # -- schedule execution -----------------------------------------------

    def _plan(self, api: str) -> "dict | None":
        """Decide this call's fate under the lock (counter + RNG draws
        stay deterministic); the caller executes it lock-free."""
        with self._mu:
            if not self._rules:
                return None
            n = self._calls.get(api, 0) + 1
            self._calls[api] = n
            plan = None
            for rule in self._rules:
                if rule["api"] != api and not (
                    rule["api"] == "*" and api not in _STREAM_OPS
                ):
                    continue
                if rule["calls"] is not None and n not in rule["calls"]:
                    continue
                if rule["prob"] < 1.0 and self._rng.random() > rule["prob"]:
                    continue
                if plan is None:
                    plan = {
                        "delay_s": 0.0,
                        "hang_s": 0.0,
                        "error": False,
                        "corrupt": False,
                        "byte": 0,
                    }
                plan["delay_s"] += rule["delay_s"]
                plan["hang_s"] = max(plan["hang_s"], rule["hang_s"])
                plan["error"] = plan["error"] or rule["error"]
                plan["corrupt"] = plan["corrupt"] or rule["corrupt"]
            if plan is not None and plan["corrupt"]:
                plan["byte"] = self._rng.randrange(1 << 30)
            if plan is not None:
                release = self._release
                for kind in ("delay_s", "hang_s"):
                    if plan[kind] > 0:
                        self._injected[kind[:-2]] = (
                            self._injected.get(kind[:-2], 0) + 1
                        )
                for kind in ("error", "corrupt"):
                    if plan[kind]:
                        self._injected[kind] = (
                            self._injected.get(kind, 0) + 1
                        )
                plan["release"] = release
            return plan

    def _pre(self, api: str) -> "dict | None":
        """Run the blocking/raising half of the plan; return the rest."""
        plan = self._plan(api)
        if plan is None:
            return None
        if plan["delay_s"] > 0:
            time.sleep(plan["delay_s"])
        if plan["hang_s"] > 0:
            # parks until the schedule is cleared or the hang expires —
            # a wedged disk, but one the test harness can always free
            plan["release"].wait(plan["hang_s"])
        if plan["error"]:
            raise serrors.FaultyDisk(f"injected fault: {api}")
        return plan

    @staticmethod
    def _maybe_corrupt(plan: "dict | None", data):
        if plan is None or not plan["corrupt"] or not data:
            return data
        buf = bytearray(data)
        idx = plan["byte"] % len(buf)
        buf[idx] ^= 0xFF
        return bytes(buf)

    # -- StorageAPI surface -----------------------------------------------

    def read_file_stream(self, volume: str, path: str):
        self._pre("read_file_stream")
        return _FaultReader(
            self.unwrapped.read_file_stream(volume, path), self
        )

    def create_file(self, volume: str, path: str):
        self._pre("create_file")
        return _FaultWriter(
            self.unwrapped.create_file(volume, path), self
        )

    def __getattr__(self, name: str):
        attr = getattr(self.unwrapped, name)
        if name in self._FAULTED and callable(attr):
            def wrapped(*a, **k):
                plan = self._pre(name)
                result = attr(*a, **k)
                if isinstance(result, bytes):
                    result = self._maybe_corrupt(plan, result)
                return result

            wrapped.__name__ = name
            self.__dict__[name] = wrapped
            return wrapped
        return attr


class _FaultReader:
    """ShardReader wrapper applying the disk's ``read_at`` schedule."""

    def __init__(self, inner, disk: FaultDisk):
        self._inner = inner
        self._disk = disk
        self.is_local = getattr(inner, "is_local", True)

    def read_at(self, offset: int, length: int) -> bytes:
        plan = self._disk._pre("read_at")
        data = self._inner.read_at(offset, length)
        return self._disk._maybe_corrupt(plan, data)

    def close(self) -> None:
        self._inner.close()


class _FaultWriter:
    """ShardWriter wrapper applying the disk's ``write`` schedule."""

    def __init__(self, inner, disk: FaultDisk):
        self._inner = inner
        self._disk = disk

    def write(self, data: bytes) -> None:
        plan = self._disk._pre("write")
        self._inner.write(self._disk._maybe_corrupt(plan, data))

    def close(self) -> None:
        self._inner.close()


def find_fault_disk(disk) -> "FaultDisk | None":
    """The FaultDisk inside a wrap chain, if any (tests reach through
    the metered/ID-check layers to adjust schedules mid-scenario)."""
    d = disk
    while d is not None:
        if isinstance(d, FaultDisk):
            return d
        d = d.__dict__.get("unwrapped") if hasattr(d, "__dict__") else None
    return None
