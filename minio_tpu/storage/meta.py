"""xl.meta: the per-object metadata journal (xl-storage-format-v2 analogue).

Every object directory holds one ``xl.meta`` file: a magic header plus a
msgpack document containing a *version journal* - an array of version
entries (objects and delete markers), newest first, exactly the shape of
xlMetaV2 (reference cmd/xl-storage-format-v2.go:140-228).  Each erasure
shard set member writes its own xl.meta differing only in
``erasure.index`` (which shard this disk holds), mirroring how the
reference stamps ErasureInfo.Index per disk.

Layout on disk (xl-storage-format-v2.go:71-83):

    <bucket>/<object>/xl.meta
    <bucket>/<object>/<data_dir-uuid>/part.1 ...
"""

from __future__ import annotations

import dataclasses
import time
import uuid

import msgpack

XL_MAGIC = b"XLT1"  # this framework's format magic + version
NULL_VERSION_ID = "null"


@dataclasses.dataclass
class ErasureInfo:
    """Per-object erasure geometry (ErasureInfo, xl-storage-format-v1.go)."""

    algorithm: str = "rs-vandermonde"
    data_blocks: int = 0
    parity_blocks: int = 0
    block_size: int = 0
    index: int = 0  # 1-based shard index this disk holds
    distribution: list[int] = dataclasses.field(default_factory=list)
    checksum_algo: str = "phash256"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ErasureInfo":
        return cls(**d)


@dataclasses.dataclass
class ObjectPartInfo:
    """One multipart part (ObjectPartInfo, erasure-metadata.go)."""

    number: int
    size: int  # stored (possibly compressed/encrypted) size
    actual_size: int  # original client payload size

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ObjectPartInfo":
        return cls(**d)


@dataclasses.dataclass
class FileInfo:
    """One object version's metadata (FileInfo, cmd/storage-datatypes.go).

    The unit the object layer reads/writes through StorageAPI
    ReadVersion/WriteMetadata and runs quorum over
    (findFileInfoInQuorum, cmd/erasure-metadata.go:215).
    """

    volume: str = ""
    name: str = ""
    version_id: str = ""
    is_latest: bool = True
    deleted: bool = False  # delete marker
    data_dir: str = ""
    size: int = 0
    mod_time_ns: int = 0
    metadata: dict = dataclasses.field(default_factory=dict)
    parts: list[ObjectPartInfo] = dataclasses.field(default_factory=list)
    erasure: ErasureInfo = dataclasses.field(default_factory=ErasureInfo)

    @property
    def mod_time(self) -> float:
        return self.mod_time_ns / 1e9

    def to_dict(self) -> dict:
        return {
            "version_id": self.version_id,
            "deleted": self.deleted,
            "data_dir": self.data_dir,
            "size": self.size,
            "mod_time_ns": self.mod_time_ns,
            "metadata": self.metadata,
            "parts": [p.to_dict() for p in self.parts],
            "erasure": self.erasure.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict, volume="", name="") -> "FileInfo":
        return cls(
            volume=volume,
            name=name,
            version_id=d.get("version_id", ""),
            deleted=d.get("deleted", False),
            data_dir=d.get("data_dir", ""),
            size=d.get("size", 0),
            mod_time_ns=d.get("mod_time_ns", 0),
            metadata=dict(d.get("metadata", {})),
            parts=[ObjectPartInfo.from_dict(p) for p in d.get("parts", [])],
            erasure=ErasureInfo.from_dict(
                d.get("erasure", ErasureInfo().to_dict())
            ),
        )


def new_version_id() -> str:
    return str(uuid.uuid4())


def now_ns() -> int:
    return time.time_ns()


class XLMeta:
    """The version journal held by one xl.meta file."""

    def __init__(self, versions: "list[FileInfo] | None" = None):
        self.versions: list[FileInfo] = versions or []

    # ---- journal ops (xlMetaV2 AddVersion/DeleteVersion semantics) ------

    def add_version(self, fi: FileInfo) -> None:
        """Insert/replace a version, newest kept first."""
        vid = fi.version_id or NULL_VERSION_ID
        self.versions = [
            v
            for v in self.versions
            if (v.version_id or NULL_VERSION_ID) != vid
        ]
        self.versions.insert(0, fi)
        self.versions.sort(key=lambda v: -v.mod_time_ns)

    def delete_version(self, version_id: str) -> FileInfo:
        vid = version_id or NULL_VERSION_ID
        for i, v in enumerate(self.versions):
            if (v.version_id or NULL_VERSION_ID) == vid:
                return self.versions.pop(i)
        from . import errors

        raise errors.VersionNotFound(version_id)

    def latest(self) -> FileInfo:
        from . import errors

        if not self.versions:
            raise errors.FileNotFound("no versions")
        return self.versions[0]

    def find(self, version_id: str) -> FileInfo:
        if not version_id:
            return self.latest()
        from . import errors

        for v in self.versions:
            if (v.version_id or NULL_VERSION_ID) == (
                version_id or NULL_VERSION_ID
            ):
                return v
        raise errors.VersionNotFound(version_id)

    # ---- serialization --------------------------------------------------

    def to_bytes(self) -> bytes:
        doc = {"versions": [v.to_dict() for v in self.versions]}
        return XL_MAGIC + msgpack.packb(doc, use_bin_type=True)

    @classmethod
    def from_bytes(cls, raw: bytes, volume="", name="") -> "XLMeta":
        from . import errors

        if len(raw) < len(XL_MAGIC) or raw[: len(XL_MAGIC)] != XL_MAGIC:
            raise errors.FileCorrupt("bad xl.meta magic")
        try:
            doc = msgpack.unpackb(raw[len(XL_MAGIC) :], raw=False)
            versions = [
                FileInfo.from_dict(d, volume, name)
                for d in doc.get("versions", [])
            ]
        except Exception as e:
            raise errors.FileCorrupt(f"xl.meta decode: {e}") from e
        return cls(versions)
