"""Per-disk storage API instrumentation (cmd/xl-storage-disk-id-check.go).

``MeteredDisk`` wraps any StorageAPI and records, per API endpoint:
call counts, error counts, and cumulative latency.  The reference keeps
the same ledger in its disk-ID-check decorator (storageMetrics /
getMetrics); here metering is its own layer so it composes with
``DiskIDCheck`` explicitly.

Stacking order matters: ``DiskIDCheck(MeteredDisk(xl))``.  The heal
subsystem reaches the RAW disk via one ``getattr(disk, "unwrapped")``
hop to probe/re-stamp unformatted drives (heal/background.py); with
metering innermost that hop lands on the MeteredDisk, whose
passthrough still reaches the drive while identity checks stay
outermost.  ``wrap()`` is idempotent and walks existing wrapper chains
so construction sites and the object layer can both call it safely.

Exported as ``miniotpu_disk_api_{calls,errors,seconds}_total`` with
``disk``/``api`` labels (server/metrics.py) and folded into
``admin healthinfo`` drive entries (server/admin.py).

The ledger also keeps a streaming p50/p99 per API (``P2Quantile`` — the
Jain & Chlamtac P² estimator, five markers, no sample buffer), and every
observation is forwarded to the disk's ``storage/health.py`` circuit
breaker, which is what turns latency ledgers into hedge deadlines and
trip decisions on the GET path.
"""

from __future__ import annotations

import threading
import time

from . import errors as serrors
from .diskcheck import DiskIDCheck

# Errors that are answers, not faults: a disk that promptly says "no
# such object" is healthy.  Only genuine faults (I/O errors, corrupt
# formats, timeouts, unexpected exceptions) feed the circuit breaker's
# consecutive-error ladder.
_BENIGN_ERRORS = (
    serrors.FileNotFound,
    serrors.VersionNotFound,
    serrors.VolumeNotFound,
    serrors.VolumeExists,
    serrors.VolumeNotEmpty,
    serrors.IsNotRegular,
    FileNotFoundError,
)


class P2Quantile:
    """Streaming quantile estimator (Jain & Chlamtac's P² algorithm).

    Five markers track the running q-quantile in O(1) memory — no
    sample buffer, so a disk that serves millions of reads costs the
    same 5 floats as one that served fifty.  Not thread-safe; callers
    hold their own lock (MeteredDisk._stats_mu / DiskHealth._mu).
    """

    __slots__ = ("q", "count", "_h", "_pos", "_want", "_inc")

    def __init__(self, q: float):
        self.q = float(q)
        self.count = 0
        self._h: "list[float]" = []  # first 5 raw samples, then heights
        self._pos = [0.0, 1.0, 2.0, 3.0, 4.0]
        self._want = [0.0, 2 * q, 4 * q, 2 + 2 * q, 4.0]
        self._inc = [0.0, q / 2, q, (1 + q) / 2, 1.0]

    def observe(self, x: float) -> None:
        self.count += 1
        h = self._h
        if self.count <= 5:
            h.append(float(x))
            if self.count == 5:
                h.sort()
            return
        # locate cell k such that h[k] <= x < h[k+1], clamping extremes
        if x < h[0]:
            h[0] = float(x)
            k = 0
        elif x >= h[4]:
            h[4] = float(x)
            k = 3
        else:
            k = 0
            while k < 3 and not (h[k] <= x < h[k + 1]):
                k += 1
        pos, want = self._pos, self._want
        for i in range(k + 1, 5):
            pos[i] += 1.0
        for i in range(5):
            want[i] += self._inc[i]
        # nudge interior markers toward their desired positions
        for i in (1, 2, 3):
            d = want[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1.0) or (
                d <= -1.0 and pos[i - 1] - pos[i] < -1.0
            ):
                s = 1.0 if d > 0 else -1.0
                cand = self._parabolic(i, s)
                if not (h[i - 1] < cand < h[i + 1]):
                    cand = self._linear(i, s)
                h[i] = cand
                pos[i] += s

    def _parabolic(self, i: int, s: float) -> float:
        h, n = self._h, self._pos
        return h[i] + s / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + s)
            * (h[i + 1] - h[i])
            / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - s)
            * (h[i] - h[i - 1])
            / (n[i] - n[i - 1])
        )

    def _linear(self, i: int, s: float) -> float:
        h, n = self._h, self._pos
        j = i + int(s)
        return h[i] + s * (h[j] - h[i]) / (n[j] - n[i])

    def value(self) -> "float | None":
        """Current estimate; None before the first observation.

        Below 5 samples the markers aren't live yet — fall back to the
        nearest-rank quantile of the few raw samples so early readers
        (hedge-deadline warmup) get a sane number, not None.
        """
        if self.count == 0:
            return None
        if self.count >= 5:
            return self._h[2]
        xs = sorted(self._h)
        idx = min(len(xs) - 1, int(self.q * len(xs)))
        return xs[idx]


class MeteredDisk:
    """StorageAPI decorator keeping a per-API call/error/latency ledger."""

    # the drive-touching surface (DiskIDCheck._CHECKED, same contract)
    _METERED = DiskIDCheck._CHECKED

    def __init__(self, disk):
        self.unwrapped = disk
        self._stats_mu = threading.Lock()
        # api -> [calls, errors, seconds]
        self._stats: "dict[str, list]" = {}
        # api -> (P2Quantile(p50), P2Quantile(p99)); successful calls only
        self._quantiles: "dict[str, tuple]" = {}
        self._health_cache: "tuple | None" = None

    @property
    def health(self):
        """This disk's circuit breaker (storage/health.py DiskHealth).

        Resolved through the live registry and re-fetched when tests
        swap it out with ``health.reset_registry()`` — a cached breaker
        from a dead registry would silently divorce the ledger from the
        GET path's skip/hedge decisions.  Lazy import: health.py imports
        P2Quantile from this module.
        """
        from . import health as _health

        reg = _health.registry()
        cached = self._health_cache
        if cached is not None and cached[0] is reg:
            return cached[1]
        dh = reg.get_disk(self.metered_endpoint())
        self._health_cache = (reg, dh)
        return dh

    def metered_endpoint(self) -> str:
        """Stable disk label for exported series."""
        try:
            return str(self.unwrapped.endpoint())
        except Exception:  # noqa: BLE001
            return str(getattr(self.unwrapped, "root", "?"))

    def api_stats(self) -> "dict[str, dict]":
        """Ledger snapshot: api -> {calls, errors, seconds, p50, p99}."""
        with self._stats_mu:
            out = {}
            for api, (calls, errors, secs) in self._stats.items():
                row = {
                    "calls": calls,
                    "errors": errors,
                    "seconds": round(secs, 6),
                }
                qs = self._quantiles.get(api)
                if qs is not None:
                    p50, p99 = qs[0].value(), qs[1].value()
                    if p50 is not None:
                        row["p50_seconds"] = round(p50, 6)
                    if p99 is not None:
                        row["p99_seconds"] = round(p99, 6)
                out[api] = row
            return out

    def api_p99(self, api: str) -> "float | None":
        """Live p99 seconds for one API (None before any success)."""
        with self._stats_mu:
            qs = self._quantiles.get(api)
            return qs[1].value() if qs is not None else None

    def _record(
        self, api: str, seconds: float, exc: "BaseException | None"
    ) -> None:
        with self._stats_mu:
            row = self._stats.setdefault(api, [0, 0, 0.0])
            row[0] += 1
            if exc is not None:
                row[1] += 1
            row[2] += seconds
            if exc is None:
                qs = self._quantiles.get(api)
                if qs is None:
                    qs = (P2Quantile(0.50), P2Quantile(0.99))
                    self._quantiles[api] = qs
                qs[0].observe(seconds)
                qs[1].observe(seconds)
        # breaker notification happens OUTSIDE _stats_mu: DiskHealth has
        # its own lock and must never nest inside the ledger's.  Benign
        # "no such thing" answers count as successes — the disk did its
        # job; only genuine faults climb the consecutive-error ladder.
        self.health.record_api(
            api,
            seconds,
            ok=exc is None or isinstance(exc, _BENIGN_ERRORS),
        )

    def __getattr__(self, name: str):
        attr = getattr(self.unwrapped, name)
        if name in self._METERED and callable(attr):
            def wrapped(*a, **k):
                t0 = time.monotonic()
                try:
                    result = attr(*a, **k)
                except BaseException as e:
                    self._record(name, time.monotonic() - t0, e)
                    raise
                self._record(name, time.monotonic() - t0, None)
                return result

            wrapped.__name__ = name
            # cache the bound wrapper: __getattr__ only fires on miss,
            # so the hot path pays the timing closure, not the lookup
            self.__dict__[name] = wrapped
            return wrapped
        return attr


def is_metered(disk) -> bool:
    """True if a MeteredDisk sits anywhere in the wrapper chain.

    Walks ``unwrapped`` links via ``__dict__`` lookups only - going
    through ``getattr`` would trip the wrappers' own ``__getattr__``
    forwarding on the innermost (raw) disk.
    """
    d = disk
    while d is not None:
        if isinstance(d, MeteredDisk):
            return True
        d = d.__dict__.get("unwrapped") if hasattr(d, "__dict__") else None
    return False


def wrap(disk):
    """Meter a disk unless it (or an inner layer) already is; None-safe."""
    if disk is None or is_metered(disk):
        return disk
    return MeteredDisk(disk)
