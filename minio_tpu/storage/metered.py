"""Per-disk storage API instrumentation (cmd/xl-storage-disk-id-check.go).

``MeteredDisk`` wraps any StorageAPI and records, per API endpoint:
call counts, error counts, and cumulative latency.  The reference keeps
the same ledger in its disk-ID-check decorator (storageMetrics /
getMetrics); here metering is its own layer so it composes with
``DiskIDCheck`` explicitly.

Stacking order matters: ``DiskIDCheck(MeteredDisk(xl))``.  The heal
subsystem reaches the RAW disk via one ``getattr(disk, "unwrapped")``
hop to probe/re-stamp unformatted drives (heal/background.py); with
metering innermost that hop lands on the MeteredDisk, whose
passthrough still reaches the drive while identity checks stay
outermost.  ``wrap()`` is idempotent and walks existing wrapper chains
so construction sites and the object layer can both call it safely.

Exported as ``miniotpu_disk_api_{calls,errors,seconds}_total`` with
``disk``/``api`` labels (server/metrics.py) and folded into
``admin healthinfo`` drive entries (server/admin.py).
"""

from __future__ import annotations

import threading
import time

from .diskcheck import DiskIDCheck


class MeteredDisk:
    """StorageAPI decorator keeping a per-API call/error/latency ledger."""

    # the drive-touching surface (DiskIDCheck._CHECKED, same contract)
    _METERED = DiskIDCheck._CHECKED

    def __init__(self, disk):
        self.unwrapped = disk
        self._stats_mu = threading.Lock()
        # api -> [calls, errors, seconds]
        self._stats: "dict[str, list]" = {}

    def metered_endpoint(self) -> str:
        """Stable disk label for exported series."""
        try:
            return str(self.unwrapped.endpoint())
        except Exception:  # noqa: BLE001
            return str(getattr(self.unwrapped, "root", "?"))

    def api_stats(self) -> "dict[str, dict]":
        """Ledger snapshot: api -> {calls, errors, seconds}."""
        with self._stats_mu:
            return {
                api: {
                    "calls": calls,
                    "errors": errors,
                    "seconds": round(secs, 6),
                }
                for api, (calls, errors, secs) in self._stats.items()
            }

    def _record(self, api: str, seconds: float, failed: bool) -> None:
        with self._stats_mu:
            row = self._stats.setdefault(api, [0, 0, 0.0])
            row[0] += 1
            if failed:
                row[1] += 1
            row[2] += seconds

    def __getattr__(self, name: str):
        attr = getattr(self.unwrapped, name)
        if name in self._METERED and callable(attr):
            def wrapped(*a, **k):
                t0 = time.monotonic()
                ok = False
                try:
                    result = attr(*a, **k)
                    ok = True
                    return result
                finally:
                    self._record(name, time.monotonic() - t0, not ok)

            wrapped.__name__ = name
            # cache the bound wrapper: __getattr__ only fires on miss,
            # so the hot path pays the timing closure, not the lookup
            self.__dict__[name] = wrapped
            return wrapped
        return attr


def is_metered(disk) -> bool:
    """True if a MeteredDisk sits anywhere in the wrapper chain.

    Walks ``unwrapped`` links via ``__dict__`` lookups only - going
    through ``getattr`` would trip the wrappers' own ``__getattr__``
    forwarding on the innermost (raw) disk.
    """
    d = disk
    while d is not None:
        if isinstance(d, MeteredDisk):
            return True
        d = d.__dict__.get("unwrapped") if hasattr(d, "__dict__") else None
    return False


def wrap(disk):
    """Meter a disk unless it (or an inner layer) already is; None-safe."""
    if disk is None or is_metered(disk):
        return disk
    return MeteredDisk(disk)
