"""XLStorage: local POSIX StorageAPI backend (cmd/xl-storage.go).

Disk layout (xl-storage-format-v2.go:71-83):

    <root>/.sys/tmp/<uuid>...            staging area (atomic renames)
    <root>/.sys/format.json              disk identity + set layout
    <root>/<bucket>/<object>/xl.meta     version journal (meta.XLMeta)
    <root>/<bucket>/<object>/<dataDir>/part.N   framed shard files

Crash consistency is by construction, like the reference: shard files and
metadata are staged under .sys/tmp and committed with a single directory
rename (rename_data, the analogue of xl-storage.go:2000 RenameData); a
crash leaves only garbage in tmp, never a torn object.
"""

from __future__ import annotations

import errno
import os
import shutil
import time
import uuid

from . import errors
from .api import DiskInfo, ShardReader, ShardWriter, StatInfo, StorageAPI, VolInfo
from .meta import FileInfo, XLMeta

SYS_DIR = ".sys"
TMP_DIR = f"{SYS_DIR}/tmp"
XL_META = "xl.meta"


def _check_name(name: str) -> None:
    if not name or name.startswith("/") or ".." in name.split("/"):
        raise errors.FileAccessDenied(name)


class _FileShardWriter(ShardWriter):
    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._f = open(path, "wb")

    def write(self, data: bytes) -> None:
        self._f.write(data)

    def close(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()


class _FileShardReader(ShardReader):
    def __init__(self, path: str):
        try:
            self._f = open(path, "rb")
        except FileNotFoundError:
            raise errors.FileNotFound(path) from None
        except IsADirectoryError:
            raise errors.IsNotRegular(path) from None

    def read_at(self, offset: int, length: int) -> bytes:
        self._f.seek(offset)
        return self._f.read(length)

    def close(self) -> None:
        self._f.close()


class XLStorage(StorageAPI):
    """One local disk rooted at ``root``."""

    def __init__(self, root: str, endpoint: str = ""):
        self.root = os.path.abspath(root)
        self._endpoint = endpoint or self.root
        os.makedirs(os.path.join(self.root, TMP_DIR), exist_ok=True)
        self._disk_id = ""

    # ---- identity / health ----------------------------------------------

    def is_online(self) -> bool:
        return os.path.isdir(self.root)

    def endpoint(self) -> str:
        return self._endpoint

    def is_local(self) -> bool:
        return True

    def disk_info(self) -> DiskInfo:
        st = os.statvfs(self.root)
        total = st.f_blocks * st.f_frsize
        free = st.f_bavail * st.f_frsize
        return DiskInfo(
            total=total,
            free=free,
            used=total - free,
            root_disk=False,
            endpoint=self._endpoint,
            mount_path=self.root,
            disk_id=self._disk_id,
        )

    def get_disk_id(self) -> str:
        return self._disk_id

    def set_disk_id(self, disk_id: str) -> None:
        self._disk_id = disk_id

    # ---- path helpers ---------------------------------------------------

    def _vol_path(self, volume: str) -> str:
        _check_name(volume)
        return os.path.join(self.root, volume)

    def _file_path(self, volume: str, path: str) -> str:
        vp = self._vol_path(volume)
        _check_name(path or "x")
        return os.path.join(vp, *path.split("/")) if path else vp

    def _require_vol(self, volume: str) -> str:
        vp = self._vol_path(volume)
        if not os.path.isdir(vp):
            raise errors.VolumeNotFound(volume)
        return vp

    # ---- volumes --------------------------------------------------------

    def make_vol(self, volume: str) -> None:
        vp = self._vol_path(volume)
        try:
            os.makedirs(vp)
        except FileExistsError:
            # atomic exists-check: a concurrent MakeVol racing this
            # one must surface VolumeExists, not an OS error that the
            # quorum reducer would count as a disk failure
            raise errors.VolumeExists(volume) from None

    def list_vols(self) -> list[VolInfo]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if name == SYS_DIR or name.startswith("."):
                continue
            full = os.path.join(self.root, name)
            if os.path.isdir(full):
                out.append(
                    VolInfo(name, int(os.stat(full).st_ctime_ns))
                )
        return out

    def stat_vol(self, volume: str) -> VolInfo:
        vp = self._require_vol(volume)
        try:
            return VolInfo(volume, int(os.stat(vp).st_ctime_ns))
        except FileNotFoundError:
            # a concurrent DeleteVol won between the isdir check and
            # the stat: a bucket-level outcome, never a raw errno
            raise errors.VolumeNotFound(volume) from None

    def delete_vol(self, volume: str, force: bool = False) -> None:
        vp = self._require_vol(volume)
        if force:
            # rmtree racing a concurrent deleter (root vanishes) or a
            # concurrent writer (an entry vanishes mid-walk) surfaces
            # ENOENT; both are linearizable outcomes, not disk faults
            # (storage-errors.go errno mapping)
            for _ in range(8):
                try:
                    shutil.rmtree(vp)
                    return
                except FileNotFoundError:
                    if not os.path.lexists(vp):
                        raise errors.VolumeNotFound(volume) from None
                    continue  # entry vanished mid-walk; retry
                except OSError as e:
                    if e.errno in (errno.ENOTEMPTY, errno.EEXIST):
                        continue  # writer re-filled a dir mid-walk
                    raise
            shutil.rmtree(vp, ignore_errors=True)
            if os.path.lexists(vp):
                raise errors.VolumeNotEmpty(volume)
            return
        try:
            os.rmdir(vp)
        except FileNotFoundError:
            raise errors.VolumeNotFound(volume) from None
        except OSError:
            raise errors.VolumeNotEmpty(volume) from None

    # ---- raw files ------------------------------------------------------

    def list_dir(self, volume: str, dir_path: str, count: int = -1) -> list[str]:
        self._require_vol(volume)
        full = self._file_path(volume, dir_path) if dir_path else self._vol_path(volume)
        try:
            names = sorted(os.listdir(full))
        except FileNotFoundError:
            raise errors.FileNotFound(dir_path) from None
        except NotADirectoryError:
            raise errors.IsNotRegular(dir_path) from None
        out = []
        for nm in names:
            if os.path.isdir(os.path.join(full, nm)):
                nm += "/"
            out.append(nm)
            if 0 <= count <= len(out):
                break
        return out

    def read_all(self, volume: str, path: str) -> bytes:
        self._require_vol(volume)
        try:
            with open(self._file_path(volume, path), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise errors.FileNotFound(path) from None
        except IsADirectoryError:
            raise errors.IsNotRegular(path) from None

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        self._require_vol(volume)
        full = self._file_path(volume, path)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        tmp = os.path.join(
            self.root, TMP_DIR, f"wa-{uuid.uuid4().hex}"
        )
        # the tmp area may have been pruned by delete_file parent cleanup
        os.makedirs(os.path.dirname(tmp), exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, full)

    def delete_file(self, volume: str, path: str, recursive: bool = False) -> None:
        self._require_vol(volume)
        full = self._file_path(volume, path)
        try:
            if os.path.isdir(full):
                if recursive:
                    shutil.rmtree(full)
                else:
                    os.rmdir(full)
            else:
                os.remove(full)
        except FileNotFoundError:
            raise errors.FileNotFound(path) from None
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e
        # prune now-empty parents up to the volume root (deleteFile,
        # xl-storage.go parent cleanup)
        parent = os.path.dirname(full)
        vol = self._vol_path(volume)
        while parent != vol:
            try:
                os.rmdir(parent)
            except OSError:
                break
            parent = os.path.dirname(parent)

    def rename_file(
        self, src_volume: str, src_path: str, dst_volume: str, dst_path: str
    ) -> None:
        self._require_vol(src_volume)
        self._require_vol(dst_volume)
        src = self._file_path(src_volume, src_path)
        dst = self._file_path(dst_volume, dst_path)
        if not os.path.exists(src):
            raise errors.FileNotFound(src_path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(src, dst)

    def stat_file(self, volume: str, path: str) -> StatInfo:
        self._require_vol(volume)
        try:
            st = os.stat(self._file_path(volume, path))
        except FileNotFoundError:
            raise errors.FileNotFound(path) from None
        return StatInfo(
            size=st.st_size,
            mod_time_ns=st.st_mtime_ns,
            is_dir=os.path.isdir(self._file_path(volume, path)),
        )

    # ---- shard streams --------------------------------------------------

    def create_file(self, volume: str, path: str) -> ShardWriter:
        self._require_vol(volume)
        return _FileShardWriter(self._file_path(volume, path))

    def read_file_stream(self, volume: str, path: str) -> ShardReader:
        self._require_vol(volume)
        return _FileShardReader(self._file_path(volume, path))

    # ---- object metadata ------------------------------------------------

    def read_xl(self, volume: str, path: str) -> XLMeta:
        raw = self.read_all(volume, f"{path}/{XL_META}")
        return XLMeta.from_bytes(raw, volume, path)

    def read_version(
        self, volume: str, path: str, version_id: str = ""
    ) -> FileInfo:
        xl = self.read_xl(volume, path)
        fi = xl.find(version_id)
        fi.volume, fi.name = volume, path
        return fi

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        try:
            xl = self.read_xl(volume, path)
        except errors.FileNotFound:
            xl = XLMeta()
        xl.add_version(fi)
        self.write_all(volume, f"{path}/{XL_META}", xl.to_bytes())

    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        xl = self.read_xl(volume, path)  # must exist
        xl.add_version(fi)
        self.write_all(volume, f"{path}/{XL_META}", xl.to_bytes())

    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        xl = self.read_xl(volume, path)
        victim = xl.delete_version(fi.version_id)
        if victim.data_dir:
            try:
                self.delete_file(
                    volume, f"{path}/{victim.data_dir}", recursive=True
                )
            except errors.FileNotFound:
                pass
        if xl.versions:
            self.write_all(volume, f"{path}/{XL_META}", xl.to_bytes())
        else:
            self.delete_file(volume, f"{path}/{XL_META}")

    def rename_data(
        self,
        src_volume: str,
        src_path: str,
        fi: FileInfo,
        dst_volume: str,
        dst_path: str,
    ) -> None:
        self._require_vol(src_volume)
        self._require_vol(dst_volume)
        src_dir = self._file_path(src_volume, src_path)
        dst_obj = self._file_path(dst_volume, dst_path)
        if not os.path.isdir(src_dir):
            raise errors.FileNotFound(src_path)
        os.makedirs(dst_obj, exist_ok=True)
        if fi.data_dir:
            dst_data = os.path.join(dst_obj, fi.data_dir)
            staged = os.path.join(src_dir, fi.data_dir)
            if not os.path.isdir(staged):
                raise errors.FileNotFound(f"{src_path}/{fi.data_dir}")
            if os.path.isdir(dst_data):
                shutil.rmtree(dst_data)
            os.replace(staged, dst_data)
        # merge + commit version journal
        try:
            xl = self.read_xl(dst_volume, dst_path)
        except errors.FileNotFound:
            xl = XLMeta()
        xl.add_version(fi)
        self.write_all(
            dst_volume, f"{dst_path}/{XL_META}", xl.to_bytes()
        )
        shutil.rmtree(src_dir, ignore_errors=True)

    # ---- maintenance ----------------------------------------------------

    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        """Deep scan every part's framed blocks against its digests."""
        from ..codec import bitrot
        from ..codec.erasure import Erasure

        er = Erasure(
            fi.erasure.data_blocks,
            fi.erasure.parity_blocks,
            fi.erasure.block_size,
        )
        for part in fi.parts:
            rel = f"{path}/{fi.data_dir}/part.{part.number}"
            rd = self.read_file_stream(volume, rel)
            try:
                nblocks = er.block_count(part.size)
                for b in range(nblocks):
                    block_len = min(
                        er.block_size, part.size - b * er.block_size
                    )
                    shard_len = er.shard_size_padded(block_len)
                    frame = bitrot.DIGEST_SIZE + shard_len
                    buf = rd.read_at(er.shard_block_offset(b), frame)
                    if len(buf) != frame:
                        raise errors.FileCorrupt(
                            f"{rel}: truncated block {b}"
                        )
                    if not bitrot.verify_block(
                        buf[bitrot.DIGEST_SIZE :],
                        buf[: bitrot.DIGEST_SIZE],
                    ):
                        raise errors.FileCorrupt(f"{rel}: bitrot block {b}")
            finally:
                rd.close()

    def append_file(
        self,
        volume: str,
        path: str,
        data: bytes,
        truncate: bool = False,
        offset: "int | None" = None,
    ) -> None:
        """Append shard bytes; with ``offset``, idempotently.

        A remote writer whose response was lost retries the same append;
        writing at the *declared* offset (truncating any bytes past it)
        makes the retry converge instead of duplicating shard data
        (advisor finding r2).  Only one writer ever owns a staging file,
        so the truncate cannot race another append.
        """
        self._require_vol(volume)
        fp = self._file_path(volume, path)
        os.makedirs(os.path.dirname(fp), exist_ok=True)
        if offset is not None:
            try:
                size = os.path.getsize(fp)
            except OSError:
                size = 0
            if truncate:
                offset = 0
            if size < offset:
                raise errors.FileCorrupt(
                    f"{path}: append at {offset} but file has {size}"
                )
            with open(fp, "r+b" if size else "wb") as f:
                f.truncate(offset)
                f.seek(offset)
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            return
        with open(fp, "wb" if truncate else "ab") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    def walk(self, volume: str, prefix: str = ""):
        """Yield object paths (dirs containing xl.meta) under prefix."""
        vol = self._require_vol(volume)
        base = (
            os.path.join(vol, *prefix.split("/")) if prefix else vol
        )
        if not os.path.isdir(base):
            return
        for dirpath, dirnames, filenames in os.walk(base):
            if XL_META in filenames:
                rel = os.path.relpath(dirpath, vol).replace(os.sep, "/")
                dirnames[:] = []  # don't descend into data dirs
                yield rel

    # ---- ordered bounded walk (tree-walk.go analogue) -------------------

    def walk_sorted(
        self,
        volume: str,
        prefix: str = "",
        marker: str = "",
        recursive: bool = True,
        inclusive: bool = False,
    ):
        """Yield ``(name, is_prefix)`` in lexical order, lazily.

        The scalable-listing primitive (cmd/tree-walk.go doTreeWalk):
        directories are read in sorted order and subtrees that cannot
        contain a name matching ``prefix`` and > ``marker`` are pruned,
        so one page of results touches only the directories it needs.
        ``recursive=False`` lists a single level (delimiter="/" mode):
        plain directories come back once as ("dir/", True) without
        descending.  ``inclusive`` keeps names equal to the marker
        (version listings re-visit the marker key).
        """
        vol = self._require_vol(volume)
        if recursive:
            yield from self._walk_rec(vol, "", prefix, marker, inclusive)
            return
        base, _, leaf = prefix.rpartition("/")
        base_fs = (
            os.path.join(vol, *base.split("/")) if base else vol
        )
        if base and os.path.isfile(os.path.join(base_fs, XL_META)):
            # the prefix points INSIDE an object's directory: its
            # children are erasure data dirs, not namespace entries -
            # leaking them as CommonPrefixes exposes internal layout
            return
        try:
            entries = sorted(os.listdir(base_fs))
        except (FileNotFoundError, NotADirectoryError):
            return
        basep = base + "/" if base else ""
        for e in entries:
            if leaf and not e.startswith(leaf):
                continue
            full = os.path.join(base_fs, e)
            if not os.path.isdir(full):
                continue
            if os.path.isfile(os.path.join(full, XL_META)):
                name = basep + e
                if name > marker or (inclusive and name == marker):
                    yield (name, False)
            else:
                cp = basep + e + "/"
                if cp > marker:
                    yield (cp, True)

    def _walk_rec(self, vol, rel, prefix, marker, inclusive):
        base = os.path.join(vol, *rel.split("/")) if rel else vol
        try:
            entries = sorted(os.listdir(base))
        except (FileNotFoundError, NotADirectoryError):
            return
        for e in entries:
            name = f"{rel}/{e}" if rel else e
            full = os.path.join(base, e)
            if not os.path.isdir(full):
                continue
            if os.path.isfile(os.path.join(full, XL_META)):
                if prefix and not name.startswith(prefix):
                    continue
                if name > marker or (inclusive and name == marker):
                    yield (name, False)
                continue  # object dirs hold data dirs, not children
            sub = name + "/"
            # prefix prune: the subtree's names all start with `sub`
            if prefix and not (
                sub.startswith(prefix) or prefix.startswith(sub)
            ):
                continue
            # marker prune: every name under `sub` is < marker exactly
            # when marker doesn't extend `sub` and sorts after it
            if (
                marker
                and not marker.startswith(sub)
                and sub < marker
            ):
                continue
            yield from self._walk_rec(vol, name, prefix, marker, inclusive)

    # ---- staging helpers (object-layer use) -----------------------------

    def new_tmp_dir(self) -> str:
        """Unique staging path inside this disk's tmp area."""
        return f"{TMP_DIR}/{uuid.uuid4().hex}"

    def clean_tmp(self, tmp_path: str) -> None:
        full = os.path.join(self.root, *tmp_path.split("/"))
        shutil.rmtree(full, ignore_errors=True)
