"""Per-disk circuit breakers + pool-wide latency deadlines.

The degraded GET path needs two decisions made fast and without
coordination:

* **Should this disk be in the preference order at all?**  Each disk
  gets a ``DiskHealth`` state machine — ``healthy -> suspect ->
  tripped`` — driven by consecutive errors (a dead or flapping disk
  trips after ``MINIO_TPU_BREAKER_TRIP_ERRORS`` failures in a row) and
  by p99-outlier latency (reads far beyond the pool-wide p99, or reads
  abandoned by the hedging loop, accrue *slow strikes* that demote the
  disk to suspect for a decaying window).  Tripped disks are skipped
  everywhere ``_online_disks`` is consulted (GET preference, PUT
  fan-out bookkeeping, heal) and recover through a **half-open window**
  with exponential backoff: after the backoff lapses callers are
  admitted until the first verdict lands — success closes the breaker,
  failure re-trips it with doubled backoff — so a dead disk eats at
  most one concurrent round of probe traffic per backoff period.
* **How long is a shard read allowed to take?**  The registry keeps
  pool-wide streaming read quantiles (``P2Quantile`` from metered.py —
  constant memory); ``hedge_deadline()`` is the clamped multiple of the
  live p99 that ``codec/erasure.py`` races each round of shard reads
  against before launching a duplicate on the next parity shard.

Lock discipline: the registry lock only guards the disk table and the
pool estimators; each ``DiskHealth`` has its own lock and the two are
never nested (MeteredDisk likewise calls in only after releasing its
ledger lock).  All locks come from the module-global ``threading`` so
the MTPU3xx lock-order auditor can swap in its audited primitives.
"""

from __future__ import annotations

import os
import threading
import time

from ..utils.log import kv, logger
from .metered import P2Quantile

_log = logger("diskhealth")

# states, ordered by preference penalty (sort key in the GET path)
HEALTHY = 0
SUSPECT = 1
TRIPPED = 2
STATE_NAMES = {HEALTHY: "healthy", SUSPECT: "suspect", TRIPPED: "tripped"}


def _env_float(name: str, default: float, lo: float, hi: float) -> float:
    try:
        v = float(os.environ.get(name) or default)
    except ValueError:
        v = default
    return max(lo, min(hi, v))


def _env_int(name: str, default: int, lo: int, hi: int) -> int:
    try:
        v = int(os.environ.get(name) or default)
    except ValueError:
        v = default
    return max(lo, min(hi, v))


class _Config:
    """Env-derived knobs, read once per registry (reset_registry()
    re-reads — tests set the env first, then reset)."""

    __slots__ = (
        "enabled",
        "trip_errors",
        "suspect_errors",
        "backoff_s",
        "backoff_cap_s",
        "outlier_factor",
        "slow_strikes",
        "slow_decay_s",
        "hedge_enabled",
        "hedge_factor",
        "hedge_min_s",
        "hedge_max_s",
    )

    def __init__(self):
        self.enabled = os.environ.get("MINIO_TPU_BREAKER", "1") != "0"
        self.trip_errors = _env_int(
            "MINIO_TPU_BREAKER_TRIP_ERRORS", 5, 1, 1000
        )
        self.suspect_errors = _env_int(
            "MINIO_TPU_BREAKER_SUSPECT_ERRORS", 2, 1, 1000
        )
        self.backoff_s = (
            _env_float("MINIO_TPU_BREAKER_BACKOFF_MS", 1000.0, 1.0, 6e5)
            / 1000.0
        )
        self.backoff_cap_s = 30.0
        self.outlier_factor = _env_float(
            "MINIO_TPU_BREAKER_OUTLIER", 4.0, 1.0, 1e6
        )
        self.slow_strikes = _env_int(
            "MINIO_TPU_BREAKER_SLOW_STRIKES", 2, 1, 1000
        )
        self.slow_decay_s = (
            _env_float("MINIO_TPU_BREAKER_SLOW_DECAY_MS", 2000.0, 1.0, 6e5)
            / 1000.0
        )
        self.hedge_enabled = os.environ.get("MINIO_TPU_HEDGE", "1") != "0"
        self.hedge_factor = _env_float(
            "MINIO_TPU_HEDGE_FACTOR", 3.0, 1.0, 1e3
        )
        self.hedge_min_s = (
            _env_float("MINIO_TPU_HEDGE_MIN_MS", 2.0, 0.01, 1e6) / 1000.0
        )
        self.hedge_max_s = (
            _env_float("MINIO_TPU_HEDGE_MAX_MS", 2000.0, 0.01, 1e7) / 1000.0
        )


class DiskHealth:
    """Circuit breaker for one disk endpoint.

    healthy --errors/slow strikes--> suspect --more errors--> tripped
    tripped --backoff expiry--> single probe --success--> healthy
                                             --failure--> tripped (2x)
    """

    def __init__(self, endpoint: str, cfg: _Config):
        self.endpoint = endpoint
        self._cfg = cfg
        self._mu = threading.Lock()
        self._state = HEALTHY
        self._consec_errors = 0
        self._slow_strikes = 0
        self._slow_until = 0.0
        self._until = 0.0  # trip expiry (monotonic)
        self._backoff_s = cfg.backoff_s
        self._probing = False
        self._probe_t0 = 0.0
        self.trips = 0
        self.recoveries = 0
        # per-disk shard-read latency (successful, non-censored reads)
        self._read_p50 = P2Quantile(0.50)
        self._read_p99 = P2Quantile(0.99)

    # -- admission --------------------------------------------------------

    def admit(self, now: "float | None" = None) -> bool:
        """May the caller touch this disk right now?

        Healthy/suspect disks always admit (suspect only demotes the
        *preference order*, it never blocks — a suspect disk may still
        be the only holder of a needed shard).  A tripped disk flips to
        half-open once its backoff expires and then admits every caller
        until a verdict lands: the first success closes the breaker,
        the first failure re-trips it with doubled backoff.  A one-shot
        probe token would deadlock here — ``_online_disks()`` admits at
        list-construction time, and many callers (bucket stat, list)
        touch only a prefix of that list, so the token could be burned
        without any call ever reaching the disk.
        """
        if not self._cfg.enabled:
            return True
        now = time.monotonic() if now is None else now
        with self._mu:
            if self._state != TRIPPED:
                return True
            if now < self._until:
                return False
            if not self._probing:
                self._probing = True
                self._probe_t0 = now
            return True

    def state(self, now: "float | None" = None) -> int:
        now = time.monotonic() if now is None else now
        with self._mu:
            return self._state_locked(now)

    def _state_locked(self, now: float) -> int:
        if self._state == TRIPPED:
            return TRIPPED
        if self._state == SUSPECT:
            return SUSPECT
        if self._slow_strikes >= self._cfg.slow_strikes and (
            now < self._slow_until
        ):
            return SUSPECT
        return HEALTHY

    # -- observations -----------------------------------------------------

    def record_api(self, api: str, seconds: float, ok: bool) -> None:
        """Verdict from a metered disk-API call (MeteredDisk._record)."""
        now = time.monotonic()
        with self._mu:
            if ok:
                self._on_success_locked(now)
            else:
                self._on_failure_locked(now, api)

    def record_shard_read(
        self,
        seconds: float,
        ok: bool,
        censored: bool = False,
        pool_p99: "float | None" = None,
    ) -> None:
        """Verdict from one GET shard read (codec/erasure.py).

        ``censored=True`` means the hedging loop abandoned the read at
        ``seconds`` elapsed without an outcome — the true latency is
        *at least* that, so it never feeds the quantile estimators
        (they would be biased fast) but it does count as a slow strike:
        a disk whose reads keep getting hedged past is degraded even if
        every read would eventually have succeeded.
        """
        now = time.monotonic()
        with self._mu:
            if not ok:
                self._on_failure_locked(now, "shard_read")
                return
            if censored:
                self._note_slow_locked(now)
                return
            self._read_p50.observe(seconds)
            self._read_p99.observe(seconds)
            # outlier strikes are floored at the minimum hedge deadline:
            # a read faster than we would ever hedge past cannot be
            # "slow", however small the pool p99 gets — without the
            # floor, microsecond-scale pools turn scheduler jitter into
            # spurious suspect demotions
            if (
                pool_p99 is not None
                and seconds > self._cfg.outlier_factor * pool_p99
                and seconds > self._cfg.hedge_min_s
            ):
                self._note_slow_locked(now)
                return
            self._on_success_locked(now)

    def _note_slow_locked(self, now: float) -> None:
        self._slow_strikes += 1
        self._slow_until = now + self._cfg.slow_decay_s
        # slow strikes resolve a probe too: a probe read that had to be
        # abandoned is not a recovery
        if self._probing and self._state == TRIPPED:
            self._retrip_locked(now, "probe read abandoned")

    def _on_success_locked(self, now: float) -> None:
        self._consec_errors = 0
        if self._slow_strikes and now >= self._slow_until:
            self._slow_strikes = 0
        if self._state == TRIPPED:
            if self._probing:
                self._probing = False
                self._state = HEALTHY
                self._slow_strikes = 0
                self._backoff_s = self._cfg.backoff_s
                self.recoveries += 1
                _log.info(
                    "disk breaker recovered",
                    extra=kv(disk=self.endpoint),
                )
        elif self._state == SUSPECT:
            self._state = HEALTHY

    def _on_failure_locked(self, now: float, api: str) -> None:
        self._consec_errors += 1
        if self._state == TRIPPED:
            if self._probing:
                self._retrip_locked(now, api)
            return
        if self._consec_errors >= self._cfg.trip_errors:
            self._state = TRIPPED
            self._until = now + self._backoff_s
            self._probing = False
            self.trips += 1
            _log.warning(
                "disk breaker tripped",
                extra=kv(
                    disk=self.endpoint,
                    api=api,
                    consec_errors=self._consec_errors,
                    backoff_s=round(self._backoff_s, 3),
                ),
            )
        elif self._consec_errors >= self._cfg.suspect_errors:
            self._state = SUSPECT

    def _retrip_locked(self, now: float, why: str) -> None:
        self._probing = False
        self._backoff_s = min(
            self._backoff_s * 2.0, self._cfg.backoff_cap_s
        )
        self._until = now + self._backoff_s
        self.trips += 1
        _log.warning(
            "disk breaker probe failed; re-tripped",
            extra=kv(
                disk=self.endpoint,
                why=why,
                backoff_s=round(self._backoff_s, 3),
            ),
        )

    # -- reading ----------------------------------------------------------

    def read_p99(self) -> "float | None":
        with self._mu:
            return self._read_p99.value()

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._mu:
            out = {
                "state": STATE_NAMES[self._state_locked(now)],
                "consec_errors": self._consec_errors,
                "slow_strikes": self._slow_strikes,
                "trips": self.trips,
                "recoveries": self.recoveries,
                "probing": self._probing,
            }
            if self._state == TRIPPED:
                out["retry_in_seconds"] = round(
                    max(0.0, self._until - now), 3
                )
            p50, p99 = self._read_p50.value(), self._read_p99.value()
            if p50 is not None:
                out["read_p50_seconds"] = round(p50, 6)
            if p99 is not None:
                out["read_p99_seconds"] = round(p99, 6)
            return out


class HealthRegistry:
    """Process-wide table of DiskHealth breakers + pool read quantiles."""

    def __init__(self):
        self.cfg = _Config()
        self._mu = threading.Lock()  # disk table + pool estimators only
        self._disks: "dict[str, DiskHealth]" = {}
        self._pool_p50 = P2Quantile(0.50)
        self._pool_p99 = P2Quantile(0.99)

    def get_disk(self, endpoint: str) -> DiskHealth:
        with self._mu:
            dh = self._disks.get(endpoint)
            if dh is None:
                dh = DiskHealth(endpoint, self.cfg)
                self._disks[endpoint] = dh
            return dh

    def record_shard_read(
        self,
        endpoint: str,
        seconds: float,
        ok: bool,
        censored: bool = False,
    ) -> None:
        """One shard read's verdict: feeds the pool estimators (only
        clean successes — censored samples would bias the deadline
        fast) and the disk's breaker.  The two locks are taken in
        sequence, never nested."""
        pool_p99 = None
        if ok and not censored:
            with self._mu:
                self._pool_p50.observe(seconds)
                self._pool_p99.observe(seconds)
                pool_p99 = self._pool_p99.value()
        elif ok:
            with self._mu:
                pool_p99 = self._pool_p99.value()
        self.get_disk(endpoint).record_shard_read(
            seconds, ok, censored=censored, pool_p99=pool_p99
        )

    def read_quantile(self, q: float) -> "float | None":
        """Pool-wide read latency estimate (q in {0.5, 0.99})."""
        with self._mu:
            if q >= 0.99:
                return self._pool_p99.value()
            return self._pool_p50.value()

    def hedge_deadline(self) -> "float | None":
        """Seconds a shard read may run before the GET path hedges.

        None disables hedging this round: either MINIO_TPU_HEDGE=0 or
        the pool estimator hasn't seen a single successful read yet
        (first-ever GET has nothing to derive a deadline from).
        """
        if not self.cfg.hedge_enabled:
            return None
        with self._mu:
            p99 = self._pool_p99.value()
        if p99 is None:
            return None
        return max(
            self.cfg.hedge_min_s,
            min(self.cfg.hedge_max_s, p99 * self.cfg.hedge_factor),
        )

    def snapshot(self) -> dict:
        with self._mu:
            disks = dict(self._disks)
            p50, p99 = self._pool_p50.value(), self._pool_p99.value()
        out = {
            "pool": {
                "read_p50_seconds": round(p50, 6) if p50 is not None else None,
                "read_p99_seconds": round(p99, 6) if p99 is not None else None,
            },
            "disks": {
                ep: dh.snapshot() for ep, dh in sorted(disks.items())
            },
        }
        return out

    def states(self) -> "dict[str, int]":
        """endpoint -> numeric state (Prometheus miniotpu_disk_state)."""
        with self._mu:
            disks = dict(self._disks)
        return {ep: dh.state() for ep, dh in disks.items()}


# -- process-wide singleton ------------------------------------------------

_REGISTRY: "HealthRegistry | None" = None
_REGISTRY_LK = threading.Lock()


def registry() -> HealthRegistry:
    global _REGISTRY
    r = _REGISTRY
    if r is None:
        with _REGISTRY_LK:
            if _REGISTRY is None:
                _REGISTRY = HealthRegistry()
            r = _REGISTRY
    return r


def reset_registry() -> None:
    """Discard all breaker state and re-read env knobs (tests)."""
    global _REGISTRY
    with _REGISTRY_LK:
        _REGISTRY = None


def should_skip(disk) -> bool:
    """True if the disk's breaker is open and no probe is due.

    Works on any layer of the wrap chain: DiskIDCheck forwards the
    ``health`` attribute down to the MeteredDisk; bare disks (no
    metering, e.g. unit-test doubles) have no breaker and never skip.
    """
    h = getattr(disk, "health", None)
    if h is None:
        return False
    return not h.admit()
