"""Data update tracker: a persisted bloom journal of dirty namespaces.

Role-equivalent of the reference's dataUpdateTracker
(cmd/data-update-tracker.go:63): every object mutation marks the
bucket plus up to three path levels into the current cycle's bloom
filter; before each sweep the crawler rotates the filter
(cycleFilter, data-update-tracker.go:533) and receives the union of
every cycle since its last completed run.  Buckets whose usage is
cached and whose name never hit the filter are skipped wholesale.

Design differences from the reference, deliberate:

- the reference journals every path to disk and replays on boot; we
  instead save atomically on every rotation (and every
  ``save_every`` marks) and mark the in-flight cycle *untrusted*
  after a reload — the first post-restart sweep is a full one, and
  skipping resumes the cycle after.  One extra sweep buys out the
  whole journal/replay subsystem.
- filters union with numpy over the packed bitset, not a byte loop.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import zlib

import msgpack
import numpy as np

# ~1% false-positive rate at ~440k distinct dirty prefixes; a false
# positive only costs one needless bucket crawl
_DEFAULT_BITS = 2**22
_DEFAULT_HASHES = 7
_DEFAULT_HISTORY = 16  # cycles retained (dataUpdateTrackerHistory)


def split_path_deterministic(path: str) -> "list[str]":
    """First <=3 path components, slash/dot prefixes trimmed
    (splitPathDeterministic, data-update-tracker.go:568)."""
    parts = [p for p in path.split("/") if p and p != "."]
    return parts[:3]


class BloomFilter:
    """Double-hashed bloom filter over a packed bitset."""

    __slots__ = ("m", "k", "bits")

    def __init__(self, m: int = _DEFAULT_BITS, k: int = _DEFAULT_HASHES,
                 bits: "bytes | bytearray | None" = None):
        if m % 8:
            raise ValueError("bits must be a multiple of 8")
        self.m = m
        self.k = k
        self.bits = bytearray(m // 8) if bits is None else bytearray(bits)
        if len(self.bits) != m // 8:
            raise ValueError("bitset length mismatch")

    def _positions(self, s: str):
        d = hashlib.blake2b(s.encode(), digest_size=16).digest()
        h1 = int.from_bytes(d[:8], "little")
        h2 = int.from_bytes(d[8:], "little") | 1  # odd: full-period step
        return ((h1 + i * h2) % self.m for i in range(self.k))

    def add(self, s: str) -> None:
        for p in self._positions(s):
            self.bits[p >> 3] |= 1 << (p & 7)

    def __contains__(self, s: str) -> bool:
        return all(
            self.bits[p >> 3] & (1 << (p & 7)) for p in self._positions(s)
        )

    def contains_dir(self, path: str) -> bool:
        """Whether a bucket/prefix was marked dirty
        (bloomFilter.containsDir, data-update-tracker.go:110)."""
        return path.strip("/") in self

    def union_into(self, other: "BloomFilter") -> None:
        """self |= other (shape-checked)."""
        if (other.m, other.k) != (self.m, self.k):
            raise ValueError("bloom shape mismatch")
        a = np.frombuffer(self.bits, dtype=np.uint8)
        b = np.frombuffer(other.bits, dtype=np.uint8)
        self.bits = bytearray(np.bitwise_or(a, b).tobytes())

    def copy(self) -> "BloomFilter":
        return BloomFilter(self.m, self.k, bytes(self.bits))

    def to_bytes(self) -> bytes:
        return zlib.compress(bytes(self.bits), 1)

    @classmethod
    def from_bytes(cls, m: int, k: int, raw: bytes) -> "BloomFilter":
        return cls(m, k, zlib.decompress(raw))


@dataclasses.dataclass
class BloomResponse:
    """cycleFilter reply (bloomFilterResponse,
    data-update-tracker.go:599)."""

    current_idx: int
    oldest_idx: int
    newest_idx: int
    complete: bool
    filter: BloomFilter

    def to_wire(self) -> dict:
        return {
            "current_idx": self.current_idx,
            "oldest_idx": self.oldest_idx,
            "newest_idx": self.newest_idx,
            "complete": self.complete,
            "m": self.filter.m,
            "k": self.filter.k,
            "filter": self.filter.to_bytes(),
        }

    @classmethod
    def from_wire(cls, d: dict) -> "BloomResponse":
        return cls(
            current_idx=d["current_idx"],
            oldest_idx=d["oldest_idx"],
            newest_idx=d["newest_idx"],
            complete=d["complete"],
            filter=BloomFilter.from_bytes(d["m"], d["k"], d["filter"]),
        )


class DataUpdateTracker:
    def __init__(self, path: "str | None" = None, m: int = _DEFAULT_BITS,
                 k: int = _DEFAULT_HASHES,
                 history: int = _DEFAULT_HISTORY,
                 save_every: int = 2000):
        self._lock = threading.Lock()
        # serializes snapshot file writes, NOT taken under _lock:
        # compression and disk IO happen outside the mark hot path
        self._io_lock = threading.Lock()
        self._path = path
        self.m = m
        self.k = k
        self._keep = history
        self._save_every = save_every
        self._marks = 0
        self._snap_seq = 0  # monotone: stale snapshots never clobber
        self._written_seq = 0
        # compressed blobs of rotated (immutable) history filters so a
        # save never recompresses 16 x 512 KiB it already compressed
        self._hist_blobs: "dict[int, bytes]" = {}
        # starts at 0 so the crawler's first sweep (cycle 1) rotates:
        # marks that predate the sweep land in filter 0, inside its
        # window and OUTSIDE cycle 2's - without this every pre-boot
        # mutation would force a redundant re-crawl on the second sweep
        self.current_idx = 0
        self.cur = BloomFilter(m, k)
        self.history: "dict[int, BloomFilter]" = {}
        # cycle indices whose marks may be partially lost (the filter
        # that was live when a previous process died); ranges touching
        # them report complete=False, forcing one full sweep
        self.untrusted: "set[int]" = set()
        if path:
            self._load()

    # -- marking ----------------------------------------------------------

    def mark(self, path: str) -> None:
        """Record a mutation under bucket/object `path`.  Reserved
        volumes (dot-prefixed) are not tracked, like
        isReservedOrInvalidBucket in the reference collector."""
        parts = split_path_deterministic(path)
        if not parts or parts[0].startswith("."):
            return
        snap = None
        with self._lock:
            for i in range(len(parts)):
                self.cur.add("/".join(parts[: i + 1]))
            self._marks += 1
            if self._save_every and self._marks >= self._save_every:
                self._marks = 0
                snap = self._snapshot_locked()
        if snap is not None:
            self._write_snapshot(snap)

    def current(self) -> int:
        with self._lock:
            return self.current_idx

    # -- cycling ----------------------------------------------------------

    def cycle_filter(self, oldest: int, current: int) -> BloomResponse:
        """Start recording into cycle `current` and return the union
        filter covering [oldest, current) (cycleFilter,
        data-update-tracker.go:533)."""
        snap = None
        with self._lock:
            if current and current < self.current_idx:
                # a stale caller (e.g. a node that lost crawl
                # leadership cycles ago) must never rewind the
                # tracker; serve its window incomplete so it falls
                # back to a full sweep and resyncs its counter
                resp = self._filter_from_locked(oldest, self.current_idx)
                resp.complete = False
                return resp
            if current and self.current_idx != current:
                self.history[self.current_idx] = self.cur
                self._hist_blobs[self.current_idx] = self.cur.to_bytes()
                self.cur = BloomFilter(self.m, self.k)
                self.current_idx = current
                floor = max(oldest, current - self._keep)
                for idx in [i for i in self.history if i < floor]:
                    del self.history[idx]
                    self._hist_blobs.pop(idx, None)
                self.untrusted = {
                    i for i in self.untrusted if i >= floor
                }
                snap = self._snapshot_locked()
            resp = self._filter_from_locked(oldest, self.current_idx)
        if snap is not None:
            self._write_snapshot(snap)
        return resp

    def _filter_from_locked(self, oldest: int, newest: int) -> BloomResponse:
        out = BloomFilter(self.m, self.k)
        # the live filter (idx == newest) sits outside the window, but
        # if IT is untrusted (reloaded after a crash, no rotation yet)
        # its lost marks are unobservable anywhere - the window cannot
        # claim completeness
        complete = newest not in self.untrusted
        for idx in range(oldest, newest):
            bf = self.history.get(idx)
            if bf is None or idx in self.untrusted:
                complete = False
                continue
            out.union_into(bf)
        return BloomResponse(
            current_idx=newest,
            oldest_idx=oldest,
            newest_idx=newest,
            complete=complete,
            filter=out,
        )

    # -- persistence (atomic snapshot; see module docstring) ---------------

    def _snapshot_locked(self) -> "dict | None":
        """Cheap state capture under _lock: a copy of the live bitset
        plus already-compressed history blobs.  Compression of the
        live filter and the file write happen in _write_snapshot,
        outside the mark/rotate lock."""
        if not self._path:
            return None
        self._snap_seq += 1
        return {
            "seq": self._snap_seq,
            "idx": self.current_idx,
            "cur_raw": bytes(self.cur.bits),
            "hist": dict(self._hist_blobs),
            "untrusted": sorted(self.untrusted),
        }

    def _write_snapshot(self, snap: "dict | None") -> None:
        if snap is None:
            return
        doc = {
            "m": self.m,
            "k": self.k,
            "idx": snap["idx"],
            "cur": zlib.compress(snap.pop("cur_raw"), 1),
            "hist": snap["hist"],
            "untrusted": snap["untrusted"],
        }
        with self._io_lock:
            if snap["seq"] <= self._written_seq:
                return  # a newer snapshot already landed
            self._written_seq = snap["seq"]
            tmp = self._path + ".tmp"
            try:
                os.makedirs(os.path.dirname(self._path), exist_ok=True)
                with open(tmp, "wb") as f:
                    f.write(msgpack.packb(doc))
                os.replace(tmp, self._path)
            except OSError:
                pass

    def save(self) -> None:
        with self._lock:
            snap = self._snapshot_locked()
        self._write_snapshot(snap)

    def _load(self) -> None:
        try:
            with open(self._path, "rb") as f:
                doc = msgpack.unpackb(f.read(), strict_map_key=False)
        except (OSError, ValueError):
            return
        # parse into locals first: a partially-corrupt snapshot must
        # not leave half-adopted state behind (worse, state adopted
        # WITHOUT the untrusted marking below)
        try:
            if (doc["m"], doc["k"]) != (self.m, self.k):
                return  # shape changed: start fresh
            idx = int(doc["idx"])
            cur = BloomFilter(self.m, self.k, zlib.decompress(doc["cur"]))
            history = {
                int(i): BloomFilter.from_bytes(self.m, self.k, raw)
                for i, raw in doc.get("hist", {}).items()
            }
            hist_blobs = {
                int(i): raw for i, raw in doc.get("hist", {}).items()
            }
            untrusted = set(doc.get("untrusted", []))
        except (KeyError, TypeError, ValueError, zlib.error):
            return
        self.current_idx = idx
        self.cur = cur
        self.history = history
        self._hist_blobs = hist_blobs
        # marks after the last save died with the old process: the
        # in-flight cycle cannot be trusted for skipping
        self.untrusted = untrusted | {idx}


# -- process-wide mark hook (ObjectPathUpdated,
#    data-update-tracker.go:614) ------------------------------------------

_active: "DataUpdateTracker | None" = None


def install_tracker(tracker: "DataUpdateTracker | None") -> None:
    global _active
    _active = tracker


def object_path_updated(path: str) -> None:
    t = _active
    if t is not None:
        t.mark(path)
