"""Data crawler: usage accounting + lifecycle enforcement
(cmd/data-crawler.go, cmd/data-usage.go)."""

from .crawler import DataCrawler, DataUsage  # noqa: F401
