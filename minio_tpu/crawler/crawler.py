"""DataCrawler: the background namespace sweep
(cmd/data-crawler.go:62 runDataCrawler + cmd/data-usage.go).

One daemon thread cycles over every bucket:

- **usage accounting**: objects / versions / delete markers / logical
  bytes per bucket, persisted as one JSON document under the reserved
  meta volume (the dataUsageObjName cache the admin API and metrics
  serve) so a restart starts warm;
- **lifecycle enforcement**: each version is run through the bucket's
  parsed Lifecycle (ilm.ComputeAction) and expired objects/versions are
  deleted through the object layer - versioned buckets get a delete
  marker for current-version expiry exactly like applyLifecycle
  (data-crawler.go:877-907);
- **multipart hygiene**: incomplete uploads older than the rule's
  DaysAfterInitiation are aborted (the reference does this in the
  multipart cleanup sweep).

The crawler paces itself (``sleep_every``/``sleep_s``) instead of
scanning flat out - the dataCrawlSleepPerFolder throttle - so a big
namespace does not monopolize the disks.

When a :class:`~minio_tpu.crawler.updatetracker.DataUpdateTracker` is
attached, each sweep first rotates the bloom filter
(cycleFilter, data-update-tracker.go:533) and skips buckets whose
cached usage exists and whose name never hit the filter.  Guards,
matching the reference's behavior: a bucket with lifecycle, FIFO
quota, or replication config is always swept (time alone changes what
those do), a full sweep runs every ``_FULL_SWEEP_EVERY`` cycles
(dataUsageUpdateDirCycles), and an incomplete filter (restart, peer
down) disables skipping for that sweep.
"""

from __future__ import annotations

import dataclasses
import io
import os
import json
import threading
import time

from .. import cache as rcache
from ..ilm import Action, Lifecycle, LifecycleError
from ..objectlayer.api import META_BUCKET

from ..utils.log import kv, logger

_log = logger("crawler")

USAGE_PATH = "data-usage/usage.json"
# even "clean" buckets get re-swept this often (bloom false negatives
# are impossible, but cached usage can rot via out-of-band mutation)
_FULL_SWEEP_EVERY = 16


@dataclasses.dataclass
class BucketUsage:
    objects: int = 0  # latest, non-delete-marker versions
    versions: int = 0  # every journal entry incl. markers
    delete_markers: int = 0
    size: int = 0  # logical (client-visible) bytes, latest versions
    versions_size: int = 0  # logical bytes across ALL versions

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class DataUsage:
    """The cluster usage snapshot (madmin DataUsageInfo shape)."""

    last_update_ns: int = 0
    # bloom cycle index of the sweep that produced this snapshot
    cycles: int = 0
    buckets: "dict[str, BucketUsage]" = dataclasses.field(
        default_factory=dict
    )

    @property
    def objects_total(self) -> int:
        return sum(b.objects for b in self.buckets.values())

    @property
    def size_total(self) -> int:
        return sum(b.size for b in self.buckets.values())

    def to_dict(self) -> dict:
        return {
            "last_update_ns": self.last_update_ns,
            "cycles": self.cycles,
            "objects_total": self.objects_total,
            "size_total": self.size_total,
            "buckets_count": len(self.buckets),
            "buckets": {
                name: u.to_dict() for name, u in self.buckets.items()
            },
        }


class DataCrawler:
    """Background sweep thread; ``crawl_once`` is also callable
    directly (tests, admin-triggered scans)."""

    def __init__(
        self,
        object_layer,
        bucket_meta,
        interval_s: float = 60.0,
        events=None,
        ensure_event_rules=None,
        sleep_every: int = 256,
        sleep_s: float = 0.05,
        replication=None,
        tracker=None,
        cycle_bloom=None,
        leader_lock=None,
        heal_hook=None,
    ):
        self._ol = object_layer
        self._meta = bucket_meta
        self._interval = interval_s
        self._events = events
        # data-update tracker: local instance, or a callable
        # (oldest, current) -> BloomResponse that unions the cluster's
        # filters (distributed mode); cycle_bloom wins when both given
        self._tracker = tracker
        self._cycle_bloom = cycle_bloom
        # distributed mode: a cluster-wide lock elects ONE sweeping
        # node per cycle (the reference serializes runDataCrawler
        # behind a leader lock for the same reason) - without it every
        # node would rotate every peer's bloom tracker with its own
        # unsynchronized counter and double-run lifecycle deletes
        self._leader_lock = leader_lock
        # heal-on-crawl (the reference's healObjects pass inside the
        # data scanner): on FULL sweeps, latest versions get a dry-run
        # shard probe and damaged objects are queued here
        self._heal_hook = heal_hook
        self._heal_sweep = False  # set per sweep in _crawl_locked
        # ReplicationPool for the healReplication catch-up pass
        self._replication = replication
        # server callback hydrating a bucket's notification rules
        # before we fire (http.py ensure_event_rules); without it a
        # freshly restarted server would drop every expiry event
        self._ensure_event_rules = ensure_event_rules
        self._sleep_every = sleep_every
        self._sleep_s = sleep_s
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._mu = threading.Lock()
        self._crawl_mu = threading.Lock()  # one sweep at a time
        self._usage = self._load_usage()

    # -- usage persistence (data-usage.go storeDataUsageInBackend) --------

    def _load_usage(self) -> DataUsage:
        buf = io.BytesIO()
        try:
            self._ol.get_object(META_BUCKET, USAGE_PATH, buf)
            doc = json.loads(buf.getvalue())
            return DataUsage(
                last_update_ns=doc.get("last_update_ns", 0),
                cycles=doc.get("cycles", 0),
                buckets={
                    name: BucketUsage(**u)
                    for name, u in doc.get("buckets", {}).items()
                },
            )
        except Exception:  # noqa: BLE001 - cold start
            return DataUsage()

    def _store_usage(self, usage: DataUsage) -> None:
        raw = json.dumps(usage.to_dict()).encode()
        try:
            self._ol.put_object(
                META_BUCKET, USAGE_PATH, io.BytesIO(raw), len(raw)
            )
        except Exception as exc:
            _log.debug("usage cache store failed; next cycle retries", extra=kv(err=str(exc)))

    def usage(self) -> DataUsage:
        with self._mu:
            return self._usage

    # -- lifecycle --------------------------------------------------------

    def _bucket_lifecycle(self, bucket: str) -> "Lifecycle | None":
        try:
            raw = self._meta.get(bucket).lifecycle_xml
        except Exception:  # noqa: BLE001
            return None
        if not raw:
            return None
        try:
            return Lifecycle.from_xml(raw.encode())
        except LifecycleError:
            return None

    def _apply(self, bucket: str, oi, lc: "Lifecycle | None",
               versioned: bool, suspended: bool) -> bool:
        """Returns True when the version was expired (skip in usage)."""
        if lc is None:
            return False
        from ..ilm.lifecycle import ObjectOpts

        action = lc.compute_action(
            ObjectOpts(
                name=oi.name,
                mod_time_ns=oi.mod_time_ns,
                is_latest=oi.is_latest,
                delete_marker=oi.delete_marker,
                num_versions=getattr(oi, "num_versions", 1),
                successor_mod_time_ns=getattr(
                    oi, "successor_mod_time_ns", 0
                ),
                user_tags=(oi.user_defined or {}).get(
                    "x-amz-tagging", ""
                ),
            )
        )
        dinfo = None
        try:
            if action == Action.DELETE:
                # current-version expiry: a versioning-enabled OR
                # -suspended bucket mints a marker / replaces the null
                # version - passing versioned=False there would
                # recursively destroy every noncurrent version
                dinfo = self._ol.delete_object(
                    bucket, oi.name, "",
                    versioned=versioned, version_suspended=suspended,
                )
            elif action == Action.DELETE_VERSION:
                vid = oi.version_id or "null"
                self._ol.delete_object(bucket, oi.name, vid)
            else:
                return False
        except Exception:  # noqa: BLE001 - racing deletes are fine
            return False
        if self._events is not None:
            from ..event.event import Event, EventName

            if self._ensure_event_rules is not None:
                try:
                    self._ensure_event_rules(bucket)
                except Exception as exc:
                    _log.debug("event-rule preload failed", extra=kv(err=str(exc)))
            made_marker = dinfo is not None and dinfo.delete_marker
            self._events.send(
                Event(
                    name=EventName.OBJECT_REMOVED_DELETE_MARKER
                    if made_marker
                    else EventName.OBJECT_REMOVED_DELETE,
                    bucket=bucket,
                    object_key=oi.name,
                    version_id=(
                        dinfo.version_id if made_marker else oi.version_id
                    ),
                )
            )
        return True

    def _abort_stale_uploads(
        self, bucket: str, lc: "Lifecycle | None"
    ) -> int:
        if lc is None:
            return 0
        aborted = 0
        try:
            uploads = self._ol.list_multipart_uploads(bucket)
        except Exception:  # noqa: BLE001
            return 0
        for up in uploads:
            cutoff = lc.abort_multipart_before_ns(up.object)
            if cutoff is None or up.initiated_ns >= cutoff:
                continue
            try:
                self._ol.abort_multipart_upload(
                    bucket, up.object, up.upload_id
                )
                aborted += 1
            except Exception:  # noqa: BLE001
                continue
        return aborted

    # -- the sweep --------------------------------------------------------

    def crawl_once(self, force: bool = False) -> DataUsage:
        # one sweep at a time: an admin-triggered crawl and the
        # background cycle must not interleave deletes or publish
        # out-of-order usage snapshots
        with self._crawl_mu:
            if self._leader_lock is None:
                return self._crawl_locked()
            from ..dsync.namespace import LockTimeout

            try:
                with self._leader_lock():
                    # the lock serializes sweeps; ONE sweep per
                    # interval needs a freshness gate too, or K nodes
                    # each sweep the shared namespace once per
                    # interval, staggered by lock turnover
                    prev = self._load_usage()
                    if not force:
                        age_ns = time.time_ns() - prev.last_update_ns
                        # a NEGATIVE age (another node's future clock,
                        # or NTP stepping ours back) must read as
                        # stale, or a dead fast-clock leader would
                        # gate the whole cluster off sweeping
                        if (
                            prev.last_update_ns
                            and 0
                            <= age_ns
                            < self._effective_interval() * 0.5e9
                        ):
                            with self._mu:
                                self._usage = prev
                            return prev
                    return self._crawl_locked(prev)
            except LockTimeout:
                # another node holds crawl leadership this cycle;
                # serve ITS published numbers, not our boot snapshot
                fresh = self._load_usage()
                if fresh.last_update_ns:
                    with self._mu:
                        self._usage = fresh
                return self.usage()

    def _rotate_bloom(self, oldest: int, current: int):
        """Cluster-union update filter for [oldest, current), or None
        when no tracker is attached / the rotation failed."""
        try:
            if self._cycle_bloom is not None:
                return self._cycle_bloom(oldest, current)
            if self._tracker is not None:
                return self._tracker.cycle_filter(oldest, current)
        except Exception:  # noqa: BLE001 - a broken filter only
            return None  # disables skipping, never the sweep
        return None

    def _bucket_needs_sweep(self, bucket: str) -> bool:
        """Buckets where a sweep does WORK (lifecycle, FIFO quota,
        replication catch-up) are never bloom-skipped: time passing
        changes what those subsystems must do even with zero writes."""
        if self._bucket_lifecycle(bucket) is not None:
            return True
        from ..objectlayer import quota as quotamod

        qcfg = quotamod.config_for(self._meta, bucket)
        if qcfg is not None and qcfg.quota_type == "fifo":
            return True
        repl = self._replication
        return repl is not None and repl.config_for(bucket) is not None

    def _crawl_locked(self, prev: "DataUsage | None" = None) -> DataUsage:
        # re-read the persisted snapshot (unless the caller already
        # did): in distributed mode crawl leadership floats between
        # nodes and the cycle counter lives in the (shared) usage
        # document, not in process memory - a node that was follower
        # for N cycles must not rewind the cluster's bloom trackers
        # with its stale cached counter
        if prev is None:
            prev = self._load_usage()
        if prev.last_update_ns == 0 and prev.cycles == 0:
            prev = self.usage()  # store unreadable: trust memory
        next_cycle = prev.cycles + 1
        usage = DataUsage(
            last_update_ns=time.time_ns(), cycles=next_cycle
        )
        try:
            buckets = self._ol.list_buckets()
        except Exception:  # noqa: BLE001
            return prev
        resp = self._rotate_bloom(prev.cycles, next_cycle)
        full_sweep = next_cycle % _FULL_SWEEP_EVERY == 0
        skip_ok = (
            resp is not None and resp.complete and not full_sweep
        )
        # shard-health probes ride the forced full sweep only: a
        # dry-run heal per object is too heavy for every cycle (the
        # reference gates its crawler heal the same way)
        self._heal_sweep = self._heal_hook is not None and (
            full_sweep or prev.cycles == 0
        )
        for b in buckets:
            bucket = b.name
            if bucket.startswith("."):  # reserved meta volumes
                continue
            prior = prev.buckets.get(bucket)
            if (
                skip_ok
                and prior is not None
                and not resp.filter.contains_dir(bucket)
                and not self._bucket_needs_sweep(bucket)
            ):
                usage.buckets[bucket] = prior  # clean: reuse as-is
                continue
            usage.buckets[bucket] = self._crawl_bucket(bucket)
        with self._mu:
            self._usage = usage
        self._store_usage(usage)
        return usage

    def _crawl_bucket(self, bucket: str) -> BucketUsage:
        lc = self._bucket_lifecycle(bucket)
        versioned = suspended = False
        try:
            bm = self._meta.get(bucket)
            versioned = bm.versioning_enabled
            suspended = bm.versioning_suspended
        except Exception as exc:
            _log.debug("bucket versioning lookup failed", extra=kv(err=str(exc)))
        bu = BucketUsage()
        seen = 0
        # latest live versions - accumulated ONLY when a FIFO quota is
        # configured (the list is O(objects); without a quota the crawl
        # stays streaming)
        from ..objectlayer import quota as quotamod

        qcfg = quotamod.config_for(self._meta, bucket)
        fifo = qcfg is not None and qcfg.quota_type == "fifo"
        latest: list = []
        repl = self._replication
        repl_cfg = repl.config_for(bucket) if repl is not None else None

        def process_key(rows: list) -> None:
            """All versions of ONE key (journal order: newest first);
            grouping here gives lifecycle real num_versions and
            successor mod times."""
            nonlocal seen
            for idx, oi in enumerate(rows):
                seen += 1
                if self._sleep_every and seen % self._sleep_every == 0:
                    time.sleep(self._sleep_s)  # crawl throttle
                oi.num_versions = len(rows)
                oi.successor_mod_time_ns = (
                    rows[idx - 1].mod_time_ns if idx else 0
                )
                if self._apply(bucket, oi, lc, versioned, suspended):
                    continue
                bu.versions += 1
                if oi.delete_marker:
                    bu.delete_markers += 1
                else:
                    bu.versions_size += oi.size
                if oi.is_latest and not oi.delete_marker:
                    bu.objects += 1
                    bu.size += oi.size
                    # read-cache heat: a live latest version earns one
                    # admission-frequency credit, so objects that
                    # survive crawls win the TinyLFU contest against
                    # one-shot scan traffic before their first GET
                    try:
                        rcache.seed_heat(bucket, oi.name, hits=1)
                    except Exception as exc:  # noqa: BLE001
                        _log.debug(
                            "read-cache heat seed failed",
                            extra=kv(err=str(exc)),
                        )
                    if self._heal_sweep:
                        self._probe_heal(bucket, oi)
                    if fifo:
                        latest.append(oi)
                    # replication catch-up: PENDING/FAILED never made
                    # it to the target - queue it again
                    if repl_cfg is not None:
                        status = oi.user_defined.get(
                            "x-amz-replication-status", ""
                        )
                        if status in (
                            "PENDING", "FAILED"
                        ) and repl_cfg.rule_for(oi.name):
                            repl.queue(bucket, oi.name, oi.version_id)

        key_marker = vid_marker = ""
        group: list = []
        while True:
            try:
                page = self._ol.list_object_versions(
                    bucket, "", key_marker, vid_marker, "", 1000
                )
            except NotImplementedError:
                # FS backend: no version journal - stream the flat
                # namespace (marker-paged list_objects would re-walk
                # and re-sort the whole bucket per page)
                walker = getattr(self._ol, "iter_all_objects", None)
                try:
                    if walker is not None:
                        for oi in walker(bucket):
                            process_key([oi])
                    else:
                        marker = ""
                        while True:
                            res = self._ol.list_objects(
                                bucket, "", marker, "", 1000
                            )
                            for oi in res.objects:
                                process_key([oi])
                            if not res.is_truncated:
                                break
                            marker = res.next_marker
                except Exception as exc:
                    _log.debug("replication catch-up sweep failed", extra=kv(err=str(exc)))
                group = []
                break
            except Exception:  # noqa: BLE001
                break
            for oi in page.versions:
                if group and oi.name != group[0].name:
                    process_key(group)
                    group = []
                group.append(oi)
            if not page.is_truncated:
                break
            # a key's versions may span pages: keep buffering the
            # current group across the boundary
            key_marker = page.next_key_marker
            vid_marker = page.next_version_id_marker
        if group:
            process_key(group)
        self._abort_stale_uploads(bucket, lc)
        self._enforce_fifo_quota(bucket, bu, latest, versioned, suspended)
        return bu

    def _probe_heal(self, bucket: str, oi) -> None:
        """Metadata-only shard probe; queue a real heal for damaged
        objects (healObject path of the reference's crawler).  The
        probe is lock-free and reads no shard data - the expensive
        verify happens inside the queued heal itself."""
        probe = getattr(self._ol, "probe_object_health", None)
        if probe is None:
            self._heal_sweep = False  # backend has no heal surface
            return
        try:
            res = probe(bucket, oi.name, oi.version_id)
        except Exception:  # noqa: BLE001
            return
        if res.get("outdated"):
            try:
                self._heal_hook(bucket, oi.name, oi.version_id)
            except Exception as exc:
                _log.debug("heal hook failed for crawled object", extra=kv(err=str(exc)))

    def _enforce_fifo_quota(
        self, bucket, bu, latest, versioned, suspended
    ) -> None:
        """FIFO quota: evict oldest objects until the bucket fits
        (bucket-quota.go enforceFIFOQuota on the crawler pass)."""
        from ..objectlayer import objectlock as olock, quota as quotamod

        cfg = quotamod.config_for(self._meta, bucket)
        if cfg is None or cfg.quota_type != "fifo":
            return
        over = bu.size - cfg.quota
        if over <= 0:
            return
        for oi in sorted(latest, key=lambda o: o.mod_time_ns):
            if over <= 0:
                break
            # WORM-protected versions are never evicted
            # (enforceRetentionForDeletion guard in the reference)
            if olock.retention_blocks_delete(oi.user_defined):
                continue
            try:
                self._ol.delete_object(
                    bucket, oi.name, oi.version_id,
                    versioned=versioned, version_suspended=suspended,
                )
            except Exception:  # noqa: BLE001
                continue
            over -= oi.size
            bu.size -= oi.size
            bu.objects -= 1

    # -- lifecycle of the thread itself -----------------------------------

    def start(self) -> "DataCrawler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="data-crawler"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _effective_interval(self) -> float:
        try:
            v = float(
                os.environ.get("MINIO_TPU_CRAWL_INTERVAL_S")
                or self._interval
            )
        except ValueError:
            return self._interval
        # floor of 1s: wait(0) would busy-loop full-cluster crawls
        import math

        if not math.isfinite(v) or v < 1.0:
            return max(self._interval, 1.0)
        return v

    def _run(self) -> None:
        # initial delay so boot IO settles (crawler waits a cycle)
        # interval re-read each cycle: runtime-editable via admin
        # set-config-kv (crawler.interval_s); malformed values must
        # never kill this thread
        while not self._stop.wait(self._effective_interval()):
            try:
                self.crawl_once()
            except Exception as exc:
                _log.warning("crawl cycle failed", extra=kv(err=str(exc)))
