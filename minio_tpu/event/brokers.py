"""Broker notification targets: Redis, NATS, Kafka
(pkg/event/target/{redis,nats,kafka}.go).

Redis and NATS speak their actual wire protocols over stdlib sockets
(RESP arrays / the NATS text protocol) - no client libraries in-image.
Kafka's binary protocol is not reimplemented here: KafkaTarget takes a
``producer`` with ``produce(topic, key, value)`` (a kafka client or an
in-process fake), matching how the reference delegates to sarama.
"""

from __future__ import annotations

import json
import socket
import threading

from .targets import TargetError


class RedisTarget:
    """RPUSH each event record onto a Redis list (the reference's
    access-format redis target, pkg/event/target/redis.go)."""

    def __init__(
        self,
        target_id: str,
        addr: str,
        key: str = "minioevents",
        password: str = "",
        timeout: float = 5.0,
    ):
        self.id = target_id
        self.arn = f"arn:minio:sqs::{target_id}:redis"
        host, _, port = addr.rpartition(":")
        if not host:
            raise TargetError(f"bad redis address {addr!r}")
        self.host, self.port = host, int(port)
        self.key = key
        self.password = password
        self._timeout = timeout
        self._mu = threading.Lock()
        self._sock: "socket.socket | None" = None

    # -- RESP encoding ---------------------------------------------------

    @staticmethod
    def _resp(*args: bytes) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            out.append(b"$%d\r\n%s\r\n" % (len(a), a))
        return b"".join(out)

    def _read_reply(self, f) -> bytes:
        line = f.readline()
        if not line:
            raise TargetError("redis connection closed")
        if line[:1] == b"-":
            raise TargetError(f"redis error: {line[1:].strip().decode()}")
        if line[:1] == b"$":  # bulk string
            n = int(line[1:])
            if n >= 0:
                f.read(n + 2)
        return line.strip()

    def _connect(self) -> socket.socket:
        s = socket.create_connection(
            (self.host, self.port), timeout=self._timeout
        )
        if self.password:
            f = s.makefile("rb")
            s.sendall(self._resp(b"AUTH", self.password.encode()))
            self._read_reply(f)
        return s

    def send(self, record: dict) -> None:
        body = json.dumps(record).encode()
        with self._mu:
            try:
                if self._sock is None:
                    self._sock = self._connect()
                s = self._sock
                s.sendall(
                    self._resp(b"RPUSH", self.key.encode(), body)
                )
                self._read_reply(s.makefile("rb"))
            except (OSError, TargetError):
                self._drop()
                raise TargetError(
                    f"redis {self.host}:{self.port} unreachable"
                ) from None

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._mu:
            self._drop()


class NATSTarget:
    """PUB each record to a NATS subject (pkg/event/target/nats.go),
    speaking the plain NATS text protocol."""

    def __init__(
        self,
        target_id: str,
        addr: str,
        subject: str = "minioevents",
        timeout: float = 5.0,
    ):
        self.id = target_id
        self.arn = f"arn:minio:sqs::{target_id}:nats"
        host, _, port = addr.rpartition(":")
        if not host:
            raise TargetError(f"bad nats address {addr!r}")
        self.host, self.port = host, int(port)
        self.subject = subject
        self._timeout = timeout
        self._mu = threading.Lock()
        self._sock: "socket.socket | None" = None
        self._file = None

    def _connect(self) -> None:
        s = socket.create_connection(
            (self.host, self.port), timeout=self._timeout
        )
        f = s.makefile("rb")
        info = f.readline()  # INFO {...}
        if not info.startswith(b"INFO"):
            s.close()
            raise TargetError("not a NATS server")
        s.sendall(b'CONNECT {"verbose":false}\r\n')
        self._sock, self._file = s, f

    def send(self, record: dict) -> None:
        body = json.dumps(record).encode()
        with self._mu:
            try:
                if self._sock is None:
                    self._connect()
                self._sock.sendall(
                    b"PUB %s %d\r\n%s\r\n"
                    % (self.subject.encode(), len(body), body)
                )
                # PING/PONG round trip confirms the server consumed it
                self._sock.sendall(b"PING\r\n")
                while True:
                    line = self._file.readline()
                    if not line:
                        raise TargetError("nats connection closed")
                    if line.startswith(b"PONG"):
                        break
                    if line.startswith(b"-ERR"):
                        raise TargetError(line.decode().strip())
            except (OSError, TargetError):
                self._drop()
                raise TargetError(
                    f"nats {self.host}:{self.port} unreachable"
                ) from None

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._file = None

    def close(self) -> None:
        with self._mu:
            self._drop()


class KafkaTarget:
    """Produce each record to a Kafka topic.  The binary protocol is
    delegated to ``producer`` (kafka-python / confluent client / test
    fake) with ``produce(topic, key, value)`` - mirroring the
    reference's sarama delegation (pkg/event/target/kafka.go)."""

    def __init__(self, target_id: str, topic: str, producer=None):
        self.id = target_id
        self.arn = f"arn:minio:sqs::{target_id}:kafka"
        self.topic = topic
        self.producer = producer

    def send(self, record: dict) -> None:
        if self.producer is None:
            raise TargetError("kafka producer not configured")
        key = record.get("Key", "")
        self.producer.produce(
            self.topic, key.encode(), json.dumps(record).encode()
        )

    def close(self) -> None:
        closer = getattr(self.producer, "close", None)
        if closer is not None:
            closer()
