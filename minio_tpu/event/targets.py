"""Notification targets (pkg/event/target/webhook.go et al).

Each target consumes S3 event records.  The webhook target POSTs the
record JSON with a bounded retry-on-reconnect, the log-file target
appends JSON lines (the minio ``notify_webhook`` / audit-log shapes),
and MemoryTarget captures events for tests and the admin trace.

Targets are configured from the environment, mirroring the reference's
``MINIO_NOTIFY_WEBHOOK_ENABLE_<ID>`` convention
(cmd/config/notify/parse.go)::

    MINIO_TPU_NOTIFY_WEBHOOK_ENABLE_PRIMARY=on
    MINIO_TPU_NOTIFY_WEBHOOK_ENDPOINT_PRIMARY=http://host:port/path

yields a target with ARN ``arn:minio:sqs::PRIMARY:webhook``.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import urllib.parse

from ..utils.log import kv, logger

_log = logger("event")


class TargetError(Exception):
    pass


class WebhookTarget:
    """POST each event record to an HTTP endpoint
    (pkg/event/target/webhook.go:150 send)."""

    def __init__(self, target_id: str, endpoint: str, timeout: float = 5.0):
        self.id = target_id
        self.arn = f"arn:minio:sqs::{target_id}:webhook"
        self.endpoint = endpoint
        self._timeout = timeout
        u = urllib.parse.urlsplit(endpoint)
        if u.scheme not in ("http", "https") or not u.hostname:
            raise TargetError(f"bad webhook endpoint {endpoint!r}")
        self._host = u.hostname
        self._port = u.port or (443 if u.scheme == "https" else 80)
        self._path = u.path or "/"
        if u.query:
            self._path += "?" + u.query
        self._https = u.scheme == "https"
        self._local = threading.local()

    def _conn(self):
        c = getattr(self._local, "conn", None)
        if c is None:
            cls = (
                http.client.HTTPSConnection
                if self._https
                else http.client.HTTPConnection
            )
            c = cls(self._host, self._port, timeout=self._timeout)
            self._local.conn = c
        return c

    def _drop(self):
        c = getattr(self._local, "conn", None)
        if c is not None:
            try:
                c.close()
            except Exception as exc:
                _log.debug("target connection close failed", extra=kv(err=str(exc)))
            self._local.conn = None

    def send(self, record: dict) -> None:
        body = json.dumps(record).encode()
        headers = {
            "Content-Type": "application/json",
            "Content-Length": str(len(body)),
        }
        for attempt in (0, 1):
            conn = self._conn()
            try:
                conn.request("POST", self._path, body=body, headers=headers)
                resp = conn.getresponse()
                resp.read()
                break
            except (OSError, http.client.HTTPException):
                self._drop()
                if attempt == 1:
                    raise TargetError(
                        f"webhook {self.endpoint} unreachable"
                    ) from None
        if resp.status // 100 != 2:
            raise TargetError(
                f"webhook {self.endpoint}: HTTP {resp.status}"
            )

    def close(self) -> None:
        self._drop()


class LogFileTarget:
    """Append events as JSON lines (an event audit trail; the
    minio ``notify_webhook``-to-file dev pattern)."""

    def __init__(self, target_id: str, path: str):
        self.id = target_id
        self.arn = f"arn:minio:sqs::{target_id}:logfile"
        self.path = path
        self._mu = threading.Lock()

    def send(self, record: dict) -> None:
        line = json.dumps(record) + "\n"
        with self._mu:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line)

    def close(self) -> None:
        pass


class MemoryTarget:
    """In-process capture (tests + admin introspection)."""

    def __init__(self, target_id: str = "memory"):
        self.id = target_id
        self.arn = f"arn:minio:sqs::{target_id}:memory"
        self.records: "list[dict]" = []
        self._mu = threading.Lock()

    def send(self, record: dict) -> None:
        with self._mu:
            self.records.append(record)

    def close(self) -> None:
        pass


def targets_from_env(environ=None) -> "list":
    """Build the target list from MINIO_TPU_NOTIFY_* variables
    (cmd/config/notify/parse.go GetNotifyWebhook and siblings).

    Any target gains at-least-once disk buffering when
    ``MINIO_TPU_NOTIFY_<KIND>_QUEUE_DIR_<ID>`` is set (the reference's
    per-target queueStore)."""
    env = os.environ if environ is None else environ
    out: list = []
    for key, val in sorted(env.items()):
        if not key.startswith("MINIO_TPU_NOTIFY_") or "_ENABLE_" not in key:
            continue
        if val != "on":
            continue
        prefix, _, tid = key.partition("_ENABLE_")
        kind = prefix[len("MINIO_TPU_NOTIFY_"):]
        target = None
        try:
            if kind == "WEBHOOK":
                ep = env.get(f"MINIO_TPU_NOTIFY_WEBHOOK_ENDPOINT_{tid}", "")
                if ep:
                    target = WebhookTarget(tid, ep)
            elif kind == "LOGFILE":
                path = env.get(f"MINIO_TPU_NOTIFY_LOGFILE_PATH_{tid}", "")
                if path:
                    target = LogFileTarget(tid, path)
            elif kind == "REDIS":
                from .brokers import RedisTarget

                addr = env.get(f"MINIO_TPU_NOTIFY_REDIS_ADDRESS_{tid}", "")
                if addr:
                    target = RedisTarget(
                        tid, addr,
                        key=env.get(
                            f"MINIO_TPU_NOTIFY_REDIS_KEY_{tid}",
                            "minioevents",
                        ),
                        password=env.get(
                            f"MINIO_TPU_NOTIFY_REDIS_PASSWORD_{tid}", ""
                        ),
                    )
            elif kind == "NATS":
                from .brokers import NATSTarget

                addr = env.get(f"MINIO_TPU_NOTIFY_NATS_ADDRESS_{tid}", "")
                if addr:
                    target = NATSTarget(
                        tid, addr,
                        subject=env.get(
                            f"MINIO_TPU_NOTIFY_NATS_SUBJECT_{tid}",
                            "minioevents",
                        ),
                    )
        except TargetError:
            continue  # malformed config: skip this target
        if target is None:
            continue
        qdir = env.get(f"MINIO_TPU_NOTIFY_{kind}_QUEUE_DIR_{tid}", "")
        if qdir:
            from .queuestore import QueuedTarget

            target = QueuedTarget(target, qdir)
        out.append(target)
    return out
