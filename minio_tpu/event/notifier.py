"""EventNotifier: rules + targets + async dispatch
(cmd/notification.go NotificationSys front half +
pkg/event/targetlist.go send loop).

The S3 request path only constructs the event and enqueues it; a
dispatch thread matches rules and drives targets, so a slow webhook
never stalls a PUT (the reference's per-target async queues,
pkg/event/targetlist.go:155).  Delivery is at-most-once with bounded
buffering - the queue drops the oldest events past ``maxlen`` exactly
like the reference's store-less targets drop on a full channel.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time

from .event import Event
from .rules import NotificationConfig, RulesMap

from ..utils.log import kv, logger

_log = logger("event")

_QUEUE_MAX = 10_000


class EventNotifier:
    def __init__(self, targets: "list | None" = None):
        self.rules = RulesMap()
        self._targets: "dict[str, object]" = {}
        for t in targets or []:
            self.register_target(t)
        self._queue: "collections.deque" = collections.deque(
            maxlen=_QUEUE_MAX
        )
        # live listeners (ListenBucketNotification): every event
        # fans out here regardless of configured bucket rules.  The
        # subscription is SCOPED per bucket so one watcher does not
        # de-optimize the fast path for every other bucket
        from ..utils.pubsub import PubSub

        self.listeners = PubSub(maxlen=1000)
        self._listener_mu = threading.Lock()
        self._listener_counts: "dict[str, int]" = {}
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._seq = itertools.count(1)
        self._thread: "threading.Thread | None" = None

    # -- configuration ----------------------------------------------------

    def register_target(self, target) -> None:
        self._targets[target.arn] = target

    @property
    def known_arns(self) -> "set[str]":
        return set(self._targets)

    def set_bucket_config(
        self, bucket: str, config: NotificationConfig
    ) -> None:
        config.validate(self.known_arns)
        self.rules.set(bucket, config)

    def load_bucket_config(self, bucket: str, raw_xml: str) -> None:
        """Rebuild rules from a persisted document (boot / peer
        invalidation path); unknown ARNs are tolerated here - the
        target may exist on the node that stored the config."""
        cfg = NotificationConfig.from_xml(raw_xml.encode())
        self.rules.set(bucket, cfg)

    def remove_bucket(self, bucket: str) -> None:
        self.rules.remove(bucket)

    # -- dispatch ---------------------------------------------------------

    def start(self) -> "EventNotifier":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="event-notifier"
            )
            self._thread.start()
        return self

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def subscribe_listener(self, bucket: str):
        """Live subscription for one bucket's events."""
        sub = self.listeners.subscribe()
        with self._listener_mu:
            self._listener_counts[bucket] = (
                self._listener_counts.get(bucket, 0) + 1
            )
        return sub

    def unsubscribe_listener(self, bucket: str, sub) -> None:
        self.listeners.unsubscribe(sub)
        with self._listener_mu:
            n = self._listener_counts.get(bucket, 0) - 1
            if n > 0:
                self._listener_counts[bucket] = n
            else:
                self._listener_counts.pop(bucket, None)

    def has_listeners(self, bucket: str) -> bool:
        with self._listener_mu:
            return bucket in self._listener_counts

    def send(self, event: Event) -> None:
        """Fast path: O(1) enqueue; rule matching happens off-thread."""
        if not self.rules.has_rules(event.bucket) and not (
            self.has_listeners(event.bucket)
        ):
            return
        if not event.sequencer:
            event.sequencer = f"{next(self._seq):016X}"
        if not event.time_ns:
            event.time_ns = time.time_ns()
        self._queue.append(event)
        self._wake.set()

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until the queue drains (tests / graceful shutdown)."""
        deadline = time.monotonic() + timeout_s
        while self._queue and time.monotonic() < deadline:
            time.sleep(0.01)
        return not self._queue

    def _run(self) -> None:
        while not self._stop.is_set():
            if not self._queue:
                self._wake.wait(timeout=0.5)
                self._wake.clear()
                continue
            try:
                ev = self._queue.popleft()
            except IndexError:
                continue
            self._dispatch(ev)

    def _dispatch(self, ev: Event) -> None:
        if self.has_listeners(ev.bucket):
            self.listeners.publish(ev)
        arns = self.rules.match(ev.bucket, ev.name, ev.object_key)
        if not arns:
            return
        record = {"EventName": ev.name, "Key": f"{ev.bucket}/{ev.object_key}",
                  "Records": [ev.to_record()]}
        for arn in arns:
            target = self._targets.get(arn)
            if target is None:
                continue
            try:
                target.send(record)
            except Exception as exc:
                _log.debug("event target send failed; at-most-once drop", extra=kv(err=str(exc)))
