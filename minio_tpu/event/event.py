"""S3 event records (pkg/event/event.go).

Event names form a hierarchy: ``s3:ObjectCreated:Put`` is matched by the
wildcard ``s3:ObjectCreated:*`` (the expandEventName mask logic,
pkg/event/name.go:60-106).  ``to_record`` renders the AWS S3 event
record JSON (the Records[] element every notification target consumes,
pkg/event/event.go:76-113).
"""

from __future__ import annotations

import dataclasses
import datetime
import urllib.parse


class EventName:
    OBJECT_CREATED_PUT = "s3:ObjectCreated:Put"
    OBJECT_CREATED_POST = "s3:ObjectCreated:Post"
    OBJECT_CREATED_COPY = "s3:ObjectCreated:Copy"
    OBJECT_CREATED_COMPLETE_MULTIPART = (
        "s3:ObjectCreated:CompleteMultipartUpload"
    )
    OBJECT_REMOVED_DELETE = "s3:ObjectRemoved:Delete"
    OBJECT_REMOVED_DELETE_MARKER = (
        "s3:ObjectRemoved:DeleteMarkerCreated"
    )
    OBJECT_ACCESSED_GET = "s3:ObjectAccessed:Get"
    OBJECT_ACCESSED_HEAD = "s3:ObjectAccessed:Head"

    ALL = (
        OBJECT_CREATED_PUT,
        OBJECT_CREATED_POST,
        OBJECT_CREATED_COPY,
        OBJECT_CREATED_COMPLETE_MULTIPART,
        OBJECT_REMOVED_DELETE,
        OBJECT_REMOVED_DELETE_MARKER,
        OBJECT_ACCESSED_GET,
        OBJECT_ACCESSED_HEAD,
    )

    @staticmethod
    def expand(name: str) -> "tuple[str, ...]":
        """A wildcard covers every concrete name under its prefix
        (pkg/event/name.go Expand)."""
        if name.endswith(":*"):
            prefix = name[:-1]  # keep the trailing colon
            return tuple(
                n for n in EventName.ALL if n.startswith(prefix)
            )
        return (name,)

    @staticmethod
    def valid(name: str) -> bool:
        return bool(EventName.expand(name)) and (
            name in EventName.ALL or name.endswith(":*")
        )


@dataclasses.dataclass
class Identity:
    principal_id: str = ""
    source_ip: str = ""


@dataclasses.dataclass
class Event:
    """One bucket event; rendered as an AWS S3 record."""

    name: str
    bucket: str
    object_key: str
    etag: str = ""
    size: int = 0
    version_id: str = ""
    sequencer: str = ""
    identity: Identity = dataclasses.field(default_factory=Identity)
    time_ns: int = 0
    endpoint: str = ""

    def to_record(self) -> dict:
        ts = datetime.datetime.fromtimestamp(
            self.time_ns / 1e9, tz=datetime.timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"
        return {
            "eventVersion": "2.1",
            "eventSource": "minio-tpu:s3",
            "awsRegion": "",
            "eventTime": ts,
            "eventName": self.name[len("s3:"):],
            "userIdentity": {
                "principalId": self.identity.principal_id
            },
            "requestParameters": {
                "sourceIPAddress": self.identity.source_ip
            },
            "responseElements": {
                "x-minio-origin-endpoint": self.endpoint,
            },
            "s3": {
                "s3SchemaVersion": "1.0",
                "bucket": {
                    "name": self.bucket,
                    "ownerIdentity": {
                        "principalId": self.identity.principal_id
                    },
                    "arn": f"arn:aws:s3:::{self.bucket}",
                },
                "object": {
                    "key": urllib.parse.quote(self.object_key),
                    "size": self.size,
                    "eTag": self.etag,
                    "versionId": self.version_id,
                    "sequencer": self.sequencer,
                },
            },
        }


def matches_filter(
    ev: "Event", bucket: str, names, prefix: str, suffix: str
) -> bool:
    """The ListenBucketNotification match predicate, shared by the
    local stream loop and the remote listenbuf RPC so local and
    cluster watchers can never disagree on what matches."""
    if ev.bucket != bucket:
        return False
    if names and ev.name not in names:
        return False
    key = ev.object_key
    return key.startswith(prefix) and key.endswith(suffix)


def to_listen_record(ev: "Event") -> dict:
    """Wire shape of one notification line/record."""
    return {
        "EventName": ev.name,
        "Key": f"{ev.bucket}/{ev.object_key}",
        "Records": [ev.to_record()],
    }
