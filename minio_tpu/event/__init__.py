"""Bucket event notification subsystem (pkg/event, ~7.9k LoC in the
reference: pkg/event/event.go name masks, pkg/event/rules.go,
pkg/event/targetlist.go; wired by cmd/notification.go and
cmd/bucket-notification-handlers.go)."""

from .event import Event, EventName, Identity  # noqa: F401
from .notifier import EventNotifier  # noqa: F401
from .rules import NotificationConfig, RulesMap  # noqa: F401
from .targets import (  # noqa: F401
    LogFileTarget,
    MemoryTarget,
    WebhookTarget,
    targets_from_env,
)
