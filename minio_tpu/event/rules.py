"""NotificationConfiguration parsing + rule matching
(pkg/event/config.go ParseConfig, pkg/event/rules.go RulesMap).

The wire format is the S3 XML document::

    <NotificationConfiguration>
      <QueueConfiguration>
        <Id>1</Id>
        <Queue>arn:minio:sqs::primary:webhook</Queue>
        <Event>s3:ObjectCreated:*</Event>
        <Filter><S3Key>
          <FilterRule><Name>prefix</Name><Value>logs/</Value></FilterRule>
          <FilterRule><Name>suffix</Name><Value>.txt</Value></FilterRule>
        </S3Key></Filter>
      </QueueConfiguration>
    </NotificationConfiguration>

Validation mirrors the reference: unknown event names and ARNs not
registered in the target list are rejected at PUT time
(config.Validate, pkg/event/config.go:280-303).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import xml.etree.ElementTree as ET

from .event import EventName

S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"


class NotificationError(Exception):
    """Malformed or invalid notification configuration."""


def _local(tag: str) -> str:
    return tag.split("}")[-1]


def _find_all(el: ET.Element, name: str) -> "list[ET.Element]":
    return [c for c in el.iter() if _local(c.tag) == name]


def _child_text(el: ET.Element, name: str) -> str:
    for c in el:
        if _local(c.tag) == name:
            return c.text or ""
    return ""


@dataclasses.dataclass
class Queue:
    """One QueueConfiguration entry."""

    id: str
    arn: str
    events: "list[str]"
    prefix: str = ""
    suffix: str = ""

    def __post_init__(self):
        # expanded once here so matches() on the dispatch hot path is a
        # set lookup, not a rebuild per event
        self._covered = frozenset(
            n for e in self.events for n in EventName.expand(e)
        )

    def matches(self, event_name: str, key: str) -> bool:
        if event_name not in self._covered:
            return False
        if self.prefix and not key.startswith(self.prefix):
            return False
        if self.suffix and not key.endswith(self.suffix):
            return False
        return True


@dataclasses.dataclass
class NotificationConfig:
    queues: "list[Queue]" = dataclasses.field(default_factory=list)

    @classmethod
    def from_xml(cls, raw: bytes) -> "NotificationConfig":
        if not raw.strip():
            return cls()
        try:
            root = ET.fromstring(raw)
        except ET.ParseError as e:
            raise NotificationError(f"malformed XML: {e}") from None
        if _local(root.tag) != "NotificationConfiguration":
            raise NotificationError(
                f"unexpected root element {_local(root.tag)}"
            )
        queues = []
        for qc in _find_all(root, "QueueConfiguration"):
            arn = _child_text(qc, "Queue")
            events = [
                (e.text or "").strip()
                for e in qc
                if _local(e.tag) == "Event"
            ]
            if not arn or not events:
                raise NotificationError(
                    "QueueConfiguration needs a Queue ARN and >=1 Event"
                )
            for name in events:
                if not EventName.valid(name):
                    raise NotificationError(f"unknown event {name!r}")
            prefix = suffix = ""
            for fr in _find_all(qc, "FilterRule"):
                fr_name = _child_text(fr, "Name").lower()
                fr_val = _child_text(fr, "Value")
                if fr_name == "prefix":
                    prefix = fr_val
                elif fr_name == "suffix":
                    suffix = fr_val
                else:
                    raise NotificationError(
                        f"unsupported filter rule {fr_name!r}"
                    )
            queues.append(
                Queue(
                    id=_child_text(qc, "Id"),
                    arn=arn,
                    events=events,
                    prefix=prefix,
                    suffix=suffix,
                )
            )
        # the reference also accepts Topic/CloudFunction configurations;
        # minio routes everything through queue targets, as do we
        if _find_all(root, "TopicConfiguration") or _find_all(
            root, "CloudFunctionConfiguration"
        ):
            raise NotificationError(
                "only QueueConfiguration targets are supported"
            )
        return cls(queues)

    def validate(self, known_arns: "set[str]") -> None:
        """Reject ARNs with no registered target (config.Validate)."""
        for q in self.queues:
            if not any(
                fnmatch.fnmatchcase(q.arn, pat) or q.arn == pat
                for pat in known_arns
            ):
                raise NotificationError(
                    f"unregistered notification target {q.arn!r}"
                )

    def to_xml(self) -> bytes:
        root = ET.Element(
            "NotificationConfiguration", xmlns=S3_NS
        )
        for q in self.queues:
            qc = ET.SubElement(root, "QueueConfiguration")
            if q.id:
                ET.SubElement(qc, "Id").text = q.id
            ET.SubElement(qc, "Queue").text = q.arn
            for e in q.events:
                ET.SubElement(qc, "Event").text = e
            if q.prefix or q.suffix:
                f = ET.SubElement(qc, "Filter")
                s3k = ET.SubElement(f, "S3Key")
                for name, val in (
                    ("prefix", q.prefix),
                    ("suffix", q.suffix),
                ):
                    if val:
                        fr = ET.SubElement(s3k, "FilterRule")
                        ET.SubElement(fr, "Name").text = name
                        ET.SubElement(fr, "Value").text = val
        return (
            b'<?xml version="1.0" encoding="UTF-8"?>\n'
            + ET.tostring(root)
        )


class RulesMap:
    """bucket -> parsed config, with per-event target resolution
    (pkg/event/rules.go, cached per bucket like bucketRulesMap)."""

    def __init__(self):
        self._configs: "dict[str, NotificationConfig]" = {}

    def set(self, bucket: str, config: NotificationConfig) -> None:
        if config.queues:
            self._configs[bucket] = config
        else:
            self._configs.pop(bucket, None)

    def remove(self, bucket: str) -> None:
        self._configs.pop(bucket, None)

    def match(
        self, bucket: str, event_name: str, key: str
    ) -> "list[str]":
        """ARNs whose rules match this event."""
        cfg = self._configs.get(bucket)
        if cfg is None:
            return []
        return [
            q.arn
            for q in cfg.queues
            if q.matches(event_name, key)
        ]

    def has_rules(self, bucket: str) -> bool:
        return bucket in self._configs
