"""Persistent at-least-once event store
(pkg/event/target/queuestore.go + store.go sendEvents replay loop).

``QueueStore`` journals each undelivered event record as one JSON file
under a per-target directory (bounded by ``limit``, oldest kept - the
reference refuses new entries past maxLimit 10000).  ``QueuedTarget``
wraps any target with the store: a failed ``send`` parks the record on
disk and a replay thread retries in order once the target answers
again, so events fired while a sink is down are delivered after it
returns, surviving process restarts in between.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

from ..utils.log import kv, logger

_log = logger("event")

DEFAULT_LIMIT = 10_000
RETRY_INTERVAL_S = 5.0


class StoreFull(Exception):
    pass


class QueueStore:
    """Directory-backed FIFO of JSON event records."""

    def __init__(self, directory: str, limit: int = DEFAULT_LIMIT):
        self.dir = directory
        self.limit = limit
        self._mu = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        # counter maintained in memory: listing+sorting the backlog dir
        # per enqueue would make a filling store O(n^2)
        self._count = len(self.list())

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, key)

    def put(self, record: dict) -> str:
        """Persist one record; returns its key.  Keys sort in insertion
        order (time-prefixed) so replay preserves event order."""
        with self._mu:
            if self._count >= self.limit:
                raise StoreFull(f"store at limit {self.limit}")
            key = f"{time.time_ns():020d}-{uuid.uuid4().hex[:8]}.json"
            tmp = self._path(key + ".tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(record, f)
            os.replace(tmp, self._path(key))
            self._count += 1
            return key

    def get(self, key: str) -> dict:
        with open(self._path(key), encoding="utf-8") as f:
            return json.load(f)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            return
        with self._mu:
            self._count = max(0, self._count - 1)

    def list(self) -> "list[str]":
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return []
        return sorted(n for n in names if n.endswith(".json"))

    def count(self) -> int:
        return self._count


class QueuedTarget:
    """Wrap a target with at-least-once disk buffering.

    Live sends go straight through; a failure parks the record and
    every ``retry_interval_s`` the replay thread attempts the backlog
    in order, stopping at the first failure (the sink is still down).
    """

    def __init__(
        self,
        target,
        directory: str,
        limit: int = DEFAULT_LIMIT,
        retry_interval_s: float = RETRY_INTERVAL_S,
    ):
        self.inner = target
        self.id = target.id
        self.arn = target.arn
        self.store = QueueStore(directory, limit)
        self._interval = retry_interval_s
        self._stop = threading.Event()
        self._replay_mu = threading.Lock()
        self._thread = threading.Thread(
            target=self._replay_loop, daemon=True,
            name=f"event-store-{target.id}",
        )
        self._thread.start()

    def send(self, record: dict) -> None:
        if self.store.count():
            # order preservation: with a backlog, new events queue
            # behind it rather than jumping ahead
            self.store.put(record)
            return
        try:
            self.inner.send(record)
        except Exception:  # noqa: BLE001 - park it for replay
            self.store.put(record)

    def replay_once(self) -> int:
        """Attempt the backlog in order; returns how many delivered."""
        delivered = 0
        with self._replay_mu:
            for key in self.store.list():
                try:
                    record = self.store.get(key)
                except (OSError, ValueError):
                    self.store.delete(key)  # corrupt entry
                    continue
                try:
                    self.inner.send(record)
                except Exception:  # noqa: BLE001 - still down
                    break
                self.store.delete(key)
                delivered += 1
        return delivered

    def _replay_loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.replay_once()
            except Exception as exc:
                _log.warning("queuestore replay cycle failed", extra=kv(err=str(exc)))

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self.inner.close()
