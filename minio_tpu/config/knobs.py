"""The MINIO_TPU_* environment-knob registry (MTPU606's ground truth).

Every environment variable the tree reads must have a row here — name,
default, one-line description — and a matching row in README.md's knob
table.  The lifecycle pass (``minio_tpu.analysis.lifecycle``) enforces
all three directions as MTPU606: an env read with no registry entry, a
registry entry with no README mention, and a registry entry nothing
reads are each findings.  ``PREFIX_KNOBS`` covers dynamically-composed
families (``MINIO_TPU_NOTIFY_<KIND>_<KEY>_<ID>``) whose full names
cannot be enumerated statically.

This module is intentionally data-only (no env reads of its own): the
runtime seams keep reading ``os.environ`` per call so ConfigSys edits
apply without restart; this table is the catalog that keeps those
scattered reads honest.
"""

from __future__ import annotations

import collections

Knob = collections.namedtuple("Knob", ("default", "description"))

KNOBS: "dict[str, Knob]" = {
    # -- server front plane ------------------------------------------------
    "MINIO_TPU_SERVER": Knob("async", "server mode: async | threaded"),
    "MINIO_TPU_SERVER_LOOPS": Knob(
        "cpu-derived", "async accept-loop count (shared-nothing planes)"
    ),
    "MINIO_TPU_SERVER_REUSEPORT": Knob(
        "auto", "SO_REUSEPORT per-loop listeners: auto | on | off"
    ),
    "MINIO_TPU_SERVER_WORKERS": Knob(
        "cpu-derived", "worker threads per loop for blocking work"
    ),
    "MINIO_TPU_SERVER_BACKLOG": Knob("64", "listen(2) backlog per loop"),
    "MINIO_TPU_HEADER_TIMEOUT_S": Knob(
        "30.0", "slow-loris guard: max seconds to receive headers"
    ),
    "MINIO_TPU_BODY_TIMEOUT_S": Knob(
        "60.0", "max seconds between body chunks"
    ),
    "MINIO_TPU_IDLE_TIMEOUT_S": Knob(
        "60.0", "keep-alive idle connection timeout"
    ),
    "MINIO_TPU_REQUESTS_MAX": Knob(
        "0", "global inflight request cap (0 = auto)"
    ),
    "MINIO_TPU_REQUESTS_DEADLINE_S": Knob(
        "10.0", "queue wait deadline before 503 SlowDown"
    ),
    "MINIO_TPU_TENANT_MAX_INFLIGHT": Knob(
        "0", "per-tenant admission cap (0 = unlimited)"
    ),
    "MINIO_TPU_SELECT_MAX_INFLIGHT": Knob(
        "0", "admission cap for the select/scan class (0 = unlimited)"
    ),
    "MINIO_TPU_PROMETHEUS_AUTH_TYPE": Knob(
        "jwt", "metrics endpoint auth: jwt | public"
    ),
    "MINIO_TPU_TLS": Knob("off", "serve TLS: on | off"),
    "MINIO_TPU_CERT_FILE": Knob("", "TLS server certificate path"),
    "MINIO_TPU_KEY_FILE": Knob("", "TLS private key path"),
    "MINIO_TPU_CA_FILE": Knob("", "TLS client-verification CA path"),
    # -- codec / device plane ----------------------------------------------
    "MINIO_TPU_CODEC_KERNEL": Knob(
        "fused1", "erasure kernel variant selector"
    ),
    "MINIO_TPU_CODEC_FORMULATION": Knob(
        "swar", "GF(2^8) product formulation: swar | mxu"
    ),
    "MINIO_TPU_CODEC_OVERLAP": Knob(
        "auto", "overlapped sub-chunk DMA pipeline: on | off | auto"
    ),
    "MINIO_TPU_CODEC_SUBCHUNK_KB": Knob(
        "256", "sub-chunk size for the overlap pipeline (KiB)"
    ),
    "MINIO_TPU_CODEC_INTERPRET": Knob(
        "0", "run Pallas kernels in interpret mode (debug)"
    ),
    "MINIO_TPU_PARITY_PLANE": Knob(
        "on", "device-resident parity plane: on | off"
    ),
    "MINIO_TPU_PARITY_CACHE_MB": Knob(
        "128", "parity-plane cache budget (MiB)"
    ),
    "MINIO_TPU_PARITY_ACK": Knob(
        "settle", "PUT parity durability ack: settle | eager"
    ),
    "MINIO_TPU_DEVICE_BUDGET_MB": Knob(
        "192", "device memory ledger capacity (MiB)"
    ),
    "MINIO_TPU_COMPRESS": Knob("off", "transparent object compression"),
    "MINIO_TPU_DEVICE_COMPRESS": Knob(
        "auto", "device-side compression codec pass: on | off | auto"
    ),
    "MINIO_TPU_DCOMP_MAX_FILL": Knob(
        "0.75", "device-compression max output fill ratio"
    ),
    "MINIO_TPU_NO_INSTRUMENT": Knob(
        "0", "disable codec telemetry instrumentation"
    ),
    "MINIO_TPU_PLACEMENT": Knob(
        "auto", "device placement policy for sharded ops"
    ),
    "MINIO_TPU_SUBMESH_DEVICES": Knob(
        "1", "device count for the codec submesh"
    ),
    "MINIO_TPU_SELECT": Knob(
        "auto", "S3 Select engine: device | host | row | auto"
    ),
    # -- caches ------------------------------------------------------------
    "MINIO_TPU_READ_CACHE": Knob(
        "off", "tiered GET read cache: on | off"
    ),
    "MINIO_TPU_READ_CACHE_MB": Knob("64", "read cache host tier (MiB)"),
    "MINIO_TPU_READ_CACHE_DEVICE_MB": Knob(
        "64", "read cache device tier (MiB)"
    ),
    "MINIO_TPU_CACHE_DRIVES": Knob(
        "", "disk cache drive paths (comma-separated)"
    ),
    "MINIO_TPU_CACHE_QUOTA_MB": Knob(
        "0", "disk cache quota (MiB, 0 = unlimited)"
    ),
    "MINIO_TPU_BUCKET_META_TTL_S": Knob(
        "code default", "bucket metadata cache TTL (seconds)"
    ),
    # -- storage / io plane ------------------------------------------------
    "MINIO_TPU_IOPOOL_QUEUES": Knob("16", "io-pool queue count"),
    "MINIO_TPU_IOPOOL_DEPTH": Knob("8", "io-pool per-queue depth"),
    "MINIO_TPU_BREAKER": Knob("1", "per-disk circuit breaker: 1 | 0"),
    "MINIO_TPU_BREAKER_TRIP_ERRORS": Knob(
        "5", "consecutive errors that trip a breaker"
    ),
    "MINIO_TPU_BREAKER_SUSPECT_ERRORS": Knob(
        "2", "errors that mark a disk suspect"
    ),
    "MINIO_TPU_BREAKER_BACKOFF_MS": Knob(
        "1000.0", "tripped-breaker probe backoff (ms)"
    ),
    "MINIO_TPU_BREAKER_OUTLIER": Knob(
        "4.0", "latency outlier factor vs the disk median"
    ),
    "MINIO_TPU_BREAKER_SLOW_STRIKES": Knob(
        "code default", "slow-call strikes before suspect"
    ),
    "MINIO_TPU_BREAKER_SLOW_DECAY_MS": Knob(
        "2000.0", "slow-strike decay window (ms)"
    ),
    "MINIO_TPU_HEDGE": Knob("1", "hedged reads: 1 | 0"),
    "MINIO_TPU_HEDGE_FACTOR": Knob(
        "3.0", "hedge trigger factor vs median latency"
    ),
    "MINIO_TPU_HEDGE_MIN_MS": Knob("2.0", "minimum hedge delay (ms)"),
    "MINIO_TPU_HEDGE_MAX_MS": Knob("2000.0", "maximum hedge delay (ms)"),
    "MINIO_TPU_FAULT_INJECTION": Knob(
        "", "enable the fault-injection admin plane"
    ),
    "MINIO_TPU_FAULT_SEED": Knob("0", "fault-injection RNG seed"),
    "MINIO_TPU_SANITIZE": Knob(
        "0", "build/load the sanitizer native library variant"
    ),
    "MINIO_TPU_NATIVE_THREADS": Knob(
        "0", "native codec thread count (0 = auto)"
    ),
    # -- background services -----------------------------------------------
    "MINIO_TPU_CRAWL_INTERVAL_S": Knob(
        "60.0", "crawler cycle interval (seconds)"
    ),
    "MINIO_TPU_HEAL_THROTTLE_S": Knob(
        "0.0", "background heal per-object throttle (seconds)"
    ),
    "MINIO_TPU_FRESH_DISK_INTERVAL_S": Knob(
        "10.0", "fresh-disk detection poll interval (seconds)"
    ),
    "MINIO_TPU_IAM_REFRESH_S": Knob(
        "120.0", "IAM store refresh interval (seconds)"
    ),
    # -- dsync / federation ------------------------------------------------
    "MINIO_TPU_LOCK_REFRESH_S": Knob(
        "10.0", "dsync holder-side lock refresh cadence (seconds)"
    ),
    "MINIO_TPU_LOCK_EXPIRY_S": Knob(
        "30.0", "dsync server-side lock expiry (seconds)"
    ),
    "MINIO_TPU_WRITE_LOCK_ACQUIRE_S": Knob(
        "30.0", "namespace write-lock acquire timeout (seconds)"
    ),
    "MINIO_TPU_FEDERATION_DIR": Knob(
        "", "federation bucket-DNS directory path"
    ),
    "MINIO_TPU_FEDERATION_HOST": Knob(
        "", "this node's advertised federation host"
    ),
    # -- gateway / kms / logging -------------------------------------------
    "MINIO_TPU_GATEWAY_ACCESS_KEY": Knob(
        "", "upstream credentials for gateway mode"
    ),
    "MINIO_TPU_GATEWAY_SECRET_KEY": Knob(
        "", "upstream credentials for gateway mode"
    ),
    "MINIO_TPU_GATEWAY_INSECURE": Knob(
        "0", "skip upstream TLS verification in gateway mode"
    ),
    "MINIO_TPU_KMS_MASTER_KEY": Knob(
        "", "local KMS master key (key-id:hex)"
    ),
    "MINIO_TPU_KMS_KES_ENDPOINT": Knob("", "KES server endpoint URL"),
    "MINIO_TPU_KMS_KES_KEY_ID": Knob(
        "minio-tpu", "KES default key id"
    ),
    "MINIO_TPU_KMS_KES_TOKEN": Knob("", "KES API token"),
    "MINIO_TPU_KMS_KES_INSECURE": Knob(
        "0", "skip KES TLS verification"
    ),
    "MINIO_TPU_LOG": Knob("json", "log format: json | console"),
    "MINIO_TPU_LOG_LEVEL": Knob("info", "log level"),
    "MINIO_TPU_AUDIT_LOG_FILE": Knob(
        "", "audit-log JSON-lines sink path"
    ),
}

# Families whose member names are composed at runtime
# (MINIO_TPU_NOTIFY_<KIND>_<KEY>_<ID>: event notification targets).
PREFIX_KNOBS: "dict[str, Knob]" = {
    "MINIO_TPU_NOTIFY_": Knob(
        "", "event notification target family (webhook/logfile/redis)"
    ),
}
