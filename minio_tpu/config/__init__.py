"""Runtime KV configuration subsystem (cmd/config/config.go:188-278).

``Config = {subsys: {target: {key: value}}}`` with a registered-defaults
layer, persisted as one JSON document under the meta volume and
runtime-editable through the admin API with cluster-wide peer reload.
"""

from .sys import (
    DEFAULT_TARGET,
    ConfigError,
    ConfigSys,
    register_default_kvs,
    registered_defaults,
)

__all__ = [
    "ConfigSys",
    "ConfigError",
    "DEFAULT_TARGET",
    "register_default_kvs",
    "registered_defaults",
]
