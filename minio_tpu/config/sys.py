"""ConfigSys: persisted, runtime-editable server configuration
(cmd/config/config.go Config map + RegisterDefaultKVS at :164,
admin set-config-kv at cmd/admin-router.go:89).

Layering (highest wins):
  1. persisted KV edits (admin set-config-kv, stored in
     ``.sys/config/config.json`` through the object layer)
  2. process environment (``MINIO_TPU_<SUBSYS>_<KEY>``)
  3. registered defaults

``apply()`` pushes the effective values into the runtime seams that
read environment variables per call (compression on/off, heal/crawl
intervals, API limits), so an admin edit takes effect cluster-wide
without restart once peers reload.
"""

from __future__ import annotations

import io
import json
import os
import threading

from ..utils.log import kv, logger

_log = logger("config")

DEFAULT_TARGET = "_"
CONFIG_PATH = "config/config.json"


class ConfigError(Exception):
    pass


# -- registry (RegisterDefaultKVS, config.go:164) ------------------------

_DEFAULTS: "dict[str, dict[str, str]]" = {}
_HELP: "dict[str, dict[str, str]]" = {}


def register_default_kvs(
    subsys: str, kvs: "dict[str, str]", help_text: "dict[str, str] | None" = None
) -> None:
    _DEFAULTS[subsys] = dict(kvs)
    _HELP[subsys] = dict(help_text or {})


def registered_defaults() -> "dict[str, dict[str, str]]":
    return {s: dict(k) for s, k in _DEFAULTS.items()}


# the subsystems this framework exposes (config-current.go initHelp set,
# trimmed to what has a runtime seam here)
register_default_kvs(
    "compression",
    {"enable": "off"},
    {"enable": "on|off: transparent object compression"},
)
register_default_kvs(
    "heal",
    {"throttle_s": "0", "fresh_disk_interval_s": "10"},
    {
        "throttle_s": "sleep between background heal tasks",
        "fresh_disk_interval_s": "fresh-disk monitor poll interval",
    },
)
register_default_kvs(
    "crawler",
    {"interval_s": "60"},
    {"interval_s": "data crawler cycle interval"},
)
register_default_kvs(
    "api",
    {"requests_max": "0", "requests_deadline_s": "10"},
    {
        "requests_max": "max concurrent S3 requests (0 = auto)",
        "requests_deadline_s": "seconds a queued request may wait",
    },
)
register_default_kvs(
    "codec",
    {"backend": "auto", "batch": "on", "batch_deadline_ms": "4"},
    {
        "backend": "tpu|cpu|auto erasure codec backend",
        "batch": "on|off cross-request codec batching",
        "batch_deadline_ms": "batch flush deadline",
    },
)
register_default_kvs(
    "logger",
    {"level": "info"},
    {"level": "debug|info|warning|error"},
)

# key -> (parser, min_value): values must parse and clear the floor -
# a bad value written to the env seam would otherwise kill or busy-loop
# the background thread reading it
_NUMERIC_KEYS: "dict[tuple[str, str], tuple] " = {
    ("heal", "throttle_s"): (float, 0.0),
    ("heal", "fresh_disk_interval_s"): (float, 1.0),
    ("crawler", "interval_s"): (float, 1.0),
    ("api", "requests_max"): (int, 0),
    ("api", "requests_deadline_s"): (float, 0.1),
    ("codec", "batch_deadline_ms"): (float, 0.0),
}

# config key -> the env var its runtime seam reads
_ENV_SEAMS: "dict[tuple[str, str], str]" = {
    ("compression", "enable"): "MINIO_TPU_COMPRESS",
    ("heal", "throttle_s"): "MINIO_TPU_HEAL_THROTTLE_S",
    ("heal", "fresh_disk_interval_s"): "MINIO_TPU_FRESH_DISK_INTERVAL_S",
    ("crawler", "interval_s"): "MINIO_TPU_CRAWL_INTERVAL_S",
    ("api", "requests_max"): "MINIO_TPU_REQUESTS_MAX",
    ("api", "requests_deadline_s"): "MINIO_TPU_REQUESTS_DEADLINE_S",
    ("codec", "backend"): "MINIO_ERASURE_BACKEND",
    ("codec", "batch"): "MINIO_CODEC_BATCH",
    ("codec", "batch_deadline_ms"): "MINIO_CODEC_BATCH_DEADLINE_MS",
    ("logger", "level"): "MINIO_TPU_LOG_LEVEL",
}


class ConfigSys:
    """Persisted config document + in-memory effective view."""

    def __init__(self, object_layer=None):
        self._ol = object_layer
        self._mu = threading.RLock()
        # persisted edits only (defaults/env are layered at read time)
        self._kv: "dict[str, dict[str, dict[str, str]]]" = {}
        # operator env values saved before apply() overwrote them, so
        # deleting an edit restores the pre-edit layering
        self._orig_env: "dict[str, str | None]" = {}
        self.notifier = None  # peer control plane
        if object_layer is not None:
            self.reload()

    # -- persistence ------------------------------------------------------

    def reload(self) -> None:
        """Re-read the persisted document (peer-reload entry point)."""
        if self._ol is None:
            return
        from ..objectlayer.api import (
            META_BUCKET,
            BucketNotFound,
            ObjectNotFound,
        )

        buf = io.BytesIO()
        try:
            self._ol.get_object(META_BUCKET, CONFIG_PATH, buf)
            doc = json.loads(buf.getvalue())
        except (ObjectNotFound, BucketNotFound):
            doc = {}
        except ValueError:
            doc = {}
        if not isinstance(doc, dict):
            doc = {}
        with self._mu:
            self._kv = doc

    def _persist(self) -> None:
        if self._ol is None:
            return
        from ..objectlayer.api import META_BUCKET

        with self._mu:
            raw = json.dumps(self._kv).encode()
        self._ol.put_object(
            META_BUCKET, CONFIG_PATH, io.BytesIO(raw), len(raw)
        )

    # -- reads ------------------------------------------------------------

    def get(
        self, subsys: str, key: str, target: str = DEFAULT_TARGET
    ) -> str:
        """Effective value: persisted edit > env > registered default."""
        with self._mu:
            v = (
                self._kv.get(subsys, {})
                .get(target, {})
                .get(key)
            )
        if v is not None:
            return v
        env = _ENV_SEAMS.get((subsys, key))
        if env and os.environ.get(env) is not None:
            return os.environ[env]
        d = _DEFAULTS.get(subsys, {}).get(key)
        if d is None:
            raise ConfigError(f"unknown config key {subsys}.{key}")
        return d

    def dump(self) -> dict:
        """Full effective config (admin get-config)."""
        out: dict = {}
        for subsys, defaults in _DEFAULTS.items():
            kvs = {}
            for key in defaults:
                kvs[key] = self.get(subsys, key)
            out[subsys] = {DEFAULT_TARGET: kvs}
        # carry custom targets verbatim
        with self._mu:
            for subsys, targets in self._kv.items():
                for target, kvs in targets.items():
                    if target == DEFAULT_TARGET:
                        continue
                    out.setdefault(subsys, {})[target] = dict(kvs)
        return out

    def help(self, subsys: str) -> dict:
        if subsys not in _DEFAULTS:
            raise ConfigError(f"unknown subsystem {subsys!r}")
        return dict(_HELP.get(subsys, {}))

    # -- writes (admin set-config-kv / del-config-kv) ---------------------

    def set_kvs(
        self,
        subsys: str,
        kvs: "dict[str, str]",
        target: str = DEFAULT_TARGET,
    ) -> None:
        if subsys not in _DEFAULTS:
            raise ConfigError(f"unknown subsystem {subsys!r}")
        import math

        for k, v in kvs.items():
            if k not in _DEFAULTS[subsys]:
                raise ConfigError(f"unknown key {subsys}.{k}")
            spec = _NUMERIC_KEYS.get((subsys, k))
            if spec is not None:
                parser, floor = spec
                try:
                    num = parser(v)
                except (TypeError, ValueError):
                    raise ConfigError(
                        f"{subsys}.{k} must be {parser.__name__}, "
                        f"got {v!r}"
                    ) from None
                if not math.isfinite(num) or num < floor:
                    raise ConfigError(
                        f"{subsys}.{k} must be a finite number "
                        f">= {floor}"
                    )
        with self._mu:
            self._kv.setdefault(subsys, {}).setdefault(target, {}).update(
                {k: str(v) for k, v in kvs.items()}
            )
        self._persist()
        self.apply()
        if self.notifier is not None:
            self.notifier.config_changed()

    def del_kvs(self, subsys: str, target: str = DEFAULT_TARGET) -> None:
        """Reset a subsystem back to defaults (del-config-kv)."""
        if subsys not in _DEFAULTS:
            raise ConfigError(f"unknown subsystem {subsys!r}")
        with self._mu:
            self._kv.get(subsys, {}).pop(target, None)
            if not self._kv.get(subsys):
                self._kv.pop(subsys, None)
        self._persist()
        self.apply()
        if self.notifier is not None:
            self.notifier.config_changed()

    # -- runtime application ---------------------------------------------

    def apply(self) -> None:
        """Push effective values into the env seams the runtime reads
        per call.  Persisted edits win; without one, the seam keeps
        whatever the operator exported at process start."""
        with self._mu:
            edited = {
                (s, k)
                for s, targets in self._kv.items()
                for k in targets.get(DEFAULT_TARGET, {})
            }
        codec_touched = False
        logger_touched = False
        for (subsys, key), env in _ENV_SEAMS.items():
            if (subsys, key) in edited:
                if env not in self._orig_env:
                    self._orig_env[env] = os.environ.get(env)
                os.environ[env] = self.get(subsys, key)
                codec_touched = codec_touched or subsys == "codec"
                logger_touched = logger_touched or subsys == "logger"
            elif env in self._orig_env:
                # edit was deleted: restore the operator's value
                orig = self._orig_env.pop(env)
                if orig is None:
                    os.environ.pop(env, None)
                else:
                    os.environ[env] = orig
                codec_touched = codec_touched or subsys == "codec"
                logger_touched = logger_touched or subsys == "logger"
        if codec_touched:
            # the backend singleton captured the previous env; drop it
            # so the next codec call rebuilds with the new settings
            from ..codec import backend as backend_mod

            backend_mod.reset_backend()
        if logger_touched:
            # log level is applied at setup time, not read per call
            from ..utils import log

            try:
                log.setup(self.get("logger", "level"))
            except Exception as exc:
                _log.warning("logger level re-apply failed", extra=kv(err=str(exc)))
