"""Lock-order auditor: the race detector's little brother.

Go's race detector watches the reference MinIO's 47 lock sites at test
time; Python has nothing equivalent, so this shim instruments
``threading.Lock/RLock/Condition`` *as used by the lock-plane modules*
(dsync + storage) and records the runtime lock-acquisition graph while
the test suite (or the built-in CLI scenario) exercises them:

* MTPU301 — a cycle in the acquisition graph: thread T1 took A then B
  while T2 takes B then A.  Never deadlocks in the run that finds it —
  that is the point: the *order* is the bug, observable on any
  interleaving.
* MTPU302 — a blocking call (``time.sleep``, ``socket.create_connection``,
  ``subprocess.run``) while holding an audited lock: a hot-path mutex
  pinned for wall-clock time serializes every peer behind a timer or a
  remote node.

Mechanics: the target modules do ``import threading`` and call
``threading.Lock()`` etc. through their module-global, so swapping that
one attribute for a proxy is enough — no global monkey-patching of the
``threading`` module, and unrelated subsystems (JAX, the batcher pool)
stay untouched.  Graph nodes are (creation-site, instance) pairs, so
many short-lived locks minted at one site (per-object namespace locks,
per-attempt dsync mutexes) do not fold into a single node and
self-alias into false cycles.

Usage::

    aud = LockOrderAuditor()
    with aud.installed():
        ... exercise lock paths ...
    findings = aud.report()   # [] means acyclic and sleep-clean
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading as _real_threading
import time

from .findings import Finding

# modules whose lock usage is on the lock-plane hot path
DEFAULT_TARGETS = (
    "minio_tpu.dsync.drwmutex",
    "minio_tpu.dsync.local_locker",
    "minio_tpu.dsync.namespace",
    "minio_tpu.storage.metered",
    "minio_tpu.storage.diskcheck",
    "minio_tpu.storage.health",
    "minio_tpu.storage.faults",
    "minio_tpu.parallel.iopool",
    # cluster harness + chaos grid: lock-free today, audited so any
    # future lock added to the multi-process driver enters the graph
    "minio_tpu.cluster.harness",
    "minio_tpu.testgrid.engine",
    # multi-loop request plane: the SharedBudget/TokenCounter admit
    # path must stay lock-free (any mutex minted there would serialise
    # every loop's admission), and the per-loop plane code must keep
    # its remaining locks (PlaneStats aggregate, worker-pool stream
    # registry) acyclic against the rest of the graph
    "minio_tpu.server.admission",
    "minio_tpu.server.aio",
)

_THIS_FILE = os.path.abspath(__file__)


def _caller_site() -> "tuple[str, int]":
    """(repo-relative path, line) of the nearest frame outside this file."""
    f = sys._getframe(2)
    while f is not None and os.path.abspath(f.f_code.co_filename) == _THIS_FILE:
        f = f.f_back
    if f is None:  # pragma: no cover
        return "<unknown>", 0
    path = f.f_code.co_filename
    marker = os.sep + "minio_tpu" + os.sep
    if marker in path:
        path = "minio_tpu" + os.sep + path.rsplit(marker, 1)[1]
    return path.replace(os.sep, "/"), f.f_lineno


class _Node:
    """One audited lock instance: identity + where it was minted."""

    __slots__ = ("site", "line", "serial")

    def __init__(self, site: str, line: int, serial: int):
        self.site = site
        self.line = line
        self.serial = serial

    def label(self) -> str:
        return f"{self.site}:{self.line}#{self.serial}"


class AuditedLock:
    """Wraps a real lock; reports acquire/release to the auditor."""

    def __init__(self, auditor: "LockOrderAuditor", inner, node: _Node):
        self._auditor = auditor
        self._inner = inner
        self.node = node

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._auditor._on_acquired(self)
        return ok

    def release(self) -> None:
        self._auditor._on_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class AuditedCondition:
    """Real Condition over an audited lock's graph node.

    ``wait`` releases the lock for its duration, so the held-stack entry
    is popped and re-pushed around it — blocking in ``wait`` is the
    *intended* use of a condition variable, not an MTPU302 smell.
    """

    def __init__(self, auditor, node: _Node, lock=None):
        if isinstance(lock, AuditedLock):
            self.node = lock.node
            inner = lock._inner
        else:
            self.node = node
            inner = lock if lock is not None else _real_threading.RLock()
        self._auditor = auditor
        self._cond = _real_threading.Condition(inner)

    def acquire(self, *args) -> bool:
        ok = self._cond.acquire(*args)
        if ok:
            self._auditor._on_acquired(self)
        return ok

    def release(self) -> None:
        self._auditor._on_released(self)
        self._cond.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: "float | None" = None) -> bool:
        self._auditor._on_released(self)
        try:
            return self._cond.wait(timeout)
        finally:
            self._auditor._on_acquired(self)

    def wait_for(self, predicate, timeout: "float | None" = None):
        self._auditor._on_released(self)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            self._auditor._on_acquired(self)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


class _ThreadingProxy:
    """Stand-in for a module's ``threading`` global: Lock/RLock/Condition
    come back audited, everything else passes through."""

    def __init__(self, auditor: "LockOrderAuditor"):
        self._auditor = auditor

    def Lock(self):
        return self._auditor._make(_real_threading.Lock(), "Lock")

    def RLock(self):
        return self._auditor._make(_real_threading.RLock(), "RLock")

    def Condition(self, lock=None):
        aud = self._auditor
        node = aud._new_node("Condition")
        return AuditedCondition(aud, node, lock)

    def __getattr__(self, name):
        return getattr(_real_threading, name)


class LockOrderAuditor:
    def __init__(self, targets: "tuple[str, ...]" = DEFAULT_TARGETS):
        self.targets = targets
        self._mu = _real_threading.Lock()  # guards graph + findings
        self._serial = 0
        # adjacency: node -> {node}; edge A->B == "B acquired while A held"
        self._edges: "dict[_Node, set[_Node]]" = {}
        self._blocking: "list[Finding]" = []
        self._tls = _real_threading.local()
        self._saved_modules: "list[tuple[object, object]]" = []
        self._saved_globals: "list[tuple[object, str, object]]" = []
        self._saved_class_attrs: "list[tuple[type, str, object]]" = []
        self._installed = False

    # -- bookkeeping ------------------------------------------------------

    def _new_node(self, kind: str) -> _Node:
        site, line = _caller_site()
        with self._mu:
            self._serial += 1
            return _Node(site, line, self._serial)

    def _make(self, inner, kind: str) -> AuditedLock:
        return AuditedLock(self, inner, self._new_node(kind))

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquired(self, lock) -> None:
        st = self._stack()
        node = lock.node
        if any(h.node is node for h in st):
            st.append(lock)  # RLock reentry: no new edges
            return
        if st:
            with self._mu:
                for held in st:
                    if held.node is not node:
                        self._edges.setdefault(held.node, set()).add(node)
        st.append(lock)

    def _on_released(self, lock) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lock or st[i].node is lock.node:
                del st[i]
                return

    def held_count(self) -> int:
        return len(self._stack())

    # -- logical-lock patches (namespace RW locks) ------------------------

    def _patch_logical(self) -> None:
        """Audit ``namespace._RWLock``'s LOGICAL read/write holds.

        The RW lock is built from a condition variable: the primitive is
        held only around counter updates, while the logical read/write
        hold spans the caller's critical section with NO primitive held.
        The primitive graph alone therefore cannot order the namespace
        lock against anything — patch the four acquire/release methods
        so the logical span sits on the held stack like a plain mutex.
        """
        from minio_tpu.dsync import namespace

        aud = self
        cls = namespace._RWLock

        class _Handle:  # what _on_acquired/_on_released key on
            __slots__ = ("node",)

            def __init__(self, node):
                self.node = node

        def node_of(rw) -> _Node:
            node = rw.__dict__.get("_audit_node")
            if node is None:
                node = rw._audit_node = aud._new_node("RWLock")
            return node

        def make_acquire(original):
            def wrapper(rw, timeout=None):
                ok = original(rw, timeout)
                if ok:
                    aud._on_acquired(_Handle(node_of(rw)))
                return ok

            return wrapper

        def make_release(original):
            def wrapper(rw):
                aud._on_released(_Handle(node_of(rw)))
                return original(rw)

            return wrapper

        for name, wrap in (
            ("acquire_read", make_acquire),
            ("acquire_write", make_acquire),
            ("release_read", make_release),
            ("release_write", make_release),
        ):
            original = getattr(cls, name)
            self._saved_class_attrs.append((cls, name, original))
            setattr(cls, name, wrap(original))

    # -- blocking-call patches (MTPU302) ----------------------------------

    def _patch_blocking(self) -> None:
        aud = self

        def make(original, what):
            def wrapper(*args, **kwargs):
                st = getattr(aud._tls, "stack", None)
                if st:
                    site, line = _caller_site()
                    held = ", ".join(
                        h.node.site + ":" + str(h.node.line) for h in st
                    )
                    with aud._mu:
                        aud._blocking.append(
                            Finding(
                                "MTPU302",
                                site,
                                line,
                                f"{what} while holding lock(s) created at "
                                f"[{held}]",
                            )
                        )
                return original(*args, **kwargs)

            return wrapper

        for holder, name, what in (
            (time, "sleep", "time.sleep"),
            (socket, "create_connection", "socket.create_connection"),
            (subprocess, "run", "subprocess.run"),
        ):
            original = getattr(holder, name)
            self._saved_globals.append((holder, name, original))
            setattr(holder, name, make(original, what))

    # -- install / uninstall ----------------------------------------------

    def install(self) -> None:
        if self._installed:
            return
        import importlib

        proxy = _ThreadingProxy(self)
        for name in self.targets:
            mod = importlib.import_module(name)
            if getattr(mod, "threading", None) is _real_threading:
                self._saved_modules.append((mod, mod.threading))
                mod.threading = proxy
        self._patch_blocking()
        self._patch_logical()
        self._installed = True

    def uninstall(self) -> None:
        if not self._installed:
            return
        for mod, original in self._saved_modules:
            mod.threading = original
        self._saved_modules.clear()
        for holder, name, original in self._saved_globals:
            setattr(holder, name, original)
        self._saved_globals.clear()
        for cls, name, original in self._saved_class_attrs:
            setattr(cls, name, original)
        self._saved_class_attrs.clear()
        self._installed = False

    def installed(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            self.install()
            try:
                yield self
            finally:
                self.uninstall()

        return cm()

    # -- reporting --------------------------------------------------------

    def edge_labels(self) -> "list[tuple[str, str]]":
        """Observed (held -> acquired) creation-site pairs, sorted."""
        with self._mu:
            out = {
                (a.site + ":" + str(a.line), b.site + ":" + str(b.line))
                for a, succs in self._edges.items()
                for b in succs
            }
        return sorted(out)

    def cycles(self) -> "list[list[_Node]]":
        """Elementary cycles via iterative three-color DFS (dedup by set)."""
        with self._mu:
            edges = {a: set(b) for a, b in self._edges.items()}
        WHITE, GREY, BLACK = 0, 1, 2
        color: "dict[_Node, int]" = {}
        nodes = set(edges)
        for succs in edges.values():
            nodes |= succs
        found: "list[list[_Node]]" = []
        seen_sets: "set[frozenset]" = set()
        for root in sorted(nodes, key=lambda n: n.serial):
            if color.get(root, WHITE) != WHITE:
                continue
            stack = [(root, iter(sorted(edges.get(root, ()),
                                        key=lambda n: n.serial)))]
            path = [root]
            color[root] = GREY
            while stack:
                node, it = stack[-1]
                nxt = next(it, None)
                if nxt is None:
                    color[node] = BLACK
                    stack.pop()
                    path.pop()
                    continue
                c = color.get(nxt, WHITE)
                if c == GREY:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = frozenset(n.serial for n in cyc)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        found.append(cyc)
                elif c == WHITE:
                    color[nxt] = GREY
                    path.append(nxt)
                    stack.append(
                        (nxt, iter(sorted(edges.get(nxt, ()),
                                          key=lambda n: n.serial)))
                    )
            color[root] = BLACK
        return found

    def report(self) -> "list[Finding]":
        findings: "list[Finding]" = []
        for cyc in self.cycles():
            chain = " -> ".join(n.label() for n in cyc)
            first = cyc[0]
            findings.append(
                Finding(
                    "MTPU301",
                    first.site,
                    first.line,
                    f"lock-order cycle: {chain}",
                )
            )
        with self._mu:
            findings.extend(self._blocking)
        # dedupe (stress loops hit the same blocking site repeatedly)
        out, seen = [], set()
        for f in findings:
            k = (f.rule, f.path, f.line, f.message)
            if k not in seen:
                seen.add(k)
                out.append(f)
        return out


def run_builtin_scenario() -> "list[Finding]":
    """The CLI's lock pass: a short deterministic stress of the local
    lock plane (namespace RW locks + LocalLocker grants) under audit.

    Small on purpose — the heavyweight concurrency coverage lives in
    tests/test_race.py, which reuses this auditor under its existing
    dsync stress helpers.
    """
    aud = LockOrderAuditor()
    with aud.installed():
        from minio_tpu.dsync.drwmutex import LockArgs
        from minio_tpu.dsync.local_locker import LocalLocker
        from minio_tpu.dsync.namespace import NamespaceLock

        ns = NamespaceLock()
        ll = LocalLocker()
        errors: "list[BaseException]" = []

        def worker(tid: int) -> None:
            try:
                for i in range(25):
                    key = f"obj-{(tid + i) % 4}"
                    if (tid + i) % 3 == 0:
                        with ns.write("bucket", key, timeout=5.0):
                            pass
                    else:
                        with ns.read("bucket", key, timeout=5.0):
                            pass
                    args = LockArgs(
                        uid=f"u{tid}-{i}", resources=(key,), source="analysis"
                    )
                    if ll.lock(args):
                        ll.unlock(args)
                    else:
                        rargs = LockArgs(
                            uid=f"r{tid}-{i}",
                            resources=(key,),
                            source="analysis",
                        )
                        if ll.rlock(rargs):
                            ll.runlock(rargs)
            except BaseException as e:  # surfaced below
                errors.append(e)

        threads = [
            _real_threading.Thread(target=worker, args=(t,), daemon=True)
            for t in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        if errors:
            raise errors[0]

        # the per-disk I/O fan-out plane (parallel/iopool.py): a
        # private pool so the audited locks are created, exercised and
        # torn down entirely inside the audit window — queue cv's,
        # future locks, flusher cv, backpressure waits, quorum waits
        from minio_tpu.parallel.iopool import IOPool, ShardFlusher

        pool = IOPool(queues=4, depth=2, name_prefix="iopool-audit")
        try:
            futs = [
                pool.submit(f"disk-{i % 6}", (lambda i=i: i * i))
                for i in range(24)
            ]
            for i, f in enumerate(futs):
                if f.result_or_raise(timeout=30) != i * i:
                    raise RuntimeError("iopool scenario result mismatch")
            fl = ShardFlusher(pool, quorum_exc=RuntimeError)
            jobs = [
                (s, f"disk-{s}", (lambda s=s: None), 64)
                for s in range(4)
            ]
            fl.flush(jobs, quorum=3)
            fl.drain()
        finally:
            pool.shutdown()
    return aud.report()
