"""minio_tpu.analysis: project-native static analysis.

Four passes over the codebase's invariants (the Python/JAX stand-ins
for the go-vet / staticcheck / race-detector triad the reference MinIO
leans on):

* ``hotpath_lint``    — AST rules MTPU101-106 (syncs, retrace bombs,
  swallowed exceptions, metric conventions, stale suppressions);
* ``abi_contracts``   — ctypes/ABI rules MTPU401-405 across the
  Python↔C seam (utils/native.py vs native/csrc/gf_cpu.cc);
* ``kernel_contracts``— abstract-eval contracts MTPU201-204 for every
  jitted codec entry point (CPU-only, via jax.eval_shape);
* ``lockorder``       — runtime lock-graph audit MTPU301-302.

Run ``python -m minio_tpu.analysis`` (tier-1 runs the same passes via
tests/test_analysis.py).  Suppress a deliberate violation with
``# noqa: MTPU###`` on the offending line — MTPU106 flags the noqa
itself once the rule stops firing there, so suppressions cannot rot.
"""

from __future__ import annotations

import os

from .findings import (  # noqa: F401
    RULES,
    Finding,
    filter_suppressed,
    unused_suppressions,
)

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# the analyzers do not lint themselves (fixture-ish rule text and the
# deliberately-broad exception guards around abstract eval would need a
# noqa forest) — mirrors how linters ship their own excludes.
_EXCLUDE_PREFIXES = ("minio_tpu/analysis/",)


def _excluded_dir_names() -> "tuple[str, ...]":
    # canonical list lives with the CLI (module-level constants only,
    # so the import cannot recurse)
    from .__main__ import EXCLUDED_DIR_NAMES

    return EXCLUDED_DIR_NAMES


def is_excluded(rel_path: str) -> bool:
    """True when a repo-relative path must not be analyzed."""
    parts = rel_path.replace(os.sep, "/").split("/")
    if any(p in _excluded_dir_names() for p in parts[:-1]):
        return True
    return rel_path.startswith(_EXCLUDE_PREFIXES)


def iter_py_files(paths: "list[str] | None" = None) -> "list[str]":
    """Repo-relative .py files under ``paths`` (default: minio_tpu/).

    Honors the canonical directory exclusions even for explicitly
    passed paths: ``--paths native/build`` (or a file inside it) yields
    nothing rather than analyzing build artifacts.
    """
    roots = paths or ["minio_tpu"]
    excluded = _excluded_dir_names()
    out: "list[str]" = []
    for root in roots:
        abs_root = os.path.join(REPO_ROOT, root)
        if os.path.isfile(abs_root):
            out.append(os.path.relpath(abs_root, REPO_ROOT))
            continue
        for dirpath, dirnames, filenames in os.walk(abs_root):
            dirnames[:] = [d for d in dirnames if d not in excluded]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.append(
                        os.path.relpath(
                            os.path.join(dirpath, fn), REPO_ROOT
                        )
                    )
    out = [p.replace(os.sep, "/") for p in out]
    return sorted(p for p in out if not is_excluded(p))


def _read_lines(rel_path: str) -> "list[str]":
    with open(
        os.path.join(REPO_ROOT, rel_path), encoding="utf-8"
    ) as fh:
        return fh.read().splitlines()


def run_lint(paths: "list[str] | None" = None) -> "list[Finding]":
    """Hot-path lint over the tree, noqa-filtered and stable-sorted.

    Includes MTPU106: every MTPU-coded noqa is audited against the
    PRE-filter findings of the file-anchored passes (lint, plus the
    ABI pass for the native seam), so a suppression whose rule no
    longer fires is itself a finding.
    """
    from . import abi_contracts
    from .hotpath_lint import lint_source

    findings: "list[Finding]" = []
    sources: "dict[str, list[str]]" = {}
    for rel in iter_py_files(paths):
        lines = _read_lines(rel)
        sources[rel] = lines
        text = "\n".join(lines) + "\n"
        raw = lint_source(rel, text)
        findings.extend(raw)
        raw_for_audit = list(raw)
        if rel == abi_contracts.PY_REL:
            raw_for_audit.extend(abi_contracts.raw_run())
        findings.extend(unused_suppressions(rel, text, raw_for_audit))
    return sorted(
        filter_suppressed(findings, sources), key=Finding.sort_key
    )


def run_abi() -> "list[Finding]":
    """ctypes/ABI contract checks over the native FFI seam."""
    from . import abi_contracts

    return sorted(abi_contracts.run(), key=Finding.sort_key)


def run_contracts() -> "list[Finding]":
    """Kernel contract checks (jax.eval_shape; CPU is fine)."""
    from . import kernel_contracts

    return sorted(kernel_contracts.run(), key=Finding.sort_key)


def run_locks() -> "list[Finding]":
    """Lock-order audit over the built-in CLI scenario."""
    from .lockorder import run_builtin_scenario

    return sorted(run_builtin_scenario(), key=Finding.sort_key)


def run_all(
    paths: "list[str] | None" = None,
    skip: "set[str] | None" = None,
) -> "list[Finding]":
    skip = skip or set()
    findings: "list[Finding]" = []
    if "lint" not in skip:
        findings.extend(run_lint(paths))
    if "abi" not in skip:
        findings.extend(run_abi())
    if "contracts" not in skip:
        findings.extend(run_contracts())
    if "locks" not in skip:
        findings.extend(run_locks())
    return sorted(findings, key=Finding.sort_key)
