"""minio_tpu.analysis: project-native static analysis.

Six passes over the codebase's invariants (the Python/JAX stand-ins
for the go-vet / staticcheck / race-detector triad the reference MinIO
leans on):

* ``hotpath_lint``    — AST rules MTPU101-106 (syncs, retrace bombs,
  swallowed exceptions, metric conventions, stale suppressions);
* ``abi_contracts``   — ctypes/ABI rules MTPU401-405 across the
  Python↔C seam (utils/native.py vs native/csrc/gf_cpu.cc);
* ``kernel_contracts``— abstract-eval contracts MTPU201-204 for every
  jitted codec entry point (CPU-only, via jax.eval_shape);
* ``lockorder``       — runtime lock-graph audit MTPU301-302;
* ``deviceflow``      — interprocedural device-dataflow rules
  MTPU501-505 (use-after-donate, D2H escapes, thread-boundary
  captures, call-graph-deep blocking-under-async, registry drift)
  over the ``callgraph`` module's whole-tree call graph;
* ``lifecycle``       — interprocedural resource-lifecycle rules
  MTPU601-606 (leaked/double/unprotected acquires, use-after-
  transfer, resource-registry drift, config-knob drift) over the
  same call graph and the ``resource_registry`` table.

The file-walking passes share one mtime-keyed AST cache
(``astcache.CACHE``) so a six-pass run parses each file exactly once,
and the deviceflow/lifecycle passes share one call-graph build.

Run ``python -m minio_tpu.analysis`` (tier-1 runs the same passes via
tests/test_analysis.py).  Suppress a deliberate violation with
``# noqa: MTPU###`` on the offending line — MTPU106 flags the noqa
itself once the rule stops firing there, so suppressions cannot rot.
"""

from __future__ import annotations

import os

from .findings import (  # noqa: F401
    RULES,
    Finding,
    filter_suppressed,
    unused_suppressions,
)

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# the analyzers do not lint themselves (fixture-ish rule text and the
# deliberately-broad exception guards around abstract eval would need a
# noqa forest) — mirrors how linters ship their own excludes.
_EXCLUDE_PREFIXES = ("minio_tpu/analysis/",)


def _excluded_dir_names() -> "tuple[str, ...]":
    # canonical list lives with the CLI (module-level constants only,
    # so the import cannot recurse)
    from .__main__ import EXCLUDED_DIR_NAMES

    return EXCLUDED_DIR_NAMES


def is_excluded(rel_path: str) -> bool:
    """True when a repo-relative path must not be analyzed."""
    parts = rel_path.replace(os.sep, "/").split("/")
    if any(p in _excluded_dir_names() for p in parts[:-1]):
        return True
    return rel_path.startswith(_EXCLUDE_PREFIXES)


def iter_py_files(paths: "list[str] | None" = None) -> "list[str]":
    """Repo-relative .py files under ``paths`` (default: minio_tpu/).

    Honors the canonical directory exclusions even for explicitly
    passed paths: ``--paths native/build`` (or a file inside it) yields
    nothing rather than analyzing build artifacts.
    """
    roots = paths or ["minio_tpu"]
    excluded = _excluded_dir_names()
    out: "list[str]" = []
    for root in roots:
        abs_root = os.path.join(REPO_ROOT, root)
        if os.path.isfile(abs_root):
            out.append(os.path.relpath(abs_root, REPO_ROOT))
            continue
        for dirpath, dirnames, filenames in os.walk(abs_root):
            dirnames[:] = [d for d in dirnames if d not in excluded]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.append(
                        os.path.relpath(
                            os.path.join(dirpath, fn), REPO_ROOT
                        )
                    )
    out = [p.replace(os.sep, "/") for p in out]
    return sorted(p for p in out if not is_excluded(p))


def _read_lines(rel_path: str) -> "list[str]":
    with open(
        os.path.join(REPO_ROOT, rel_path), encoding="utf-8"
    ) as fh:
        return fh.read().splitlines()


def run_lint(paths: "list[str] | None" = None) -> "list[Finding]":
    """Hot-path lint over the tree, noqa-filtered and stable-sorted.

    Includes MTPU106: every MTPU-coded noqa is audited against the
    PRE-filter findings of the file-anchored passes (lint, plus the
    ABI pass for the native seam), so a suppression whose rule no
    longer fires is itself a finding.
    """
    from . import abi_contracts
    from .astcache import CACHE
    from .hotpath_lint import lint_source

    findings: "list[Finding]" = []
    sources: "dict[str, list[str]]" = {}
    for rel in iter_py_files(paths):
        mod = CACHE.get(rel)
        sources[rel] = mod.lines
        if mod.tree is None:
            findings.append(
                Finding(
                    "MTPU100",
                    rel,
                    (mod.error.lineno or 1) if mod.error else 1,
                    "syntax error: "
                    + (mod.error.msg if mod.error else "unparseable"),
                )
            )
            continue
        raw = lint_source(rel, mod.text, tree=mod.tree)
        findings.extend(raw)
        raw_for_audit = list(raw)
        if rel == abi_contracts.PY_REL:
            raw_for_audit.extend(abi_contracts.raw_run())
        findings.extend(unused_suppressions(rel, mod.text, raw_for_audit))
    return sorted(
        filter_suppressed(findings, sources), key=Finding.sort_key
    )


def run_deviceflow_report(
    paths: "list[str] | None" = None,
    restrict: "set[str] | None" = None,
):
    """Deviceflow pass (MTPU501-505) with its callgraph report.

    Returns ``(findings, report)`` where findings are noqa-filtered
    (with the pass's own MTPU5xx staleness audit folded in) and
    ``report`` carries the call graph + timings for ``--json``.  The
    analysis is always whole-set — provenance is an interprocedural
    fact — but ``restrict`` (a repo-relative path set, e.g. the
    reverse-dependency closure of changed files) limits which files'
    findings are REPORTED, which is the sound form of --changed-only.
    """
    from .astcache import CACHE
    from .deviceflow import analyze_sources

    sources = CACHE.load(iter_py_files(paths))
    report = analyze_sources(sources)
    by_path: "dict[str, list[Finding]]" = {}
    for f in report.findings:
        by_path.setdefault(f.path, []).append(f)
    findings = list(report.findings)
    for rel, mod in sources.items():
        findings.extend(
            unused_suppressions(
                rel, mod.text, by_path.get(rel, []), prefixes=("MTPU5",)
            )
        )
    lines = {rel: mod.lines for rel, mod in sources.items()}
    findings = filter_suppressed(findings, lines)
    if restrict is not None:
        findings = [f for f in findings if f.path in restrict]
    return sorted(findings, key=Finding.sort_key), report


def run_deviceflow(
    paths: "list[str] | None" = None,
    restrict: "set[str] | None" = None,
) -> "list[Finding]":
    """Interprocedural device-dataflow checks (MTPU501-505)."""
    findings, _ = run_deviceflow_report(paths, restrict)
    return findings


def run_lifecycle_report(
    paths: "list[str] | None" = None,
    restrict: "set[str] | None" = None,
    graph=None,
):
    """Lifecycle pass (MTPU601-606) with its callgraph report.

    Mirrors ``run_deviceflow_report``: whole-set analysis (release
    credit is an interprocedural fact), ``restrict`` limits which
    files' findings are REPORTED for sound --changed-only, and
    ``graph`` lets a caller share the deviceflow pass's call-graph
    build.
    """
    from .astcache import CACHE
    from .lifecycle import analyze_sources

    sources = CACHE.load(iter_py_files(paths))
    report = analyze_sources(sources, graph=graph)
    by_path: "dict[str, list[Finding]]" = {}
    for f in report.findings:
        by_path.setdefault(f.path, []).append(f)
    findings = list(report.findings)
    for rel, mod in sources.items():
        findings.extend(
            unused_suppressions(
                rel, mod.text, by_path.get(rel, []), prefixes=("MTPU6",)
            )
        )
    lines = {rel: mod.lines for rel, mod in sources.items()}
    findings = filter_suppressed(findings, lines)
    if restrict is not None:
        findings = [f for f in findings if f.path in restrict]
    return sorted(findings, key=Finding.sort_key), report


def run_lifecycle(
    paths: "list[str] | None" = None,
    restrict: "set[str] | None" = None,
) -> "list[Finding]":
    """Interprocedural resource-lifecycle checks (MTPU601-606)."""
    findings, _ = run_lifecycle_report(paths, restrict)
    return findings


def run_abi() -> "list[Finding]":
    """ctypes/ABI contract checks over the native FFI seam."""
    from . import abi_contracts

    return sorted(abi_contracts.run(), key=Finding.sort_key)


def run_contracts() -> "list[Finding]":
    """Kernel contract checks (jax.eval_shape; CPU is fine)."""
    from . import kernel_contracts

    return sorted(kernel_contracts.run(), key=Finding.sort_key)


def run_locks() -> "list[Finding]":
    """Lock-order audit over the built-in CLI scenario."""
    from .lockorder import run_builtin_scenario

    return sorted(run_builtin_scenario(), key=Finding.sort_key)


def run_all(
    paths: "list[str] | None" = None,
    skip: "set[str] | None" = None,
) -> "list[Finding]":
    findings, _, _ = run_all_timed(paths, skip)
    return findings


def run_all_timed(
    paths: "list[str] | None" = None,
    skip: "set[str] | None" = None,
    deviceflow_restrict: "set[str] | None" = None,
):
    """All passes, with per-pass wall time.

    Returns ``(findings, pass_seconds, callgraph_stats)`` —
    ``pass_seconds`` maps each pass that ran to its wall time (the
    analyzer's cost is tracked like a benchmark), ``callgraph_stats``
    is the deviceflow pass's graph summary (or None when skipped).
    """
    import time

    skip = skip or set()
    findings: "list[Finding]" = []
    pass_seconds: "dict[str, float]" = {}
    callgraph_stats = None

    def timed(name, fn):
        t0 = time.monotonic()
        findings.extend(fn())
        pass_seconds[name] = round(time.monotonic() - t0, 3)

    if "lint" not in skip:
        timed("lint", lambda: run_lint(paths))
    if "abi" not in skip:
        timed("abi", run_abi)
    if "contracts" not in skip:
        timed("contracts", run_contracts)
    if "locks" not in skip:
        timed("locks", run_locks)
    shared_graph = None
    if "deviceflow" not in skip:
        t0 = time.monotonic()
        # a restrict set implies whole-tree analysis (the closure was
        # computed over the whole graph); otherwise honor --paths
        df, report = run_deviceflow_report(
            None if deviceflow_restrict is not None else paths,
            restrict=deviceflow_restrict,
        )
        findings.extend(df)
        pass_seconds["deviceflow"] = round(time.monotonic() - t0, 3)
        callgraph_stats = report.graph.stats()
        shared_graph = report.graph
    if "lifecycle" not in skip:
        t0 = time.monotonic()
        lc, lc_report = run_lifecycle_report(
            None if deviceflow_restrict is not None else paths,
            restrict=deviceflow_restrict,
            graph=shared_graph,
        )
        findings.extend(lc)
        pass_seconds["lifecycle"] = round(time.monotonic() - t0, 3)
        if callgraph_stats is None:
            callgraph_stats = lc_report.graph.stats()
    return (
        sorted(findings, key=Finding.sort_key),
        pass_seconds,
        callgraph_stats,
    )
