"""minio_tpu.analysis: project-native static analysis.

Three passes over the codebase's invariants (the Python/JAX stand-ins
for the go-vet / staticcheck / race-detector triad the reference MinIO
leans on):

* ``hotpath_lint``    — AST rules MTPU101-105 (syncs, retrace bombs,
  swallowed exceptions, metric conventions);
* ``kernel_contracts``— abstract-eval contracts MTPU201-204 for every
  jitted codec entry point (CPU-only, via jax.eval_shape);
* ``lockorder``       — runtime lock-graph audit MTPU301-302.

Run ``python -m minio_tpu.analysis`` (tier-1 runs the same passes via
tests/test_analysis.py).  Suppress a deliberate violation with
``# noqa: MTPU###`` on the offending line.
"""

from __future__ import annotations

import os

from .findings import RULES, Finding, filter_suppressed  # noqa: F401

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# the analyzers do not lint themselves (fixture-ish rule text and the
# deliberately-broad exception guards around abstract eval would need a
# noqa forest) — mirrors how linters ship their own excludes.
_EXCLUDE_PREFIXES = ("minio_tpu/analysis/",)


def iter_py_files(paths: "list[str] | None" = None) -> "list[str]":
    """Repo-relative .py files under ``paths`` (default: minio_tpu/)."""
    roots = paths or ["minio_tpu"]
    out: "list[str]" = []
    for root in roots:
        abs_root = os.path.join(REPO_ROOT, root)
        if os.path.isfile(abs_root):
            out.append(os.path.relpath(abs_root, REPO_ROOT))
            continue
        for dirpath, dirnames, filenames in os.walk(abs_root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.append(
                        os.path.relpath(
                            os.path.join(dirpath, fn), REPO_ROOT
                        )
                    )
    out = [p.replace(os.sep, "/") for p in out]
    return sorted(p for p in out if not p.startswith(_EXCLUDE_PREFIXES))


def _read_lines(rel_path: str) -> "list[str]":
    with open(
        os.path.join(REPO_ROOT, rel_path), encoding="utf-8"
    ) as fh:
        return fh.read().splitlines()


def run_lint(paths: "list[str] | None" = None) -> "list[Finding]":
    """Hot-path lint over the tree, noqa-filtered and stable-sorted."""
    from .hotpath_lint import lint_source

    findings: "list[Finding]" = []
    sources: "dict[str, list[str]]" = {}
    for rel in iter_py_files(paths):
        lines = _read_lines(rel)
        sources[rel] = lines
        findings.extend(lint_source(rel, "\n".join(lines) + "\n"))
    return sorted(
        filter_suppressed(findings, sources), key=Finding.sort_key
    )


def run_contracts() -> "list[Finding]":
    """Kernel contract checks (jax.eval_shape; CPU is fine)."""
    from . import kernel_contracts

    return sorted(kernel_contracts.run(), key=Finding.sort_key)


def run_locks() -> "list[Finding]":
    """Lock-order audit over the built-in CLI scenario."""
    from .lockorder import run_builtin_scenario

    return sorted(run_builtin_scenario(), key=Finding.sort_key)


def run_all(
    paths: "list[str] | None" = None,
    skip: "set[str] | None" = None,
) -> "list[Finding]":
    skip = skip or set()
    findings: "list[Finding]" = []
    if "lint" not in skip:
        findings.extend(run_lint(paths))
    if "contracts" not in skip:
        findings.extend(run_contracts())
    if "locks" not in skip:
        findings.extend(run_locks())
    return sorted(findings, key=Finding.sort_key)
