"""Interprocedural resource-lifecycle analysis (MTPU601-606).

Proves, over the PR 17 call graph, that every acquire of a registered
resource (``resource_registry.py``) has a release or a sanctioned
ownership transfer on every path — the static stand-in for Go's defer
discipline that the reference MinIO leans on:

* MTPU601 — a path reaches function exit still holding an acquisition;
* MTPU602 — the same acquisition is released twice on one path;
* MTPU603 — an unprotected hold across a raisable call (no try/finally
  or ``with`` guarantees the release if that call throws);
* MTPU604 — a handle is used again after a registered ownership
  transfer;
* MTPU605 — registry drift: a registered function the call graph does
  not have, or an acquire-shaped API in a registered module that the
  registry misses;
* MTPU606 — config-knob drift: a ``MINIO_TPU_*`` env read without a
  ``config/knobs.py`` registry entry, a registered knob with no README
  mention, or a registry entry nothing reads.

The local dataflow is path-condition aware: try-style acquires
(``if not adm.try_enter_tenant(t): return``) hold only on the truthy
refinement, try/finally and ``with`` protect and discharge, and
release credit flows interprocedurally — a helper (or a closure handed
to a worker pool) that releases on behalf of its caller discharges the
caller's obligation through the call-graph edge, which is what makes
``--changed-only`` need the reverse-dependency closure.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import time

from . import callgraph
from .astcache import ParsedModule
from .findings import Finding
from .resource_registry import (
    ACQUIRE_SHAPED_NAMES,
    ACQUIRE_SHAPED_PREFIXES,
    Registry,
    ResourceClass,
    registered_call_names,
)

KNOBS_REL = "minio_tpu/config/knobs.py"

# Calls that cannot meaningfully throw for MTPU603 purposes: clock
# reads, size probes, logger/metric verbs, and the container ops the
# counters themselves are built from.  Everything else is raisable.
_SAFE_CALLS = frozenset(
    {
        "monotonic",
        "perf_counter",
        "time",
        "len",
        "id",
        "str",
        "bool",
        "isinstance",
        "getattr",
        "hasattr",
        "min",
        "max",
        "append",
        "pop",
        "popleft",
        "get",
        "debug",
        "info",
        "warning",
        "error",
        "exception",
        "observe",
        "inc",
        "dec",
        "set",
        "labels",
        "shed_inc",
        "value",
        "snapshot",
        "tenant_of",
        "field",
        "kv",
        "log_success",
        "log_failure",
    }
)

_TRUTHY_CONSTS = (True,)
_FALSY_CONSTS = (False, None, 0)


@dataclasses.dataclass
class LifecycleReport:
    findings: "list[Finding]"
    graph: "callgraph.CallGraph"
    seconds: float


# ---------------------------------------------------------------------------
# local state
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Ob:
    """One tracked acquisition within a function body."""

    uid: int
    res: ResourceClass
    line: int
    var: "str | None" = None  # handle variable name
    # held | pending | pending_transfer | released | transferred | maybe
    state: str = "held"
    cond_var: "str | None" = None
    transfer_line: int = 0
    warned603: bool = False
    from_with: bool = False

    def clone(self) -> "_Ob":
        return dataclasses.replace(self)


class _State:
    """Per-path obligation set (branch-cloneable, mergeable by uid)."""

    def __init__(self):
        self.obs: "list[_Ob]" = []
        self.aliases: "dict[str, str]" = {}  # local name -> self attr

    def clone(self) -> "_State":
        s = _State()
        s.obs = [ob.clone() for ob in self.obs]
        s.aliases = dict(self.aliases)
        return s

    def live(self, res_name: "str | None" = None) -> "list[_Ob]":
        return [
            ob
            for ob in self.obs
            if ob.state in ("held", "pending", "pending_transfer")
            and (res_name is None or ob.res.name == res_name)
        ]


def _merge(a: "_State", b: "_State") -> "_State":
    """Join two fallthrough branches; disagreements become 'maybe'
    (no further findings — the conservative, quiet direction)."""
    out = _State()
    out.aliases = dict(a.aliases)
    bmap = {ob.uid: ob for ob in b.obs}
    seen = set()
    for ob in a.obs:
        other = bmap.get(ob.uid)
        seen.add(ob.uid)
        if other is None:
            merged = ob.clone()
            if merged.state in ("held", "pending", "pending_transfer"):
                merged.state = "maybe"
            out.obs.append(merged)
            continue
        merged = ob.clone()
        if other.state != ob.state:
            merged.state = "maybe"
        out.obs.append(merged)
    for ob in b.obs:
        if ob.uid not in seen:
            merged = ob.clone()
            if merged.state in ("held", "pending", "pending_transfer"):
                merged.state = "maybe"
            out.obs.append(merged)
    return out


# ---------------------------------------------------------------------------
# syntactic matchers
# ---------------------------------------------------------------------------


def _call_name(call: ast.Call) -> "str | None":
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _recv_tail(call: ast.Call) -> "str | None":
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return None
    base = fn.value
    if isinstance(base, ast.Name):
        return base.id
    if isinstance(base, ast.Attribute):
        return base.attr
    return None


def _name_matches(spec: str, call: ast.Call) -> bool:
    if "." in spec:
        recv, name = spec.rsplit(".", 1)
        return _call_name(call) == name and _recv_tail(call) == recv
    return _call_name(call) == spec


def _attr_op(call: ast.Call, state: "_State") -> "tuple[str, str] | None":
    """``(attr, method)`` for ``self._res.append(...)`` or an aliased
    ``res.append(...)`` where ``res = self._res``."""
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return None
    base = fn.value
    if isinstance(base, ast.Attribute):
        return (base.attr, fn.attr)
    if isinstance(base, ast.Name) and base.id in state.aliases:
        return (state.aliases[base.id], fn.attr)
    return None


def _has_kwarg(call: ast.Call, kwarg: str) -> bool:
    return any(kw.arg == kwarg for kw in call.keywords)


def _arg_names(call: ast.Call) -> "set[str]":
    out: "set[str]" = set()
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for node in ast.walk(arg):
            if isinstance(node, ast.Name):
                out.add(node.id)
    return out


def _const_value(node: "ast.AST | None"):
    if node is None:
        return None
    if isinstance(node, ast.Constant):
        return node.value
    return _MISSING


_MISSING = object()


# ---------------------------------------------------------------------------
# the per-function interpreter
# ---------------------------------------------------------------------------


class _Interp:
    def __init__(
        self,
        pass_,
        info: "callgraph.FuncInfo",
        resources: "tuple[ResourceClass, ...]",
    ):
        self.p = pass_
        self.info = info
        self.rel = info.rel_path
        self.resources = resources
        self.findings: "list[Finding]" = []
        self.credit: "dict[str, int]" = {}
        self.ever_acquired: "set[str]" = set()
        self._uid = 0
        # (protect_keys, effects): keys protect obligations for
        # MTPU603, effects are replayed on early exits (finally runs)
        self.frames: "list[tuple[set, list]]" = []
        self.local_defs = pass_.graph.locals_of.get(info.qname, {})
        # resources this function is a registered acquire seam for
        self.seam_res: "set[str]" = set()
        name = info.qname.split("::", 1)[1]
        for res in resources:
            for drel, dq in res.defs:
                if drel == self.rel and dq == name:
                    bare = name.rsplit(".", 1)[-1]
                    if any(
                        bare == s.rsplit(".", 1)[-1]
                        for s in res.acquire_calls
                    ):
                        self.seam_res.add(res.name)

    # -- driving ----------------------------------------------------------

    def run(self) -> None:
        body = list(self.info.node.body)
        state = _State()
        status = self._walk(body, state)
        if status == "fall":
            self._check_exit(state, None, None)

    def emit(self, rule: str, line: int, msg: str) -> None:
        self.findings.append(Finding(rule, self.rel, line, msg))

    def _new_ob(self, res: ResourceClass, line: int, **kw) -> _Ob:
        self._uid += 1
        return _Ob(uid=self._uid, res=res, line=line, **kw)

    # -- statement walk ---------------------------------------------------

    def _walk(self, stmts, state: "_State") -> str:
        """Returns "fall" when control can reach past ``stmts``."""
        for stmt in stmts:
            self._check_transferred_use(stmt, state)
            status = self._stmt(stmt, state)
            if status == "exit":
                return "exit"
        return "fall"

    def _stmt(self, stmt, state: "_State") -> str:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt = stmt.targets[0]
            bind = tgt.id if isinstance(tgt, ast.Name) else None
            # alias: res = self._res
            if (
                bind
                and isinstance(stmt.value, ast.Attribute)
                and isinstance(stmt.value.value, ast.Name)
            ):
                state.aliases[bind] = stmt.value.attr
            self._expr(stmt.value, state, bind_var=bind)
            if bind is None:
                # storing a handle into an attribute/element transfers
                # ownership to the heap
                self._escape_stores(stmt.targets[0], stmt.value, state)
            return "fall"
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            bind = (
                stmt.target.id
                if isinstance(stmt.target, ast.Name)
                else None
            )
            self._expr(stmt.value, state, bind_var=bind)
            return "fall"
        if isinstance(stmt, (ast.Expr, ast.AugAssign)):
            val = stmt.value if isinstance(stmt, ast.Expr) else stmt.value
            self._expr(val, state, bind_var=None)
            return "fall"
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._expr(stmt.value, state, bind_var=None, in_return=True)
            self._check_exit(state, stmt, stmt.value)
            return "exit"
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._expr(stmt.exc, state, bind_var=None)
            self._check_exit(state, stmt, None, raising=True)
            return "exit"
        if isinstance(stmt, ast.If):
            return self._if(stmt, state)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, state)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, state)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, state, bind_var=None)
            self._walk(stmt.body, state)
            if stmt.orelse:
                self._walk(stmt.orelse, state)
            return "fall"
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, state, bind_var=None)
            self._walk(stmt.body, state)
            if stmt.orelse:
                self._walk(stmt.orelse, state)
            # ``while True`` with no break never falls through
            if (
                isinstance(stmt.test, ast.Constant)
                and stmt.test.value
                and not any(
                    isinstance(n, ast.Break) for n in ast.walk(stmt)
                )
            ):
                return "exit"
            return "fall"
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return "fall"
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return "fall"
        if isinstance(stmt, (ast.Assert, ast.Delete)):
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    self._call(node, state, bind_var=None)
            return "fall"
        # anything else: process calls generically
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._call(node, state, bind_var=None)
        return "fall"

    # -- branches ---------------------------------------------------------

    def _if(self, stmt: ast.If, state: "_State") -> str:
        before = {ob.uid for ob in state.obs}
        before_states = {ob.uid: ob.state for ob in state.obs}
        self._expr(stmt.test, state, bind_var=None)
        test_obs = [ob for ob in state.obs if ob.uid not in before]
        # obligations the test itself turned into pending transfers
        # (``if not pool.try_submit(closure):``) are gated by it too
        test_obs.extend(
            ob
            for ob in state.obs
            if ob.uid in before
            and ob.state == "pending_transfer"
            and before_states.get(ob.uid) != "pending_transfer"
        )
        gate, negated = self._gate(stmt.test, state, test_obs)

        then_state = state.clone()
        else_state = state.clone()
        if gate is not None:
            self._refine(then_state, gate, truthy=not negated)
            self._refine(else_state, gate, truthy=negated)
        then_status = self._walk(stmt.body, then_state)
        else_status = (
            self._walk(stmt.orelse, else_state) if stmt.orelse else "fall"
        )
        if then_status == "exit" and else_status == "exit":
            return "exit"
        if then_status == "exit":
            state.obs = else_state.obs
            state.aliases = else_state.aliases
            return "fall"
        if else_status == "exit":
            state.obs = then_state.obs
            state.aliases = then_state.aliases
            return "fall"
        merged = _merge(then_state, else_state)
        state.obs = merged.obs
        state.aliases = merged.aliases
        return "fall"

    def _gate(self, test, state, test_obs):
        """(gate, negated): gate identifies pending obligations this
        test decides — the uid list of obligations created in the test
        itself, or a cond_var name."""
        negated = False
        node = test
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            negated = True
            node = node.operand
        if test_obs:
            return [ob.uid for ob in test_obs], negated
        if isinstance(node, ast.Name):
            uids = [
                ob.uid
                for ob in state.obs
                if ob.cond_var == node.id
                and ob.state in ("pending", "pending_transfer")
            ]
            if uids:
                return uids, negated
        return None, negated

    def _refine(self, state: "_State", uids, *, truthy: bool) -> None:
        for ob in state.obs:
            if ob.uid not in uids:
                continue
            if ob.state == "pending":
                ob.state = "held" if truthy else "released"
            elif ob.state == "pending_transfer":
                ob.state = "transferred" if truthy else "held"

    # -- try / with -------------------------------------------------------

    def _try(self, stmt: ast.Try, state: "_State") -> str:
        effects = self._release_effects(stmt.finalbody)
        protect = set()
        for res_name, var in effects:
            protect.add(("res", res_name))
            if var:
                protect.add(("var", var))
        # a handler that releases and re-raises protects the same way
        for handler in stmt.handlers:
            if any(isinstance(n, ast.Raise) for n in handler.body):
                for res_name, var in self._release_effects(handler.body):
                    protect.add(("res", res_name))
                    if var:
                        protect.add(("var", var))
        self.frames.append((protect, effects))
        entry = state.clone()
        body_status = self._walk(stmt.body, state)
        if body_status == "fall" and stmt.orelse:
            body_status = self._walk(stmt.orelse, state)
        handler_states = []
        for handler in stmt.handlers:
            hs = entry.clone()
            if self._walk(handler.body, hs) == "fall":
                handler_states.append(hs)
        self.frames.pop()
        if body_status == "fall":
            merged = state
            for hs in handler_states:
                merged = _merge(merged, hs)
        elif handler_states:
            merged = handler_states[0]
            for hs in handler_states[1:]:
                merged = _merge(merged, hs)
        else:
            # neither body nor any handler falls through, but finally
            # still runs on the way out
            if stmt.finalbody:
                fs = entry.clone()
                self._walk(stmt.finalbody, fs)
            return "exit"
        state.obs = merged.obs
        state.aliases = merged.aliases
        if stmt.finalbody:
            return (
                "exit"
                if self._walk(stmt.finalbody, state) == "exit"
                else "fall"
            )
        return "fall"

    def _with(self, stmt, state: "_State") -> str:
        with_obs: "list[_Ob]" = []
        for item in stmt.items:
            before = {ob.uid for ob in state.obs}
            bind = (
                item.optional_vars.id
                if isinstance(item.optional_vars, ast.Name)
                else None
            )
            self._expr(item.context_expr, state, bind_var=bind)
            for ob in state.obs:
                if ob.uid not in before and ob.state in (
                    "held",
                    "pending",
                ):
                    ob.state = "held"
                    ob.from_with = True
                    with_obs.append(ob)
        protect = set()
        for ob in with_obs:
            protect.add(("res", ob.res.name))
            if ob.var:
                protect.add(("var", ob.var))
        effects = [(ob.res.name, ob.var) for ob in with_obs]
        self.frames.append((protect, effects))
        status = self._walk(stmt.body, state)
        self.frames.pop()
        for ob in with_obs:
            if ob.state in ("held", "maybe"):
                ob.state = "released"
        return status

    def _release_effects(self, stmts) -> "list[tuple[str, str | None]]":
        """(resource, handle-var|None) releases a block performs —
        used to replay enclosing ``finally`` bodies on early exits."""
        out: "list[tuple[str, str | None]]" = []
        for stmt in stmts:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                for res in self.resources:
                    for spec in res.release_calls:
                        if _name_matches(spec, node):
                            var = None
                            if res.handle and node.args and isinstance(
                                node.args[0], ast.Name
                            ):
                                var = node.args[0].id
                            out.append((res.name, var))
                    if res.handle and isinstance(node.func, ast.Attribute):
                        if node.func.attr in res.release_methods and (
                            isinstance(node.func.value, ast.Name)
                        ):
                            out.append((res.name, node.func.value.id))
                credit = self._callee_credit(node)
                for res_name, count in credit.items():
                    out.extend([(res_name, None)] * count)
        return out

    # -- exits ------------------------------------------------------------

    def _check_exit(self, state, stmt, ret_value, raising=False) -> None:
        line = stmt.lineno if stmt is not None else None
        temp = state.clone()
        # finally blocks on the way out still run their releases
        for _, effects in reversed(self.frames):
            for res_name, var in effects:
                self._discharge(temp, res_name, var, None, quiet=True)
        ret_names: "set[str]" = set()
        # bare `return` is a falsy (None) result for seam purposes
        ret_const = (
            _const_value(ret_value) if ret_value is not None else None
        )
        if ret_value is not None:
            for node in ast.walk(ret_value):
                if isinstance(node, ast.Name):
                    ret_names.add(node.id)
        for ob in temp.live():
            res = ob.res
            # a pending transfer nobody refuted is a transfer
            if ob.state == "pending_transfer":
                continue
            # returning the handle / the gating var hands it to the
            # caller
            if ob.var and ob.var in ret_names:
                continue
            if ob.cond_var and ob.cond_var in ret_names:
                continue
            # acquire seams: a truthy return hands held tokens to the
            # caller by contract; unconditional seams do so on every
            # non-raising exit
            if res.name in self.seam_res:
                if not res.conditional and not raising:
                    continue
                if res.conditional:
                    if ret_const is _MISSING:
                        # non-constant return: the result decides
                        # ownership dynamically; trust the seam
                        continue
                    if ret_const not in _FALSY_CONSTS:
                        continue  # truthy constant: caller owns
                    # falsy constant return while holding: a leak
            if raising and self._protected(ob):
                continue
            anchor = line if line is not None else ob.line
            self.emit(
                "MTPU601",
                anchor,
                f"{res.name} acquired at line {ob.line} leaks on this "
                "exit path: no release or registered ownership "
                "transfer before "
                + ("raise" if raising else "function exit"),
            )
            ob.state = "maybe"

    def _protected(self, ob: "_Ob") -> bool:
        for protect, _ in self.frames:
            if ("res", ob.res.name) in protect:
                return True
            if ob.var and ("var", ob.var) in protect:
                return True
        return False

    def _check_transferred_use(self, stmt, state: "_State") -> None:
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return
        for ob in state.obs:
            if ob.state != "transferred" or not ob.var:
                continue
            for node in ast.walk(stmt):
                if (
                    isinstance(node, ast.Name)
                    and node.id == ob.var
                    and isinstance(node.ctx, ast.Load)
                    and node.lineno > ob.transfer_line
                ):
                    self.emit(
                        "MTPU604",
                        node.lineno,
                        f"{ob.res.name} handle '{ob.var}' used after "
                        f"ownership transfer at line {ob.transfer_line}",
                    )
                    ob.state = "maybe"
                    break

    # -- expressions ------------------------------------------------------

    def _expr(self, expr, state, *, bind_var, in_return=False) -> None:
        # lambda bodies do not run when the expression does
        deferred: "set[int]" = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                for sub in ast.walk(node.body):
                    if isinstance(sub, ast.Call):
                        deferred.add(id(sub))
        calls = [
            n
            for n in ast.walk(expr)
            if isinstance(n, ast.Call) and id(n) not in deferred
        ]
        outer = expr
        while isinstance(outer, ast.Await):
            outer = outer.value
        # reversed pre-order puts every argument call before the call
        # that consumes it — evaluation order, which is what MTPU603's
        # "held across" means
        for call in reversed(calls):
            self._call(
                call,
                state,
                bind_var=bind_var if call is outer else None,
                nested=call is not outer,
                in_return=in_return,
            )

    def _escape_stores(self, target, value, state: "_State") -> None:
        names: "set[str]" = set()
        for node in ast.walk(value):
            if isinstance(node, ast.Name):
                names.add(node.id)
        if not names:
            return
        for ob in state.obs:
            if (
                ob.var
                and ob.var in names
                and ob.state in ("held", "pending")
            ):
                # heap escape: ownership leaves the frame silently
                ob.state = "released"

    # -- the call classifier ---------------------------------------------

    def _call(
        self,
        call: ast.Call,
        state: "_State",
        *,
        bind_var,
        nested: bool = False,
        in_return: bool = False,
    ) -> None:
        name = _call_name(call)
        handled = False
        for res in self.resources:
            # releases first: `release(acquire())` shapes are not in
            # this tree, and release-before-acquire keeps `x = f(x)`
            # stable
            for spec in res.release_calls:
                if _name_matches(spec, call):
                    var = None
                    if res.handle and call.args and isinstance(
                        call.args[0], ast.Name
                    ):
                        var = call.args[0].id
                    self._discharge(state, res.name, var, call)
                    handled = True
            if res.handle and isinstance(call.func, ast.Attribute):
                recv = call.func.value
                if (
                    call.func.attr in res.release_methods
                    and isinstance(recv, ast.Name)
                ):
                    for ob in state.obs:
                        if ob.var == recv.id and ob.res is res:
                            self._discharge(
                                state, res.name, recv.id, call
                            )
                            handled = True
                            break
            for spec in res.transfer_calls:
                if _name_matches(spec, call):
                    args = _arg_names(call)
                    recv = (
                        call.func.value.id
                        if isinstance(call.func, ast.Attribute)
                        and isinstance(call.func.value, ast.Name)
                        else None
                    )
                    for ob in state.obs:
                        if ob.state in ("held", "pending") and (
                            ob.var in args or ob.var == recv
                            if ob.var
                            else False
                        ):
                            ob.state = "transferred"
                            ob.transfer_line = call.lineno
                            handled = True
            op = _attr_op(call, state)
            if op is not None:
                if op in res.acquire_attr_ops:
                    self.ever_acquired.add(res.name)
                    state.obs.append(
                        self._new_ob(res, call.lineno)
                    )
                    handled = True
                elif op in res.release_attr_ops:
                    self._discharge(state, res.name, None, call)
                    handled = True
            for spec in res.acquire_calls:
                if not _name_matches(spec, call):
                    continue
                if res.acquire_kwarg and not _has_kwarg(
                    call, res.acquire_kwarg
                ):
                    continue
                if res.handle and (nested or in_return):
                    # a handle constructed inside a larger expression
                    # (tuple, comprehension, argument) or returned
                    # directly escapes to whoever consumes it —
                    # ownership never rests in this frame
                    handled = True
                    continue
                self.ever_acquired.add(res.name)
                ob = self._new_ob(res, call.lineno)
                if res.handle:
                    ob.var = bind_var
                if res.conditional:
                    ob.state = "pending"
                    ob.cond_var = bind_var
                state.obs.append(ob)
                handled = True
        if not handled:
            # interprocedural credit: callee (or a closure argument)
            # releases on the caller's behalf
            credit = self._callee_credit(call)
            closure_credit = self._closure_arg_credit(call)
            for res_name, count in credit.items():
                for _ in range(count):
                    self._discharge(state, res_name, None, call)
            if closure_credit:
                for res_name, count in closure_credit.items():
                    for _ in range(count):
                        self._transfer_token(
                            state, res_name, call, bind_var
                        )
                handled = True
            elif credit:
                handled = True
        if not handled:
            # passing a live handle to an unregistered call lets it
            # escape the frame: ownership moves, tracking stops
            args = _arg_names(call)
            if args:
                for ob in state.obs:
                    if (
                        ob.var
                        and ob.var in args
                        and ob.state in ("held", "pending")
                    ):
                        ob.state = "released"
        if not handled and name not in _SAFE_CALLS:
            self._raisable(call, state)

    def _transfer_token(self, state, res_name, call, bind_var) -> None:
        """A closure that releases R was handed off: the obligation
        becomes a pending transfer — the hand-off result (bound, or the
        enclosing ``if`` test) decides whether the pool took it; a
        pending transfer nobody tests is trusted at exit."""
        for ob in reversed(state.obs):
            if ob.res.name == res_name and ob.state == "held":
                ob.state = "pending_transfer"
                ob.cond_var = bind_var
                ob.transfer_line = call.lineno
                return

    def _discharge(
        self, state, res_name, var, call, *, quiet=False
    ) -> None:
        line = call.lineno if call is not None else 0
        # prefer the exact handle, then the most recent live holding
        candidates = [
            ob
            for ob in reversed(state.obs)
            if ob.res.name == res_name
            and (var is None or ob.var == var)
        ]
        for ob in candidates:
            if ob.state in ("held", "pending"):
                ob.state = "released"
                return
        for ob in candidates:
            if ob.state in ("maybe", "pending_transfer"):
                ob.state = "released"
                return
        if quiet:
            return
        for ob in candidates:
            if ob.state == "released":
                self.emit(
                    "MTPU602",
                    line,
                    f"{res_name} already released (acquired at line "
                    f"{ob.line}) is released again",
                )
                return
            if ob.state == "transferred":
                self.emit(
                    "MTPU604",
                    line,
                    f"{res_name} released after ownership transfer at "
                    f"line {ob.transfer_line}",
                )
                return
        if res_name in self.ever_acquired:
            self.emit(
                "MTPU602",
                line,
                f"{res_name} released more times than acquired on "
                "this path",
            )
            return
        # releasing a resource this frame never acquired: credit the
        # caller (the helper-releases-for-caller pattern)
        self.credit[res_name] = self.credit.get(res_name, 0) + 1

    def _callee_credit(self, call: ast.Call) -> "dict[str, int]":
        edge = self.p.graph.call_info.get(id(call))
        if edge is None or edge.callee in (None, "<multi>"):
            return {}
        if edge.boundary is not None:
            # the callee runs later on another thread/loop (or not at
            # all, if the pool sheds) — its releases are a transfer,
            # not synchronous credit; _closure_arg_credit handles it
            return {}
        return self.p.summaries.get(edge.callee, {})

    def _closure_arg_credit(self, call: ast.Call) -> "dict[str, int]":
        out: "dict[str, int]" = {}
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Name) and arg.id in self.local_defs:
                child = self.local_defs[arg.id]
                for res_name, count in self.p.summaries.get(
                    child, {}
                ).items():
                    out[res_name] = out.get(res_name, 0) + count
        return out

    def _raisable(self, call: ast.Call, state: "_State") -> None:
        for ob in state.live():
            if ob.state != "held" or ob.warned603 or ob.from_with:
                continue
            if ob.line >= call.lineno:
                continue
            if self._protected(ob):
                continue
            ob.warned603 = True
            self.emit(
                "MTPU603",
                call.lineno,
                f"{ob.res.name} acquired at line {ob.line} is held "
                f"across raisable call "
                f"'{_call_name(call) or '<expr>'}' with no try/finally "
                "protecting its release",
            )


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


class _LifecyclePass:
    def __init__(self, sources, registry, graph):
        self.sources: "dict[str, ParsedModule]" = sources
        self.registry = registry
        self.graph = graph
        self.summaries: "dict[str, dict[str, int]]" = {}
        self.findings: "list[Finding]" = []

    def run(self) -> None:
        scoped_funcs = [
            info
            for qname, info in sorted(self.graph.funcs.items())
            if self.registry.scoped(info.rel_path)
        ]
        # fixpoint the release-credit summaries (a helper's credit can
        # come from its own callees), then one reporting pass
        for _ in range(4):
            changed = False
            for info in scoped_funcs:
                interp = _Interp(
                    self, info, self.registry.scoped(info.rel_path)
                )
                interp.run()
                if interp.credit != self.summaries.get(
                    info.qname, {}
                ):
                    self.summaries[info.qname] = dict(interp.credit)
                    changed = True
            if not changed:
                break
        for info in scoped_funcs:
            interp = _Interp(
                self, info, self.registry.scoped(info.rel_path)
            )
            interp.run()
            self.findings.extend(interp.findings)
        self._check_registry_drift()
        self.findings.extend(
            check_knobs(self.sources, repo_root=_repo_root())
        )

    def _check_registry_drift(self) -> None:
        # direction 1: every registered def resolves in the call graph
        for res in self.registry.resources:
            for rel, qname in res.defs:
                if self.graph.lookup(rel, qname) is None:
                    if rel not in self.sources:
                        continue  # file outside the analyzed set
                    self.emit_drift(
                        rel,
                        1,
                        f"resource_registry names {qname} for "
                        f"{res.name} but the call graph has no such "
                        "def in this module",
                    )
        # direction 2: acquire-shaped defs in registered scopes must
        # be registered
        known = registered_call_names(self.registry)
        for qname, info in sorted(self.graph.funcs.items()):
            if not self.registry.scoped(info.rel_path):
                continue
            bare = info.name
            shaped = bare.startswith(
                ACQUIRE_SHAPED_PREFIXES
            ) or bare in ACQUIRE_SHAPED_NAMES
            if shaped and bare not in known:
                self.emit_drift(
                    info.rel_path,
                    info.lineno,
                    f"acquire-shaped def '{bare}' in a registered "
                    "resource module has no resource_registry entry",
                )

    def emit_drift(self, rel, line, msg) -> None:
        self.findings.append(Finding("MTPU605", rel, line, msg))


# ---------------------------------------------------------------------------
# MTPU606: config-knob drift
# ---------------------------------------------------------------------------


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _env_read_sites(mod: ParsedModule):
    """(line, knob, is_prefix) for every MINIO_TPU_* env read —
    direct environ/getenv calls, subscripts, membership tests, and
    calls through local first-arg-is-the-key wrapper helpers."""
    tree = mod.tree
    if tree is None:
        return
    wrappers: "set[str]" = set()
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) and node.args.args:
            first = node.args.args[0].arg
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    nm = _call_name(sub)
                    if (
                        nm in ("get", "getenv")
                        and sub.args
                        and isinstance(sub.args[0], ast.Name)
                        and sub.args[0].id == first
                    ):
                        wrappers.add(node.name)

    def _env_recv(expr) -> bool:
        try:
            text = ast.unparse(expr)
        except Exception:
            return False
        return "environ" in text or text == "env"

    def _knob_of(arg):
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value.startswith("MINIO_TPU_"):
                return arg.value, False
        if isinstance(arg, ast.JoinedStr) and arg.values:
            head = arg.values[0]
            if isinstance(head, ast.Constant) and isinstance(
                head.value, str
            ) and head.value.startswith("MINIO_TPU_"):
                return head.value, True
        return None, False

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            nm = _call_name(node)
            base = (
                node.func.value
                if isinstance(node.func, ast.Attribute)
                else None
            )
            is_env = nm == "getenv" or (
                nm in ("get", "setdefault", "pop")
                and base is not None
                and _env_recv(base)
            )
            if is_env and node.args:
                knob, pref = _knob_of(node.args[0])
                if knob:
                    yield node.lineno, knob, pref
            elif nm in wrappers and node.args:
                knob, pref = _knob_of(node.args[0])
                if knob:
                    yield node.lineno, knob, pref
        elif isinstance(node, ast.Subscript) and _env_recv(node.value):
            knob, pref = _knob_of(node.slice)
            if knob:
                yield node.lineno, knob, pref
        elif isinstance(node, ast.Compare) and any(
            isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
        ):
            if node.comparators and _env_recv(node.comparators[0]):
                knob, pref = _knob_of(node.left)
                if knob:
                    yield node.lineno, knob, pref


def _parse_knob_registry(mod: ParsedModule):
    """(exact: {name: line}, prefixes: {prefix: line}) from the
    KNOBS/PREFIX_KNOBS dict literals in config/knobs.py."""
    exact: "dict[str, int]" = {}
    prefixes: "dict[str, int]" = {}
    if mod.tree is None:
        return exact, prefixes
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign):
            tgt, value = node.target, node.value
        else:
            continue
        if not isinstance(tgt, ast.Name):
            continue
        if tgt.id not in ("KNOBS", "PREFIX_KNOBS"):
            continue
        if not isinstance(value, ast.Dict):
            continue
        table = exact if tgt.id == "KNOBS" else prefixes
        for key in value.keys:
            if isinstance(key, ast.Constant) and isinstance(
                key.value, str
            ):
                table[key.value] = key.lineno
    return exact, prefixes


def check_knobs(
    sources: "dict[str, ParsedModule]",
    *,
    repo_root: "str | None" = None,
    readme_text: "str | None" = None,
) -> "list[Finding]":
    """MTPU606 over an analyzed source set.

    Read-site checks always run; the registry-side checks (README
    mention, dead entries) run only when the registry module itself is
    part of the set — a --paths run over a fixture cannot audit the
    whole tree's docs.
    """
    findings: "list[Finding]" = []
    reg_mod = sources.get(KNOBS_REL)
    exact, prefixes = (
        _parse_knob_registry(reg_mod) if reg_mod else ({}, {})
    )

    reads: "dict[str, list[tuple[str, int]]]" = {}
    prefix_reads: "list[tuple[str, int, str]]" = []
    for rel, mod in sorted(sources.items()):
        if rel == KNOBS_REL:
            continue
        for line, knob, is_pref in _env_read_sites(mod):
            if is_pref:
                prefix_reads.append((rel, line, knob))
            else:
                reads.setdefault(knob, []).append((rel, line))

    def _registered(knob: str) -> bool:
        if knob in exact:
            return True
        return any(knob.startswith(p) for p in prefixes)

    if reg_mod is not None:
        for knob, sites in sorted(reads.items()):
            if not _registered(knob):
                rel, line = sites[0]
                findings.append(
                    Finding(
                        "MTPU606",
                        rel,
                        line,
                        f"env knob {knob} is read here but has no "
                        "entry in minio_tpu/config/knobs.py (register "
                        "a default + README row)",
                    )
                )
        for rel, line, head in sorted(prefix_reads):
            if not any(
                head.startswith(p) or p.startswith(head)
                for p in prefixes
            ) and not _registered(head):
                findings.append(
                    Finding(
                        "MTPU606",
                        rel,
                        line,
                        f"dynamic env knob '{head}*' is read here but "
                        "no PREFIX_KNOBS family covers it in "
                        "minio_tpu/config/knobs.py",
                    )
                )
        if readme_text is None:
            root = repo_root or _repo_root()
            try:
                with open(
                    os.path.join(root, "README.md"), encoding="utf-8"
                ) as fh:
                    readme_text = fh.read()
            except OSError:
                readme_text = ""
        for knob, line in sorted(exact.items()):
            if knob not in readme_text:
                findings.append(
                    Finding(
                        "MTPU606",
                        KNOBS_REL,
                        line,
                        f"registered knob {knob} has no README.md "
                        "mention",
                    )
                )
            if knob not in reads:
                findings.append(
                    Finding(
                        "MTPU606",
                        KNOBS_REL,
                        line,
                        f"registered knob {knob} is read nowhere in "
                        "the tree (dead registry entry)",
                    )
                )
        for prefix, line in sorted(prefixes.items()):
            if prefix not in readme_text:
                findings.append(
                    Finding(
                        "MTPU606",
                        KNOBS_REL,
                        line,
                        f"registered knob family {prefix}* has no "
                        "README.md mention",
                    )
                )
            if not any(
                head.startswith(prefix) or prefix.startswith(head)
                for _, _, head in prefix_reads
            ) and not any(k.startswith(prefix) for k in reads):
                findings.append(
                    Finding(
                        "MTPU606",
                        KNOBS_REL,
                        line,
                        f"registered knob family {prefix}* is read "
                        "nowhere in the tree (dead registry entry)",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def analyze_sources(
    sources: "dict[str, ParsedModule]",
    *,
    registry: "Registry | None" = None,
    graph: "callgraph.CallGraph | None" = None,
) -> LifecycleReport:
    """Run the lifecycle pass over parsed sources.

    ``registry`` defaults to the shipped resource table; tests inject
    synthetic ones.  ``graph`` lets the CLI share one call-graph build
    with the deviceflow pass.
    """
    t0 = time.monotonic()
    registry = registry or Registry.default()
    if graph is None:
        graph = callgraph.build(sources)
    p = _LifecyclePass(sources, registry, graph)
    p.run()
    findings = sorted(set(p.findings), key=Finding.sort_key)
    return LifecycleReport(
        findings=findings,
        graph=graph,
        seconds=round(time.monotonic() - t0, 3),
    )
