"""Findings, the rule catalog, and inline suppression.

The project-native analogue of Go's vet/staticcheck diagnostics: every
analyzer pass (hot-path lint, kernel contract checker, lock-order
auditor) emits ``Finding`` records carrying a stable ``MTPU###`` rule id
so future PRs can diff reports, gate CI on exact rule sets, and suppress
individual sites with ``# noqa: MTPU###`` where a violation is a
documented, deliberate exception.
"""

from __future__ import annotations

import dataclasses
import re

# Rule catalog.  1xx = hot-path lint (AST), 2xx = kernel contract
# checker (abstract eval), 3xx = lock-order auditor (runtime shim).
RULES: "dict[str, str]" = {
    "MTPU101": (
        "host-device sync (block_until_ready / jax.device_get / .item() / "
        "np.asarray of a traced value) inside jit-traced code or a "
        "device-only module"
    ),
    "MTPU102": (
        "retrace bomb: jax.jit function takes a non-array Python "
        "parameter (int/str/bool/bytes/float/tuple annotation) not "
        "routed through static_argnames/static_argnums"
    ),
    "MTPU103": (
        "silently swallowed failure: `except Exception/BaseException/"
        "bare except` whose body is only pass/..."
    ),
    "MTPU104": (
        "prometheus metric-name convention: family must be "
        "miniotpu_-prefixed lowercase, counters must end in _total"
    ),
    "MTPU105": (
        "prometheus label-key hygiene: label keys must match "
        "[a-z_][a-z0-9_]*"
    ),
    "MTPU201": "kernel contract: wrong output dtype from a jitted entry point",
    "MTPU202": "kernel contract: wrong output shape from a jitted entry point",
    "MTPU203": (
        "kernel contract: encode->reconstruct shape round-trip broken"
    ),
    "MTPU204": (
        "kernel contract: jitted entry point in minio_tpu/ops has no "
        "registered contract check"
    ),
    "MTPU301": "lock-order cycle in the observed acquisition graph",
    "MTPU302": (
        "blocking call (sleep / socket connect / subprocess) while "
        "holding a registered hot-path lock"
    ),
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: rule id + location + message.

    ``path`` is repo-relative where the finding is file-anchored;
    runtime passes anchor at the closest code object they can name.
    """

    rule: str
    path: str
    line: int
    message: str

    def sort_key(self):
        return (self.path, self.line, self.rule, self.message)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


_NOQA_RE = re.compile(
    r"#\s*noqa(?::\s*(?P<codes>[A-Z]+[0-9]+(?:\s*,\s*[A-Z]+[0-9]+)*))?",
)


def noqa_codes_for_line(line: str) -> "set[str] | None":
    """Suppression codes on a source line.

    Returns None when the line carries no noqa directive, the empty set
    for a bare ``# noqa`` (suppress everything), else the specific codes.
    """
    m = _NOQA_RE.search(line)
    if m is None:
        return None
    codes = m.group("codes")
    if not codes:
        return set()
    return {c.strip() for c in codes.split(",")}


def filter_suppressed(
    findings: "list[Finding]", source_lines: "dict[str, list[str]]"
) -> "list[Finding]":
    """Drop findings whose source line carries a matching noqa.

    ``source_lines`` maps finding paths to their file's lines; findings
    for paths not in the map (runtime findings) pass through untouched.
    """
    out = []
    for f in findings:
        lines = source_lines.get(f.path)
        if lines is not None and 1 <= f.line <= len(lines):
            codes = noqa_codes_for_line(lines[f.line - 1])
            if codes is not None and (not codes or f.rule in codes):
                continue
        out.append(f)
    return out
