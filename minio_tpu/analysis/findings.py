"""Findings, the rule catalog, and inline suppression.

The project-native analogue of Go's vet/staticcheck diagnostics: every
analyzer pass (hot-path lint, kernel contract checker, lock-order
auditor) emits ``Finding`` records carrying a stable ``MTPU###`` rule id
so future PRs can diff reports, gate CI on exact rule sets, and suppress
individual sites with ``# noqa: MTPU###`` where a violation is a
documented, deliberate exception.
"""

from __future__ import annotations

import dataclasses
import re

# Rule catalog.  1xx = hot-path lint (AST), 2xx = kernel contract
# checker (abstract eval), 3xx = lock-order auditor (runtime shim),
# 4xx = ctypes/ABI contract checker (native FFI seam).
RULES: "dict[str, str]" = {
    "MTPU101": (
        "host-device sync (block_until_ready / jax.device_get / .item() / "
        "np.asarray of a traced value) inside jit-traced code or a "
        "device-only module"
    ),
    "MTPU102": (
        "retrace bomb: jax.jit function takes a non-array Python "
        "parameter (int/str/bool/bytes/float/tuple annotation) not "
        "routed through static_argnames/static_argnums"
    ),
    "MTPU103": (
        "silently swallowed failure: `except Exception/BaseException/"
        "bare except` whose body is only pass/..."
    ),
    "MTPU104": (
        "prometheus metric-name convention: family must be "
        "miniotpu_-prefixed lowercase, counters must end in _total"
    ),
    "MTPU105": (
        "prometheus label-key hygiene: label keys must match "
        "[a-z_][a-z0-9_]*"
    ),
    "MTPU106": (
        "unused suppression: a `# noqa: MTPU###` whose rule does not "
        "fire on that line (stale suppressions rot; silence MTPU106 "
        "itself on the line to keep one deliberately)"
    ),
    "MTPU107": (
        "eager parity readback: np.asarray/np.array/jax.device_get of a "
        "device parity output outside the *_end/drain seams in "
        "minio_tpu/ops or codec/backend.py (re-introduces the D2H "
        "round-trip the digest-only PUT path removed)"
    ),
    "MTPU108": (
        "event-loop-blocking call inside an async def under "
        "minio_tpu/server/: time.sleep, raw socket send/recv, or a "
        "Future.result()/Event.wait() that is not awaited (one stalled "
        "coroutine stalls every connection; route blocking work through "
        "the worker-pool bridge)"
    ),
    "MTPU109": (
        "hand-written PartitionSpec literal in minio_tpu/parallel or "
        "minio_tpu/ops outside parallel/rules.py: shardings must come "
        "from the partition-rule table (rules.spec_for), the single "
        "source of truth the compile seam fingerprints"
    ),
    "MTPU110": (
        "object-data mutation outside the read-cache invalidation seam: "
        "a function in objectlayer/erasure_object.py or "
        "erasure_multipart.py that calls rename_data/delete_version (or "
        "delete_file/write_metadata/update_metadata on a non-SYS_VOL "
        "volume) must also call the invalidation seam "
        "(_invalidate_read_cache / cache.invalidate_object), or peers "
        "serve stale cached groups and FileInfo"
    ),
    "MTPU111": (
        "eager S3-Select readback: np.asarray/np.array/jax.device_get in "
        "s3select/device.py outside the result-drain seam (functions "
        "whose name contains 'drain'); only candidate row bytes may "
        "cross D2H, or the pushdown degenerates into a whole-plane "
        "host scan"
    ),
    "MTPU201": "kernel contract: wrong output dtype from a jitted entry point",
    "MTPU202": "kernel contract: wrong output shape from a jitted entry point",
    "MTPU203": (
        "kernel contract: encode->reconstruct shape round-trip broken"
    ),
    "MTPU204": (
        "kernel contract: jitted entry point in minio_tpu/ops has no "
        "registered contract check"
    ),
    "MTPU301": "lock-order cycle in the observed acquisition graph",
    "MTPU302": (
        "blocking call (sleep / socket connect / subprocess) while "
        "holding a registered hot-path lock"
    ),
    "MTPU401": (
        "ABI contract: ctypes binding arity differs from the native "
        "export's C parameter count (or annotation disagrees with the "
        "C signature)"
    ),
    "MTPU402": (
        "ABI contract: argtypes/restype drift between a ctypes binding "
        "and the export's declared `// @ctypes` annotation"
    ),
    "MTPU403": (
        "ABI contract: exported symbol with no ctypes binding, or a "
        "binding for a symbol the library does not export"
    ),
    "MTPU404": (
        "ABI contract: buffer pointer passed to native code with a "
        "length argument computed from a different array's shape"
    ),
    "MTPU405": (
        "ABI contract: numpy buffer reaches .ctypes.data_as() without "
        "contiguity evidence (ascontiguousarray/require/flags assert)"
    ),
    "MTPU501": (
        "device dataflow: use-after-donate — a value passed at a "
        "donate_argnums position of a registered donating entry point "
        "is read again afterwards (XLA may alias the donated buffer "
        "into an output; the PR 14 bug class, caught statically)"
    ),
    "MTPU502": (
        "device dataflow: interprocedural D2H escape — a "
        "device-provenance value (return of a registered jitted entry "
        "point, through any chain of calls) reaches np.asarray / "
        "bytes() / .item() / jax.device_get outside a registered drain "
        "seam (whole-tree generalization of MTPU107/111)"
    ),
    "MTPU503": (
        "device dataflow: device value captured across a thread/loop "
        "boundary (iopool.submit*, worker-pool submit/spawn_stream, "
        "run_coroutine_threadsafe, run_in_executor, Thread(target=)) "
        "without materialization — the D2H becomes a hidden sync on an "
        "arbitrary thread"
    ),
    "MTPU504": (
        "device dataflow: call-graph-deep blocking-under-async — a "
        "blocking call (time.sleep, raw socket I/O, Future.result(), "
        "non-asyncio .wait()) in a sync function reachable from a "
        "minio_tpu/server async def through plain calls, so it runs on "
        "the event loop (MTPU108 one-or-more frames deep; worker-pool "
        "boundary edges exempt the sanctioned sync-def bridges)"
    ),
    "MTPU505": (
        "device dataflow: registry drift — kernel_contracts declares a "
        "jitted entry point, donation position, or drain seam the tree "
        "does not have, or the tree declares one the registry misses "
        "(the MTPU403 orphan-check discipline for dataflow facts)"
    ),
    "MTPU601": (
        "resource lifecycle: leaked acquire — a registered resource "
        "(staging-ledger reservation, admission token, parity ref, "
        "io-pool future, rw-lock, fault hang) is acquired and a path "
        "reaches function exit without a matching release or a "
        "registered ownership transfer (the defer-less leak class: one "
        "missed release starves the device budget or wedges admission)"
    ),
    "MTPU602": (
        "resource lifecycle: double release — the same acquisition is "
        "released twice on one path (over-release corrupts the ledger "
        "or admission counters as silently as a leak)"
    ),
    "MTPU603": (
        "resource lifecycle: unprotected hold — an acquired resource is "
        "held across a raisable call without a try/finally (or `with`) "
        "guaranteeing its release; an exception on that call leaks the "
        "resource even though the straight-line path releases it"
    ),
    "MTPU604": (
        "resource lifecycle: use after ownership transfer — a resource "
        "handed to a registered transfer seam (async handle, band "
        "adopt, caller-owned return) is released or re-used afterwards "
        "by the original holder"
    ),
    "MTPU605": (
        "resource lifecycle: registry drift — resource_registry names "
        "an acquire/release/transfer function the call graph does not "
        "have, or an acquire-shaped API in a registered resource module "
        "has no registry entry (the MTPU505 discipline for lifecycle "
        "facts)"
    ),
    "MTPU606": (
        "config-knob drift: a MINIO_TPU_* environment knob is read "
        "without a minio_tpu/config/knobs.py registry entry, or a "
        "registered knob is missing its README mention, or a registry "
        "entry names a knob nothing reads (docs, defaults, and code "
        "move together)"
    ),
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: rule id + location + message.

    ``path`` is repo-relative where the finding is file-anchored;
    runtime passes anchor at the closest code object they can name.
    """

    rule: str
    path: str
    line: int
    message: str

    def sort_key(self):
        return (self.path, self.line, self.rule, self.message)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


_NOQA_RE = re.compile(
    r"#\s*noqa(?::\s*(?P<codes>[A-Z]+[0-9]+(?:\s*,\s*[A-Z]+[0-9]+)*))?",
)


def noqa_codes_for_line(line: str) -> "set[str] | None":
    """Suppression codes on a source line.

    Returns None when the line carries no noqa directive, the empty set
    for a bare ``# noqa`` (suppress everything), else the specific codes.
    """
    m = _NOQA_RE.search(line)
    if m is None:
        return None
    codes = m.group("codes")
    if not codes:
        return set()
    return {c.strip() for c in codes.split(",")}


def filter_suppressed(
    findings: "list[Finding]", source_lines: "dict[str, list[str]]"
) -> "list[Finding]":
    """Drop findings whose source line carries a matching noqa.

    ``source_lines`` maps finding paths to their file's lines; findings
    for paths not in the map (runtime findings) pass through untouched.
    """
    out = []
    for f in findings:
        lines = source_lines.get(f.path)
        if lines is not None and 1 <= f.line <= len(lines):
            codes = noqa_codes_for_line(lines[f.line - 1])
            if codes is not None and (not codes or f.rule in codes):
                continue
        out.append(f)
    return out


# Only codes of the file-anchored passes are audited for staleness: 1xx
# (lint) and 4xx (ABI) anchor at source lines, so "does it fire here"
# is well-defined — the deviceflow pass audits its own 5xx codes the
# same way, passing its prefix explicitly.  Foreign codes (BLE001,
# F401, ...) belong to other tools; MTPU106 on a line is the sanctioned
# keep-this-suppression escape hatch and MTPU100 is the syntax-error
# sentinel.
_AUDITED_PREFIXES = ("MTPU1", "MTPU4")
_AUDIT_EXEMPT = ("MTPU100", "MTPU106")


def unused_suppressions(
    rel_path: str,
    text: str,
    raw_findings: "list[Finding]",
    prefixes: "tuple[str, ...]" = _AUDITED_PREFIXES,
) -> "list[Finding]":
    """MTPU106: noqa'd MTPU rules that do not fire on their line.

    ``raw_findings`` must be PRE-noqa-filter findings for this file
    from every file-anchored pass whose codes ``prefixes`` covers —
    otherwise a working suppression looks unused.  Comments are found
    with tokenize, so a ``# noqa:`` inside a docstring is ignored.
    """
    import io
    import tokenize

    fired: "dict[int, set[str]]" = {}
    for f in raw_findings:
        fired.setdefault(f.line, set()).add(f.rule)
    out: "list[Finding]" = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out  # broken files are MTPU100's problem, not ours
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        codes = noqa_codes_for_line(tok.string)
        if not codes:
            continue  # no noqa, or a bare one (out of audit scope)
        line = tok.start[0]
        for code in sorted(codes):
            if not code.startswith(prefixes):
                continue
            if code in _AUDIT_EXEMPT:
                continue
            if code not in fired.get(line, ()):
                out.append(
                    Finding(
                        "MTPU106",
                        rel_path,
                        line,
                        f"unused suppression: {code} does not fire on "
                        "this line; drop the noqa (or add MTPU106 to "
                        "it to keep deliberately)",
                    )
                )
    return out
