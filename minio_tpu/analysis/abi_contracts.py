"""ABI contract checker: the Python↔C seam of the native codec (MTPU4xx).

PR 4 moved the PUT/GET hot path into hand-written C++
(``native/csrc/gf_cpu.cc``) reached through ctypes bindings in
``minio_tpu/utils/native.py``.  None of the other analysis passes can
see across that boundary: an argtypes list that drifts from the C
signature corrupts memory silently, and a length argument computed from
the wrong array is a heap overflow the type system never sees.  This
pass cross-checks the two sides statically:

* MTPU401 — arity drift: the ctypes ``argtypes`` list has a different
  length than the export's C parameter list (or than its ``@ctypes``
  annotation);
* MTPU402 — argtypes/restype drift: the binding's ctypes signature
  differs from the export's declared ``// @ctypes`` annotation;
* MTPU403 — orphan: an exported symbol with no ctypes binding, or a
  binding for a symbol the library does not export;
* MTPU404 — length/pointer mismatch: a ``.ctypes.data_as()`` buffer
  pointer passed alongside a length argument whose value provably
  derives from a *different* array's ``.shape`` (AST provenance);
* MTPU405 — unchecked buffer: a numpy array reaches
  ``.ctypes.data_as()`` without contiguity evidence on its def-use
  chain (``np.ascontiguousarray`` / ``np.require`` / an assert on
  ``.flags.c_contiguous``); a non-contiguous view handed to C reads or
  writes the wrong bytes.

The C side is parsed from the ``extern "C"`` block; each export carries
a ``// @ctypes name(argtypes...) -> restype`` annotation comment that
states the intended ctypes signature (the authoritative side for
MTPU402 — C pointer types are ambiguous between ``c_void_p`` and
``c_char_p``).  The Python side is parsed from the AST: any
``<lib>.<symbol>.argtypes / .restype`` assignment is a binding, and
every function touching ``.ctypes.data_as()`` gets the MTPU404/405
data-flow treatment.

Both sides are pure text/AST analysis — the pass never compiles or
loads the library, so it runs anywhere the lint pass runs.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from .findings import Finding, filter_suppressed

# the one FFI seam in the tree; fixtures route through analyze() instead
PY_REL = "minio_tpu/utils/native.py"
CC_REL = "native/csrc/gf_cpu.cc"

# ---------------------------------------------------------------------------
# C side: extern "C" export table + @ctypes annotations
# ---------------------------------------------------------------------------

# args capture is greedy: nested parens (POINTER(c_void_p)) end before
# the final `) ->`, and annotations are single-line comments.
_ANNOT_RE = re.compile(
    r"//\s*@ctypes\s+(?P<name>[A-Za-z_]\w*)\s*\((?P<args>.*)\)"
    r"\s*->\s*(?P<restype>[\w()]+)"
)

# a definition inside the extern block: `<type words> <name>(<params>) {`
# anchored at line start so control flow (`for (...) {`) cannot match —
# those have a single identifier before the paren, this needs two.
_FUNC_RE = re.compile(
    r"^[ \t]*(?!//)(?P<ret>[A-Za-z_][A-Za-z0-9_ \t*]*?)\b"
    r"(?P<name>[A-Za-z_]\w*)\s*\((?P<params>[^)]*)\)\s*\{",
    re.M | re.S,
)


@dataclasses.dataclass
class Export:
    """One ``extern "C"`` function and its declared ctypes contract."""

    name: str
    line: int  # def line in the .cc file
    c_arity: int
    annot_args: "list[str] | None" = None
    annot_restype: "str | None" = None


def _split_args(text: str) -> "list[str]":
    """Split an arg list on top-level commas (POINTER(...) stays whole)."""
    out, depth, cur = [], 0, ""
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append(cur.strip())
            cur = ""
        else:
            cur += ch
    if cur.strip():
        out.append(cur.strip())
    return out


def _extern_c_block(text: str) -> "tuple[str, int]":
    """The extern "C" { ... } body and the line offset of its start."""
    m = re.search(r'extern\s+"C"\s*\{', text)
    if m is None:
        return "", 0
    start = m.end()
    depth = 1
    i = start
    while i < len(text) and depth:
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
        i += 1
    return text[start:i], text[:start].count("\n")


def parse_exports(cc_text: str) -> "dict[str, Export]":
    """Name -> Export for every function in the extern "C" block."""
    block, line0 = _extern_c_block(cc_text)
    annots: "dict[str, tuple[list[str], str]]" = {}
    for m in _ANNOT_RE.finditer(block):
        annots[m.group("name")] = (
            _split_args(m.group("args")),
            m.group("restype").strip(),
        )
    exports: "dict[str, Export]" = {}
    for m in _FUNC_RE.finditer(block):
        name = m.group("name")
        params = m.group("params").strip()
        arity = 0 if params in ("", "void") else len(_split_args(params))
        exp = Export(
            name=name,
            line=line0 + block[: m.start()].count("\n") + 1,
            c_arity=arity,
        )
        if name in annots:
            exp.annot_args, exp.annot_restype = annots[name]
        exports[name] = exp
    return exports


# ---------------------------------------------------------------------------
# Python side: ctypes binding assignments
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Binding:
    """ctypes signature assignments for one symbol in the loader."""

    name: str
    argtypes: "list[str] | None" = None
    argtypes_line: int = 0
    restype: "str | None" = None
    restype_line: int = 0

    @property
    def anchor(self) -> int:
        return self.argtypes_line or self.restype_line or 1


def _canon(node: ast.AST) -> str:
    """A ctypes expression as annotation-comparable text."""
    return ast.unparse(node).replace("ctypes.", "").replace(" ", "")


def parse_bindings(py_tree: ast.AST) -> "dict[str, Binding]":
    """Every ``<obj>.<symbol>.argtypes / .restype`` assignment."""
    bindings: "dict[str, Binding]" = {}
    for node in ast.walk(py_tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (
            isinstance(tgt, ast.Attribute)
            and tgt.attr in ("argtypes", "restype")
            and isinstance(tgt.value, ast.Attribute)
        ):
            continue
        sym = tgt.value.attr
        b = bindings.setdefault(sym, Binding(name=sym))
        if tgt.attr == "argtypes":
            b.argtypes_line = node.lineno
            if isinstance(node.value, (ast.List, ast.Tuple)):
                b.argtypes = [_canon(e) for e in node.value.elts]
        else:
            b.restype_line = node.lineno
            b.restype = _canon(node.value)
    return bindings


# ---------------------------------------------------------------------------
# MTPU404 / MTPU405: buffer/length data-flow over the caller functions
# ---------------------------------------------------------------------------

_SANITIZERS = ("ascontiguousarray", "require")


def _is_data_as(node: ast.AST) -> "ast.AST | None":
    """The buffer expression X for an ``X.ctypes.data_as(...)`` call."""
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "data_as"
        and isinstance(node.func.value, ast.Attribute)
        and node.func.value.attr == "ctypes"
    ):
        return node.func.value.value
    return None


class _BufferFlow:
    """Per-function provenance walk behind MTPU404/405.

    Deliberately sequential (loop/branch bodies visited once, in order)
    — the FFI wrappers it audits are straight-line code, and a
    heuristic that over-approximates provenance only ever *misses* a
    mismatch, it cannot invent one.
    """

    def __init__(self, rel_path: str, findings: "list[Finding]"):
        self.rel = rel_path
        self.findings = findings
        # var -> the original array names its value derives from
        self.roots: "dict[str, set[str]]" = {}
        # var -> array names whose .shape its value derives from
        self.shape_src: "dict[str, set[str]]" = {}
        # parameter-rooted names with no contiguity evidence yet
        self.unsafe: "set[str]" = set()

    # -- provenance helpers --------------------------------------------

    def _name_roots(self, node: ast.AST) -> "set[str]":
        out: "set[str]" = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                out |= self.roots.get(n.id, {n.id})
        return out

    def _shape_roots(self, node: ast.AST) -> "set[str]":
        out: "set[str]" = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and n.attr == "shape":
                out |= self._name_roots(n.value)
            elif isinstance(n, ast.Name):
                out |= self.shape_src.get(n.id, set())
        return out

    def _is_sanitizer(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else ""
        )
        if fname in _SANITIZERS:
            return True
        return fname == "asarray" and any(
            kw.arg == "order" for kw in node.keywords
        )

    def _expr_unsafe(self, node: ast.AST) -> bool:
        """Does this value's contiguity trace back to a raw parameter?"""
        if isinstance(node, ast.Name):
            return node.id in self.unsafe
        if isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
            return self._expr_unsafe(node.value)
        if isinstance(node, ast.Call):
            if self._is_sanitizer(node):
                return False
            # a method call inherits its receiver's safety (x.reshape
            # of a raw parameter can be non-contiguous); plain calls
            # (np.empty, helper functions) allocate fresh arrays
            if isinstance(node.func, ast.Attribute):
                return self._expr_unsafe(node.func.value)
            return False
        return any(self._expr_unsafe(c) for c in ast.iter_child_nodes(node))

    # -- statement walk ------------------------------------------------

    def run(self, fn: ast.FunctionDef) -> None:
        args = fn.args
        params = [
            a.arg
            for a in (
                list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
            )
        ]
        self.roots = {p: {p} for p in params}
        self.shape_src = {}
        self.unsafe = set(params)
        self._body(fn.body)

    def _assign(self, targets: "list[ast.AST]", value: ast.AST) -> None:
        roots = self._name_roots(value)
        shape = self._shape_roots(value)
        unsafe = self._expr_unsafe(value) and not self._is_sanitizer(value)
        names: "list[str]" = []
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    names.append(n.id)
        for name in names:
            self.roots[name] = roots
            self.shape_src[name] = shape
            if unsafe:
                self.unsafe.add(name)
            else:
                self.unsafe.discard(name)

    def _handle_assert(self, node: ast.Assert) -> None:
        for n in ast.walk(node.test):
            attr = None
            if isinstance(n, ast.Attribute) and n.attr in (
                "c_contiguous",
                "contiguous",
            ):
                attr = n.value
            elif isinstance(n, ast.Subscript):
                attr = n.value
            if (
                isinstance(attr, ast.Attribute)
                and attr.attr == "flags"
                and isinstance(attr.value, ast.Name)
            ):
                self.unsafe.discard(attr.value.id)

    def _check_calls(self, node: "ast.AST | None") -> None:
        if node is None:
            return
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            base = _is_data_as(call)
            if base is not None:
                if self._expr_unsafe(base):
                    self.findings.append(
                        Finding(
                            "MTPU405",
                            self.rel,
                            call.lineno,
                            f"buffer {ast.unparse(base)} reaches "
                            ".ctypes.data_as() with no contiguity "
                            "evidence (np.ascontiguousarray / "
                            "np.require / .flags.c_contiguous assert)",
                        )
                    )
                continue
            ptr_bases = [
                b for b in (_is_data_as(a) for a in call.args) if b is not None
            ]
            if not ptr_bases:
                continue
            ptr_roots: "set[str]" = set()
            for b in ptr_bases:
                ptr_roots |= self._name_roots(b)
            for i, arg in enumerate(call.args):
                if _is_data_as(arg) is not None:
                    continue
                sroots = self._shape_roots(arg)
                if sroots and sroots.isdisjoint(ptr_roots):
                    self.findings.append(
                        Finding(
                            "MTPU404",
                            self.rel,
                            call.lineno,
                            f"length argument #{i + 1} "
                            f"({ast.unparse(arg)}) derives from "
                            f"{sorted(sroots)} but the buffer pointers "
                            f"come from {sorted(ptr_roots)}",
                        )
                    )

    def _body(self, stmts: "list[ast.stmt]") -> None:
        for st in stmts:
            if isinstance(st, ast.Assign):
                self._check_calls(st.value)
                self._assign(st.targets, st.value)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                self._check_calls(st.value)
                self._assign([st.target], st.value)
            elif isinstance(st, ast.AugAssign):
                self._check_calls(st.value)
            elif isinstance(st, ast.Assert):
                self._handle_assert(st)
            elif isinstance(st, ast.For):
                self._check_calls(st.iter)
                self._assign([st.target], st.iter)
                # element iteration: a raw iterable yields raw elements
                if any(
                    isinstance(n, ast.Name) and n.id in self.unsafe
                    for n in ast.walk(st.iter)
                ):
                    for n in ast.walk(st.target):
                        if isinstance(n, ast.Name):
                            self.unsafe.add(n.id)
                self._body(st.body)
                self._body(st.orelse)
            elif isinstance(st, (ast.If, ast.While)):
                self._check_calls(st.test)
                self._body(st.body)
                self._body(st.orelse)
            elif isinstance(st, ast.With):
                self._body(st.body)
            elif isinstance(st, ast.Try):
                self._body(st.body)
                for h in st.handlers:
                    self._body(h.body)
                self._body(st.orelse)
                self._body(st.finalbody)
            elif isinstance(st, (ast.Return, ast.Expr)):
                self._check_calls(st.value)
            elif isinstance(st, ast.FunctionDef):
                _BufferFlow(self.rel, self.findings).run(st)
            else:
                self._check_calls(st)


# ---------------------------------------------------------------------------
# cross-checks + entry points
# ---------------------------------------------------------------------------


def _check_cross(
    exports: "dict[str, Export]",
    bindings: "dict[str, Binding]",
    py_rel: str,
    cc_rel: str,
    findings: "list[Finding]",
) -> None:
    for name, exp in sorted(exports.items()):
        b = bindings.get(name)
        if b is None:
            findings.append(
                Finding(
                    "MTPU403",
                    cc_rel,
                    exp.line,
                    f"exported symbol {name} has no ctypes binding in "
                    f"{py_rel}",
                )
            )
            continue
        if exp.annot_args is not None and len(exp.annot_args) != exp.c_arity:
            findings.append(
                Finding(
                    "MTPU401",
                    cc_rel,
                    exp.line,
                    f"@ctypes annotation for {name} declares "
                    f"{len(exp.annot_args)} argument(s) but the C "
                    f"signature has {exp.c_arity}",
                )
            )
        bound_arity = len(b.argtypes) if b.argtypes is not None else 0
        if bound_arity != exp.c_arity:
            findings.append(
                Finding(
                    "MTPU401",
                    py_rel,
                    b.anchor,
                    f"binding for {name} declares {bound_arity} "
                    f"argtypes but the export takes {exp.c_arity} "
                    "parameter(s)",
                )
            )
        elif exp.annot_args is not None and b.argtypes is not None:
            bad = [
                f"#{i + 1}: bound {got}, declared {want}"
                for i, (got, want) in enumerate(
                    zip(b.argtypes, exp.annot_args)
                )
                if got != want
            ]
            if bad:
                findings.append(
                    Finding(
                        "MTPU402",
                        py_rel,
                        b.argtypes_line,
                        f"argtypes drift for {name} vs its @ctypes "
                        f"annotation ({'; '.join(bad)})",
                    )
                )
        if exp.annot_restype is not None:
            got = b.restype if b.restype is not None else "c_int"
            if got != exp.annot_restype:
                findings.append(
                    Finding(
                        "MTPU402",
                        py_rel,
                        b.restype_line or b.anchor,
                        f"restype drift for {name}: bound {got}, "
                        f"declared {exp.annot_restype} (unset restype "
                        "defaults to c_int)",
                    )
                )
    for name, b in sorted(bindings.items()):
        if name not in exports:
            findings.append(
                Finding(
                    "MTPU403",
                    py_rel,
                    b.anchor,
                    f"ctypes binding for {name} has no exported symbol "
                    f"in {cc_rel}",
                )
            )


def analyze(
    py_text: str,
    py_rel: str,
    cc_text: "str | None" = None,
    cc_rel: "str | None" = None,
) -> "list[Finding]":
    """All MTPU4xx findings for one binding file (pre-noqa filtering).

    With ``cc_text`` the export cross-checks (MTPU401-403) run too;
    without it only the caller-side data-flow rules (MTPU404/405).
    """
    findings: "list[Finding]" = []
    try:
        tree = ast.parse(py_text)
    except SyntaxError as e:
        return [
            Finding(
                "MTPU401",
                py_rel,
                e.lineno or 1,
                f"binding file does not parse: {e.msg}",
            )
        ]
    if cc_text is not None:
        _check_cross(
            parse_exports(cc_text),
            parse_bindings(tree),
            py_rel,
            cc_rel or CC_REL,
            findings,
        )
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            _BufferFlow(py_rel, findings).run(node)
    return findings


def raw_run() -> "list[Finding]":
    """The real seam's findings BEFORE noqa filtering (MTPU106 input)."""
    from . import REPO_ROOT

    with open(os.path.join(REPO_ROOT, PY_REL), encoding="utf-8") as fh:
        py_text = fh.read()
    with open(os.path.join(REPO_ROOT, CC_REL), encoding="utf-8") as fh:
        cc_text = fh.read()
    return analyze(py_text, PY_REL, cc_text, CC_REL)


def run() -> "list[Finding]":
    """ABI pass over the real native seam, noqa-filtered."""
    from . import REPO_ROOT

    with open(os.path.join(REPO_ROOT, PY_REL), encoding="utf-8") as fh:
        py_lines = fh.read().splitlines()
    findings = raw_run()
    return filter_suppressed(findings, {PY_REL: py_lines})
