"""Kernel contract checker: abstract-eval every jitted codec entry point.

``jax.eval_shape`` runs the tracer without compiling or executing, so the
shape/dtype contracts of the TPU codec kernels — including the Pallas
ones — are checkable on any host, no accelerator required.  For each
jitted entry point in ``minio_tpu/ops/`` a registered contract states,
over a grid of (data_shards, parity_shards, shard_len) erasure configs:

* MTPU201 — output dtypes (words stay uint32, byte shards stay uint8,
  verify masks are bool);
* MTPU202 — output shard shapes (parity rows = m, digest width = 8, ...);
* MTPU203 — encode→reconstruct shape round-trips: encoding (k, L) data
  and reconstructing after dropping all parity-count-many shards must
  yield (k, L) back, in both the byte and the packed-word domain;
* MTPU204 — a jitted entry point with NO registered contract.  The
  registry is closed over module introspection, so adding a kernel
  without a contract fails the gate rather than silently shrinking
  coverage.

Findings anchor at the entry point's ``def`` line and name the offending
config, e.g. ``(data_shards=8, parity_shards=4, shard_len=256)``.
"""

from __future__ import annotations

import os

from .findings import Finding

# (data_shards, parity_shards, shard_len_bytes); shard_len % 32 == 0
# (words-per-shard multiple of 8, the encode_and_hash_words floor).
CONFIG_GRID = [
    (2, 1, 64),
    (4, 2, 128),
    (8, 4, 256),
    (16, 4, 512),
]

# encode_hash_fused tiles at rs_pallas._TW uint32 words (16 KiB shards);
# keep this grid small — abstract eval of the Pallas kernel still traces
# the full XOR chain.
FUSED_GRID = [
    (2, 1, 16384),
    (4, 2, 16384),
    (8, 4, 16384),
]

_BATCH = 3  # leading batch dim for the batched kernels

# ---------------------------------------------------------------------------
# Device-dataflow registry (consumed by analysis/callgraph.py and
# analysis/deviceflow.py).
#
# These tables are the single source of truth for the whole-program
# MTPU5xx dataflow rules: which calls *produce* device-resident values,
# which argument positions are *donated* (dead after the call), and
# which functions are the sanctioned *drain seams* where device values
# may legally materialize on host.  MTPU505 cross-checks every table
# against the tree (a declared fact absent in code, or a code fact
# absent here, is a finding), so the registry cannot rot — the same
# discipline MTPU403 applies to the native export table.
# ---------------------------------------------------------------------------

# short module name -> repo-relative path of the module that defines it
ENTRY_POINT_PATHS = {
    "rs": "minio_tpu/ops/rs.py",
    "rs_pallas": "minio_tpu/ops/rs_pallas.py",
    "codec_step": "minio_tpu/ops/codec_step.py",
    "hash": "minio_tpu/ops/hash.py",
    "select_step": "minio_tpu/ops/select_step.py",
    "backend": "minio_tpu/codec/backend.py",
    "mesh": "minio_tpu/parallel/mesh.py",
    "rules": "minio_tpu/parallel/rules.py",
}

# Every jitted entry point the tree ships, (module_short_name, attr).
# Introspection (jit_entry_points) must find at least these — tier-1
# asserts it — and the callgraph pass must resolve a def node for each.
# Calls to any of these return device-resident values.
KNOWN_ENTRY_POINTS = {
    ("rs", "_encode_jit"),
    ("rs", "_reconstruct_jit"),
    ("rs", "_reconstruct_static_jit"),
    ("rs_pallas", "_matmul_words_jit"),
    ("rs_pallas", "_mxu_matmul_jit"),
    ("rs_pallas", "encode_hash_fused"),
    ("rs_pallas", "encode_pack_fused"),
    ("rs_pallas", "verify_reconstruct_fused"),
    ("rs_pallas", "encode_pack_pipelined"),
    ("rs_pallas", "verify_reconstruct_pipelined"),
    ("codec_step", "encode_and_hash_words"),
    ("codec_step", "encode_and_hash_words_digest"),
    ("codec_step", "encode_words_fused1"),
    ("codec_step", "verify_and_reconstruct_words"),
    ("codec_step", "encode_subchunk_words"),
    ("codec_step", "verify_reconstruct_subchunk_words"),
    ("codec_step", "group_flags"),
    ("codec_step", "pack_nonzero_groups"),
    ("codec_step", "verify_hashes_words"),
    ("codec_step", "reconstruct_words_batch"),
    ("codec_step", "encode_throughput_probe"),
    ("codec_step", "reconstruct_throughput_probe"),
    ("codec_step", "verify_throughput_probe"),
    ("select_step", "screen_chunk"),
    ("select_step", "extract_positions"),
    ("select_step", "row_spans"),
    ("select_step", "anchors_back"),
    ("select_step", "gather_rows"),
}

# (module_short_name, attr) -> donated positional argument indices.
# A value passed at a donated position is DEAD after the call (XLA may
# alias its buffer into an output); reading it again is the PR 14 bug
# class, caught statically as MTPU501.  MTPU505 cross-checks this table
# against the ``donate_argnums`` literals in the jit decorators.
DONATING_ENTRY_POINTS = {
    ("codec_step", "encode_and_hash_words_digest"): (0,),
    ("codec_step", "encode_words_fused1"): (0,),
    # the async overlap sub-chunk chain donates BOTH the staging chunk
    # (dies into the parity allocation) and the ping-pong hash
    # accumulator (threads through the chunk chain)
    ("codec_step", "encode_subchunk_words"): (0, 1),
    ("codec_step", "verify_reconstruct_subchunk_words"): (0, 1),
}

# Mesh kernel kinds registered with the rules.py compile seam that
# declare donation (register_kernel(..., donate_argnums=...)).  MTPU505
# cross-checks against the register_kernel call sites in the tree.
MESH_DONATING_KERNELS = {
    "mesh_encode_hash": (0,),
}

# repo-relative path -> function names that are sanctioned drain seams:
# inside these, device values may materialize on host (np.asarray /
# bytes / .item() / jax.device_get), and their RETURN values are host
# facts, not device facts.  Names ending in ``_end`` or containing
# ``drain`` in these files MUST be registered here (MTPU505), so a new
# seam cannot appear without joining the audited set.
DRAIN_SEAMS = {
    "minio_tpu/codec/backend.py": (
        # PUT side: the begin/end split and the lazy parity-plane drain
        "encode_end",
        "encode_digest_end",
        "drain",
        "_drain_d2h",
        "_drain_precomputed",
        # GET side: decode IS the sanctioned D2H — reconstructed rows
        # leave the device here and nowhere else
        "reconstruct",
        "reconstruct_and_verify",
        "verify",
        "digest",
        # sub-chunk overlap pipeline (MINIO_TPU_CODEC_OVERLAP=async):
        # the chunked parity-plane drain and the GET-side chain that
        # drains chunk s D2H while chunk s+1 computes
        "_drain_chunks",
        "_drain_vr_subchunks",
    ),
    "minio_tpu/s3select/device.py": (
        # candidate row bytes are the only payload that crosses D2H,
        # through exactly these functions (MTPU111 enforces locally)
        "_drain_scalars",
        "_drain_array",
        "_drain_fallback_chunk",
        "drain_plane",
    ),
    "minio_tpu/ops/codec_step.py": (
        # byte-domain convenience wrappers: eager by design (tests and
        # small host-side callers), documented in the module
        "encode_and_hash",
        "verify_hashes",
        "decode_and_verify",
    ),
    "minio_tpu/parallel/mesh.py": (
        # the mesh pipeline's sync point: begin dispatches async,
        # _end materializes — the double-buffer overlap contract
        "mesh_encode_hash_end",
    ),
}


def _ops_modules():
    # codec.backend is watched too: the PR 4 fused-codec seams
    # (encode_and_hash / reconstruct_and_verify) route through backend
    # objects, and a jitted wrapper landing there without a contract
    # must fail MTPU204 the same as one in ops/.  parallel.mesh/rules
    # register their kernels with the compile seam instead of module
    # attrs; watching them here catches a stray module-level jit, and
    # the seam registry gets its own MTPU204 closure in run().
    from minio_tpu.codec import backend
    from minio_tpu.ops import (
        codec_step,
        hash as phash,
        rs,
        rs_pallas,
        select_step,
    )
    from minio_tpu.parallel import mesh, rules

    return {
        "rs": rs,
        "rs_pallas": rs_pallas,
        "codec_step": codec_step,
        "hash": phash,
        "select_step": select_step,
        "backend": backend,
        "mesh": mesh,
        "rules": rules,
    }


def is_jitted(obj) -> bool:
    """True for jax.jit-wrapped callables (PjitFunction and kin)."""
    return (
        callable(obj)
        and hasattr(obj, "eval_shape")
        and hasattr(obj, "lower")
        and hasattr(obj, "__wrapped__")
    )


def jit_entry_points() -> "dict[tuple[str, str], object]":
    """(module_short_name, attr_name) -> jitted callable, by introspection.

    This is the ground truth the MTPU204 coverage check (and the tier-1
    introspection test) compare the contract registry against.
    """
    out = {}
    for mod_name, mod in _ops_modules().items():
        for attr, val in sorted(vars(mod).items()):
            if is_jitted(val):
                out[(mod_name, attr)] = val
    return out


def _anchor(fn, default_path: str) -> "tuple[str, int]":
    """Repo-relative path + def line of a jitted callable."""
    code = getattr(getattr(fn, "__wrapped__", fn), "__code__", None)
    if code is None:
        return default_path, 1
    path = code.co_filename
    marker = os.sep + "minio_tpu" + os.sep
    if marker in path:
        path = "minio_tpu" + os.sep + path.split(marker, 1)[1]
    return path.replace(os.sep, "/"), code.co_firstlineno


class _ContractContext:
    """Collects findings for one entry point, tagging the config."""

    def __init__(self, findings, fn, default_path):
        self.findings = findings
        self.path, self.line = _anchor(fn, default_path)
        self.config = ""

    def expect(self, rule: str, got, want, what: str) -> None:
        if got != want:
            self.findings.append(
                Finding(
                    rule,
                    self.path,
                    self.line,
                    f"{what}: got {got}, want {want} at {self.config}",
                )
            )

    def shape(self, got, want, what: str) -> None:
        self.expect("MTPU202", tuple(got.shape), tuple(want), what + " shape")

    def dtype(self, got, want, what: str) -> None:
        self.expect("MTPU201", str(got.dtype), str(want), what + " dtype")

    def fail(self, exc: BaseException) -> None:
        self.findings.append(
            Finding(
                "MTPU202",
                self.path,
                self.line,
                f"abstract eval raised {type(exc).__name__}: {exc} "
                f"at {self.config}",
            )
        )


def run() -> "list[Finding]":
    """Check every registered contract; returns findings (empty = green)."""
    import jax
    import jax.numpy as jnp

    from minio_tpu.ops import codec_step, gf, rs, rs_pallas

    findings: "list[Finding]" = []
    S = jax.ShapeDtypeStruct
    u8, u32 = jnp.uint8, jnp.uint32
    reps = S((), jnp.int32)  # dynamic trip count of the bench probes

    def ctx(fn, default_path):
        return _ContractContext(findings, fn, default_path)

    def cfg_str(k, m, L):
        return f"(data_shards={k}, parity_shards={m}, shard_len={L})"

    checked: "set[tuple[str, str]]" = set()

    def covers(mod, name):
        checked.add((mod, name))

    # ---- rs.py ----------------------------------------------------------

    covers("rs", "_encode_jit")
    c = ctx(rs._encode_jit, "minio_tpu/ops/rs.py")
    for k, m, L in CONFIG_GRID:
        c.config = cfg_str(k, m, L)
        try:
            out = rs._encode_jit.eval_shape(S((k, L), u8), k, m)
            c.shape(out, (m, L), "parity")
            c.dtype(out, "uint8", "parity")
        except Exception as e:  # pragma: no cover - defensive
            c.fail(e)

    covers("rs", "_reconstruct_jit")
    c = ctx(rs._reconstruct_jit, "minio_tpu/ops/rs.py")
    for k, m, L in CONFIG_GRID:
        n = k + m
        c.config = cfg_str(k, m, L)
        try:
            out = rs._reconstruct_jit.eval_shape(
                S((n, L), u8), S((n,), u8), S((k, k), u8), k, m, True
            )
            c.shape(out, (n, L), "rebuilt (want_parity)")
            c.dtype(out, "uint8", "rebuilt")
            out = rs._reconstruct_jit.eval_shape(
                S((n, L), u8), S((n,), u8), S((k, k), u8), k, m, False
            )
            c.shape(out, (k, L), "rebuilt (data only)")
        except Exception as e:
            c.fail(e)

    covers("rs", "_reconstruct_static_jit")
    c = ctx(rs._reconstruct_static_jit, "minio_tpu/ops/rs.py")
    for k, m, L in CONFIG_GRID:
        n = k + m
        # worst admissible erasure: all m losses fall on data shards
        present = (False,) * m + (True,) * (n - m)
        c.config = cfg_str(k, m, L)
        try:
            out = rs._reconstruct_static_jit.eval_shape(
                S((n, L), u8), present, k, m, True
            )
            c.shape(out, (n, L), "rebuilt (want_parity)")
            c.dtype(out, "uint8", "rebuilt")
            # MTPU203: encode -> reconstruct round-trip in the byte domain
            parity = rs._encode_jit.eval_shape(S((k, L), u8), k, m)
            data_only = rs._reconstruct_static_jit.eval_shape(
                S((k + parity.shape[0], L), parity.dtype),
                present,
                k,
                m,
                False,
            )
            c.expect(
                "MTPU203",
                (tuple(data_only.shape), str(data_only.dtype)),
                ((k, L), "uint8"),
                "encode->reconstruct round-trip (bytes)",
            )
        except Exception as e:
            c.fail(e)

    # ---- codec_step.py --------------------------------------------------

    covers("codec_step", "encode_and_hash_words")
    c = ctx(codec_step.encode_and_hash_words, "minio_tpu/ops/codec_step.py")
    for k, m, L in CONFIG_GRID:
        w, n = L // 4, k + m
        c.config = cfg_str(k, m, L)
        try:
            parity, digests = codec_step.encode_and_hash_words.eval_shape(
                S((_BATCH, k, w), u32), m, L
            )
            c.shape(parity, (_BATCH, m, w), "parity")
            c.dtype(parity, "uint32", "parity")
            c.shape(digests, (_BATCH, n, 8), "digests")
            c.dtype(digests, "uint32", "digests")
        except Exception as e:
            c.fail(e)

    covers("codec_step", "encode_and_hash_words_digest")
    c = ctx(
        codec_step.encode_and_hash_words_digest,
        "minio_tpu/ops/codec_step.py",
    )
    for k, m, L in CONFIG_GRID:
        w, n = L // 4, k + m
        c.config = cfg_str(k, m, L)
        try:
            # identical contract to encode_and_hash_words: the digest
            # variant only changes buffer lifetime (donated input,
            # device-resident parity), never shapes or dtypes
            parity, digests = (
                codec_step.encode_and_hash_words_digest.eval_shape(
                    S((_BATCH, k, w), u32), m, L
                )
            )
            c.shape(parity, (_BATCH, m, w), "device-resident parity")
            c.dtype(parity, "uint32", "device-resident parity")
            c.shape(digests, (_BATCH, n, 8), "digests")
            c.dtype(digests, "uint32", "digests")
        except Exception as e:
            c.fail(e)

    # parity transport compression: group granularity must divide the
    # words-per-shard of every grid config (smallest is 64B -> 16 words)
    _GROUP = 8

    covers("codec_step", "group_flags")
    c = ctx(codec_step.group_flags, "minio_tpu/ops/codec_step.py")
    for k, m, L in CONFIG_GRID:
        w, g = L // 4, L // 4 // _GROUP
        c.config = cfg_str(k, m, L)
        try:
            flags = codec_step.group_flags.eval_shape(
                S((_BATCH, m, w), u32), _GROUP
            )
            c.shape(flags, (_BATCH, m, g), "group flags")
            c.dtype(flags, "bool", "group flags")
        except Exception as e:
            c.fail(e)

    covers("codec_step", "pack_nonzero_groups")
    c = ctx(codec_step.pack_nonzero_groups, "minio_tpu/ops/codec_step.py")
    for k, m, L in CONFIG_GRID:
        w, g = L // 4, L // 4 // _GROUP
        c.config = cfg_str(k, m, L)
        try:
            flags, packed = codec_step.pack_nonzero_groups.eval_shape(
                S((_BATCH, m, w), u32), _GROUP
            )
            c.shape(flags, (_BATCH, m, g), "pack flags")
            c.dtype(flags, "bool", "pack flags")
            c.shape(packed, (_BATCH, m, w), "packed words")
            c.dtype(packed, "uint32", "packed words")
        except Exception as e:
            c.fail(e)

    covers("codec_step", "verify_hashes_words")
    c = ctx(codec_step.verify_hashes_words, "minio_tpu/ops/codec_step.py")
    for k, m, L in CONFIG_GRID:
        w, n = L // 4, k + m
        c.config = cfg_str(k, m, L)
        try:
            ok = codec_step.verify_hashes_words.eval_shape(
                S((_BATCH, n, w), u32), S((_BATCH, n, 8), u32), L
            )
            c.shape(ok, (_BATCH, n), "ok mask")
            c.dtype(ok, "bool", "ok mask")
        except Exception as e:
            c.fail(e)

    covers("codec_step", "reconstruct_words_batch")
    c = ctx(codec_step.reconstruct_words_batch, "minio_tpu/ops/codec_step.py")
    for k, m, L in CONFIG_GRID:
        w, n = L // 4, k + m
        present = (False,) * m + (True,) * (n - m)
        c.config = cfg_str(k, m, L)
        try:
            dw = codec_step.reconstruct_words_batch.eval_shape(
                S((_BATCH, n, w), u32), present, k, m
            )
            c.shape(dw, (_BATCH, k, w), "data words")
            c.dtype(dw, "uint32", "data words")
            # MTPU203: word-domain round-trip — encode a batch, drop m
            # shards, reconstruct; shapes must close.
            parity, _ = codec_step.encode_and_hash_words.eval_shape(
                S((_BATCH, k, w), u32), m, L
            )
            rt = codec_step.reconstruct_words_batch.eval_shape(
                S((_BATCH, k + parity.shape[1], w), parity.dtype),
                present,
                k,
                m,
            )
            c.expect(
                "MTPU203",
                (tuple(rt.shape), str(rt.dtype)),
                ((_BATCH, k, w), "uint32"),
                "encode->reconstruct round-trip (words)",
            )
        except Exception as e:
            c.fail(e)

    for name in (
        "encode_throughput_probe",
        "reconstruct_throughput_probe",
        "verify_throughput_probe",
    ):
        covers("codec_step", name)
    for k, m, L in CONFIG_GRID:
        w, n = L // 4, k + m
        present = (False,) * m + (True,) * (n - m)
        probes = [
            (
                codec_step.encode_throughput_probe,
                (S((_BATCH, k, w), u32), m, L, reps),
            ),
            (
                codec_step.reconstruct_throughput_probe,
                (S((_BATCH, n, w), u32), present, k, m, reps),
            ),
            (
                codec_step.verify_throughput_probe,
                (S((_BATCH, n, w), u32), S((_BATCH, n, 8), u32), L, reps),
            ),
        ]
        for fn, args in probes:
            c = ctx(fn, "minio_tpu/ops/codec_step.py")
            c.config = cfg_str(k, m, L)
            try:
                sample, acc = fn.eval_shape(*args)
                c.shape(sample, (8,), "probe checksum sample")
                c.dtype(sample, "uint32", "probe checksum sample")
                c.shape(acc, (), "probe accumulator")
                c.dtype(acc, "uint32", "probe accumulator")
            except Exception as e:
                c.fail(e)

    # ---- codec_step.py: one-kernel codec (fused1) -----------------------
    #
    # The fused1 entries subsume three legacy passes (encode+digest,
    # group_flags, pack_nonzero_groups) resp. two (verify, reconstruct).
    # Portable formulation is checked over CONFIG_GRID; the Pallas path
    # over FUSED_GRID in interpret mode, both formulations, so contract
    # coverage matches everything the dispatcher can launch.

    covers("codec_step", "encode_words_fused1")
    c = ctx(codec_step.encode_words_fused1, "minio_tpu/ops/codec_step.py")
    for k, m, L in CONFIG_GRID:
        w, n = L // 4, k + m
        for group in (0, _GROUP):
            g = w // group if group else 0
            c.config = cfg_str(k, m, L) + f" [portable, group={group}]"
            try:
                parity, digests, flags, packed = (
                    codec_step.encode_words_fused1.eval_shape(
                        S((_BATCH, k, w), u32), m, L, group
                    )
                )
                c.shape(parity, (_BATCH, m, w), "fused1 parity")
                c.dtype(parity, "uint32", "fused1 parity")
                c.shape(digests, (_BATCH, n, 8), "fused1 digests")
                c.dtype(digests, "uint32", "fused1 digests")
                c.shape(flags, (_BATCH, m, g), "fused1 flags")
                c.dtype(flags, "bool", "fused1 flags")
                c.shape(packed, (_BATCH, m, w), "fused1 packed")
                c.dtype(packed, "uint32", "fused1 packed")
            except Exception as e:
                c.fail(e)
    for k, m, L in FUSED_GRID:
        w, n = L // 4, k + m
        group = 256  # compress.PARITY_GROUP_WORDS, the production granule
        for formulation in ("swar", "mxu"):
            c.config = cfg_str(k, m, L) + f" [pallas, {formulation}]"
            try:
                parity, digests, flags, packed = (
                    codec_step.encode_words_fused1.eval_shape(
                        S((_BATCH, k, w), u32), m, L, group,
                        formulation, True, True,
                    )
                )
                c.shape(parity, (_BATCH, m, w), "fused1 parity")
                c.dtype(parity, "uint32", "fused1 parity")
                c.shape(digests, (_BATCH, n, 8), "fused1 digests")
                c.dtype(digests, "uint32", "fused1 digests")
                c.shape(flags, (_BATCH, m, w // group), "fused1 flags")
                c.dtype(flags, "bool", "fused1 flags")
                c.shape(packed, (_BATCH, m, w), "fused1 packed")
                c.dtype(packed, "uint32", "fused1 packed")
            except Exception as e:
                c.fail(e)

    covers("codec_step", "verify_and_reconstruct_words")
    c = ctx(
        codec_step.verify_and_reconstruct_words,
        "minio_tpu/ops/codec_step.py",
    )
    for k, m, L in CONFIG_GRID:
        w, n = L // 4, k + m
        present = (False,) * m + (True,) * (n - m)
        c.config = cfg_str(k, m, L) + " [portable]"
        try:
            data, ok = codec_step.verify_and_reconstruct_words.eval_shape(
                S((_BATCH, n, w), u32), S((_BATCH, n, 8), u32),
                present, k, m, L,
            )
            c.shape(data, (_BATCH, k, w), "fused GET data words")
            c.dtype(data, "uint32", "fused GET data words")
            c.shape(ok, (_BATCH, n), "fused GET ok mask")
            c.dtype(ok, "bool", "fused GET ok mask")
            # MTPU203: fused1 encode -> fused1 verify+reconstruct closes
            parity, digests, _, _ = (
                codec_step.encode_words_fused1.eval_shape(
                    S((_BATCH, k, w), u32), m, L, 0
                )
            )
            rt, _ = codec_step.verify_and_reconstruct_words.eval_shape(
                S((_BATCH, k + parity.shape[1], w), parity.dtype),
                S(tuple(digests.shape), digests.dtype),
                present, k, m, L,
            )
            c.expect(
                "MTPU203",
                (tuple(rt.shape), str(rt.dtype)),
                ((_BATCH, k, w), "uint32"),
                "fused1 encode->verify+reconstruct round-trip (words)",
            )
        except Exception as e:
            c.fail(e)
    for k, m, L in FUSED_GRID:
        w, n = L // 4, k + m
        present = (False,) * m + (True,) * (n - m)
        for formulation in ("swar", "mxu"):
            c.config = cfg_str(k, m, L) + f" [pallas, {formulation}]"
            try:
                data, ok = (
                    codec_step.verify_and_reconstruct_words.eval_shape(
                        S((_BATCH, n, w), u32), S((_BATCH, n, 8), u32),
                        present, k, m, L, formulation, True, True,
                    )
                )
                c.shape(data, (_BATCH, k, w), "fused GET data words")
                c.dtype(data, "uint32", "fused GET data words")
                c.shape(ok, (_BATCH, n), "fused GET ok mask")
                c.dtype(ok, "bool", "fused GET ok mask")
            except Exception as e:
                c.fail(e)

    # ---- codec_step.py: async-overlap sub-chunk twins -------------------
    #
    # The MINIO_TPU_CODEC_OVERLAP=async chain: per-chunk parity/verify
    # passes threading a donated (B, n, 8) hash-partial accumulator.
    # Contracts run each entry as a mid-chain link (finalize=False) and
    # as the chain tail (finalize=True) — shapes must agree so the
    # backend's ping-pong reassignment stays well-typed, and the chunk
    # width grid includes a NON-dividing width (the ragged tail chunk
    # compiles as its own program).

    covers("codec_step", "encode_subchunk_words")
    c = ctx(codec_step.encode_subchunk_words, "minio_tpu/ops/codec_step.py")
    for k, m, L in CONFIG_GRID:
        w, n = L // 4, k + m
        for cw in (w, w // 2 if w // 2 % 8 == 0 else w, 8):
            for group in (0, _GROUP):
                if group and cw % group:
                    continue
                gc = cw // group if group else 0
                for fin in (False, True):
                    c.config = (
                        cfg_str(k, m, L)
                        + f" [cw={cw}, group={group}, finalize={fin}]"
                    )
                    try:
                        parity, acc, flags, packed = (
                            codec_step.encode_subchunk_words.eval_shape(
                                S((_BATCH, k, cw), u32),
                                S((_BATCH, n, 8), u32),
                                S((), u32),
                                m, L, group, fin,
                            )
                        )
                        c.shape(parity, (_BATCH, m, cw), "chunk parity")
                        c.dtype(parity, "uint32", "chunk parity")
                        c.shape(acc, (_BATCH, n, 8), "chunk partials")
                        c.dtype(acc, "uint32", "chunk partials")
                        c.shape(flags, (_BATCH, m, gc), "chunk flags")
                        c.dtype(flags, "bool", "chunk flags")
                        c.shape(packed, (_BATCH, m, cw), "chunk packed")
                        c.dtype(packed, "uint32", "chunk packed")
                    except Exception as e:
                        c.fail(e)

    covers("codec_step", "verify_reconstruct_subchunk_words")
    c = ctx(
        codec_step.verify_reconstruct_subchunk_words,
        "minio_tpu/ops/codec_step.py",
    )
    for k, m, L in CONFIG_GRID:
        w, n = L // 4, k + m
        present = (False,) * m + (True,) * (n - m)
        for cw in (w, 8):
            for fin in (False, True):
                c.config = cfg_str(k, m, L) + f" [cw={cw}, finalize={fin}]"
                try:
                    data, acc, ok = (
                        codec_step
                        .verify_reconstruct_subchunk_words.eval_shape(
                            S((_BATCH, n, cw), u32),
                            S((_BATCH, n, 8), u32),
                            S((_BATCH, n, 8), u32),
                            S((), u32),
                            present, k, m, L, fin,
                        )
                    )
                    c.shape(data, (_BATCH, k, cw), "chunk data words")
                    c.dtype(data, "uint32", "chunk data words")
                    c.shape(acc, (_BATCH, n, 8), "chunk partials")
                    c.dtype(acc, "uint32", "chunk partials")
                    c.shape(ok, (_BATCH, n), "chunk ok mask")
                    c.dtype(ok, "bool", "chunk ok mask")
                except Exception as e:
                    c.fail(e)

    # ---- select_step.py: S3 Select scan kernels -------------------------
    #
    # SWAR flag-words are uint64, so every contract evaluates under
    # enable_x64 exactly like the runtime call sites (the flag is part
    # of the jit cache key).  The plane grid is tiny — shapes close over
    # N the same way at 64 MiB as at 4 KiB.

    from jax.experimental import enable_x64

    from minio_tpu.ops import select_step

    u8_ = jnp.uint8
    _SELECT_PLANES = (4096, 16384)  # bytes; multiples of BLOCK_BYTES
    # one branch per screen-atom kind, so the contract traces every
    # _atom_mask arm the compiler can emit
    _SELECT_ATOMS = (
        (("len", 0, 3),),
        (("deep", 2),),
        (("byte0", 43, 48),),
        (("nd", 4),),
        (("lex", b"42", "lt"),),
        (("lex", b"42", "ge"),),
        (("lex", b"name", "eq"),),
    )

    def sel_cfg(n, extra=""):
        return f"(plane_bytes={n}{extra})"

    with enable_x64():
        u64 = jnp.uint64
        wpb = select_step.POP_WORDS  # words per popcount block

        covers("select_step", "screen_chunk")
        c = ctx(select_step.screen_chunk, "minio_tpu/ops/select_step.py")
        for n in _SELECT_PLANES:
            for anchor in ("row", "field"):
                for sci in (False, True):
                    c.config = sel_cfg(
                        n, f", anchor={anchor}, sci_guard={sci}"
                    )
                    try:
                        cand, blk, nrows, haz = (
                            select_step.screen_chunk.eval_shape(
                                S((n,), u8_),
                                fd=44,
                                qc=34,
                                atoms=_SELECT_ATOMS,
                                anchor=anchor,
                                sci_guard=sci,
                            )
                        )
                        c.shape(cand, (n // 8,), "candidate flag words")
                        c.dtype(cand, "uint64", "candidate flag words")
                        c.shape(
                            blk, (n // (8 * wpb),), "block popcounts"
                        )
                        c.dtype(blk, "int32", "block popcounts")
                        c.shape(nrows, (), "row count")
                        c.dtype(nrows, "int32", "row count")
                        c.shape(haz, (), "hazard scalar")
                        c.dtype(haz, "bool", "hazard scalar")
                    except Exception as e:
                        c.fail(e)

        covers("select_step", "extract_positions")
        c = ctx(
            select_step.extract_positions, "minio_tpu/ops/select_step.py"
        )
        for n in _SELECT_PLANES:
            for cap in (64, 1024):
                c.config = sel_cfg(n, f", cap={cap}")
                try:
                    pos = select_step.extract_positions.eval_shape(
                        S((n // 8,), u64),
                        S((n // (8 * wpb),), jnp.int32),
                        cap=cap,
                    )
                    c.shape(pos, (cap,), "candidate byte positions")
                    c.dtype(pos, "int32", "candidate byte positions")
                except Exception as e:
                    c.fail(e)

        _C = 7  # candidate count for the windowed kernels

        covers("select_step", "row_spans")
        c = ctx(select_step.row_spans, "minio_tpu/ops/select_step.py")
        for n in _SELECT_PLANES:
            for window in (256, 4096):
                c.config = sel_cfg(n, f", window={window}")
                try:
                    lens, found = select_step.row_spans.eval_shape(
                        S((n,), u8_), S((_C,), jnp.int32), window=window
                    )
                    c.shape(lens, (_C,), "row lengths")
                    c.dtype(lens, "int32", "row lengths")
                    c.shape(found, (_C,), "row-end found mask")
                    c.dtype(found, "bool", "row-end found mask")
                except Exception as e:
                    c.fail(e)

        covers("select_step", "anchors_back")
        c = ctx(select_step.anchors_back, "minio_tpu/ops/select_step.py")
        for n in _SELECT_PLANES:
            for window in (256, 1024):
                c.config = sel_cfg(n, f", window={window}")
                try:
                    anch, found = select_step.anchors_back.eval_shape(
                        S((n,), u8_), S((_C,), jnp.int32), window=window
                    )
                    c.shape(anch, (_C,), "row anchors")
                    c.dtype(anch, "int32", "row anchors")
                    c.shape(found, (_C,), "anchor found mask")
                    c.dtype(found, "bool", "anchor found mask")
                except Exception as e:
                    c.fail(e)

        covers("select_step", "gather_rows")
        c = ctx(select_step.gather_rows, "minio_tpu/ops/select_step.py")
        for n in _SELECT_PLANES:
            for window in (64, 1024):
                c.config = sel_cfg(n, f", window={window}")
                try:
                    mat = select_step.gather_rows.eval_shape(
                        S((n,), u8_), S((_C,), jnp.int32), window=window
                    )
                    c.shape(mat, (_C, window), "gathered row matrix")
                    c.dtype(mat, "uint8", "gathered row matrix")
                except Exception as e:
                    c.fail(e)

        # sanity: the padding granularity must be whole popcount
        # blocks, or screen_chunk's reshape would fail on a padded
        # plane (512 bytes / (8 words * 8 bytes) today)
        assert select_step.BLOCK_BYTES % (wpb * 8) == 0
        assert all(n % select_step.BLOCK_BYTES == 0
                   for n in _SELECT_PLANES)

    # ---- rs_pallas.py ---------------------------------------------------

    covers("rs_pallas", "_matmul_words_jit")
    c = ctx(rs_pallas._matmul_words_jit, "minio_tpu/ops/rs_pallas.py")
    for k, m, L in CONFIG_GRID:
        w = L // 4
        key = gf.parity_matrix(k, m).tobytes()
        c.config = cfg_str(k, m, L)
        try:
            out = rs_pallas._matmul_words_jit.eval_shape(
                S((k, w), u32), key, m, k, True
            )
            c.shape(out, (m, w), "pallas parity words")
            c.dtype(out, "uint32", "pallas parity words")
        except Exception as e:
            c.fail(e)

    covers("rs_pallas", "encode_hash_fused")
    c = ctx(rs_pallas.encode_hash_fused, "minio_tpu/ops/rs_pallas.py")
    for k, m, L in FUSED_GRID:
        w, n = L // 4, k + m
        c.config = cfg_str(k, m, L)
        try:
            parity, hacc = rs_pallas.encode_hash_fused.eval_shape(
                S((_BATCH, k, w), u32), m, True
            )
            c.shape(parity, (_BATCH, m, w), "fused parity")
            c.dtype(parity, "uint32", "fused parity")
            c.shape(hacc, (_BATCH, n, 8), "fused hash partials")
            c.dtype(hacc, "uint32", "fused hash partials")
        except Exception as e:
            c.fail(e)

    covers("rs_pallas", "_mxu_matmul_jit")
    c = ctx(rs_pallas._mxu_matmul_jit, "minio_tpu/ops/rs_pallas.py")
    for k, m, L in CONFIG_GRID:
        key = gf.parity_matrix(k, m).tobytes()
        c.config = cfg_str(k, m, L)
        try:
            out = rs_pallas._mxu_matmul_jit.eval_shape(
                S((k, L), u8), key, m, k, True
            )
            c.shape(out, (m, L), "mxu parity bytes")
            c.dtype(out, "uint8", "mxu parity bytes")
        except Exception as e:
            c.fail(e)

    covers("rs_pallas", "encode_pack_fused")
    c = ctx(rs_pallas.encode_pack_fused, "minio_tpu/ops/rs_pallas.py")
    for k, m, L in FUSED_GRID:
        w, n = L // 4, k + m
        for group in (0, 256):
            g = w // group if group else 0
            for formulation in ("swar", "mxu"):
                c.config = (
                    cfg_str(k, m, L) + f" [group={group}, {formulation}]"
                )
                try:
                    parity, hacc, flags, packed = (
                        rs_pallas.encode_pack_fused.eval_shape(
                            S((_BATCH, k, w), u32), m, group,
                            formulation, True,
                        )
                    )
                    c.shape(parity, (_BATCH, m, w), "fused1 parity")
                    c.dtype(parity, "uint32", "fused1 parity")
                    c.shape(hacc, (_BATCH, n, 8), "fused1 hash partials")
                    c.dtype(hacc, "uint32", "fused1 hash partials")
                    c.shape(flags, (_BATCH, m, g), "fused1 flag words")
                    c.dtype(flags, "uint32", "fused1 flag words")
                    c.shape(packed, (_BATCH, m, w), "fused1 packed")
                    c.dtype(packed, "uint32", "fused1 packed")
                except Exception as e:
                    c.fail(e)

    covers("rs_pallas", "verify_reconstruct_fused")
    c = ctx(rs_pallas.verify_reconstruct_fused, "minio_tpu/ops/rs_pallas.py")
    for k, m, L in FUSED_GRID:
        w, n = L // 4, k + m
        # worst admissible erasure: all m losses fall on data shards
        idx = tuple(range(m, n))[:k]
        for formulation in ("swar", "mxu"):
            c.config = cfg_str(k, m, L) + f" [{formulation}]"
            try:
                data, hacc = (
                    rs_pallas.verify_reconstruct_fused.eval_shape(
                        S((_BATCH, n, w), u32), idx, k, m,
                        formulation, True,
                    )
                )
                c.shape(data, (_BATCH, k, w), "fused GET data words")
                c.dtype(data, "uint32", "fused GET data words")
                c.shape(hacc, (_BATCH, n, 8), "fused GET hash partials")
                c.dtype(hacc, "uint32", "fused GET hash partials")
            except Exception as e:
                c.fail(e)

    # ---- rs_pallas.py: manual-DMA pipelined twins -----------------------
    #
    # MINIO_TPU_CODEC_OVERLAP=pipeline swaps these in for the fused
    # kernels above — identical output contracts by construction (the
    # runtime bit-identity tests assert values; here shapes/dtypes),
    # checked over both formulations like their serialized twins.

    covers("rs_pallas", "encode_pack_pipelined")
    c = ctx(rs_pallas.encode_pack_pipelined, "minio_tpu/ops/rs_pallas.py")
    for k, m, L in FUSED_GRID:
        w, n = L // 4, k + m
        for group in (0, 256):
            g = w // group if group else 0
            for formulation in ("swar", "mxu"):
                c.config = (
                    cfg_str(k, m, L) + f" [group={group}, {formulation}]"
                )
                try:
                    parity, hacc, flags, packed = (
                        rs_pallas.encode_pack_pipelined.eval_shape(
                            S((_BATCH, k, w), u32), m, group,
                            formulation, True,
                        )
                    )
                    c.shape(parity, (_BATCH, m, w), "pipelined parity")
                    c.dtype(parity, "uint32", "pipelined parity")
                    c.shape(hacc, (_BATCH, n, 8), "pipelined partials")
                    c.dtype(hacc, "uint32", "pipelined partials")
                    c.shape(flags, (_BATCH, m, g), "pipelined flag words")
                    c.dtype(flags, "uint32", "pipelined flag words")
                    c.shape(packed, (_BATCH, m, w), "pipelined packed")
                    c.dtype(packed, "uint32", "pipelined packed")
                except Exception as e:
                    c.fail(e)

    covers("rs_pallas", "verify_reconstruct_pipelined")
    c = ctx(
        rs_pallas.verify_reconstruct_pipelined, "minio_tpu/ops/rs_pallas.py"
    )
    for k, m, L in FUSED_GRID:
        w, n = L // 4, k + m
        idx = tuple(range(m, n))[:k]
        for formulation in ("swar", "mxu"):
            c.config = cfg_str(k, m, L) + f" [{formulation}]"
            try:
                data, hacc = (
                    rs_pallas.verify_reconstruct_pipelined.eval_shape(
                        S((_BATCH, n, w), u32), idx, k, m,
                        formulation, True,
                    )
                )
                c.shape(data, (_BATCH, k, w), "pipelined GET data words")
                c.dtype(data, "uint32", "pipelined GET data words")
                c.shape(hacc, (_BATCH, n, 8), "pipelined GET partials")
                c.dtype(hacc, "uint32", "pipelined GET partials")
            except Exception as e:
                c.fail(e)

    # ---- parallel/mesh.py: compile-seam mesh kernels --------------------
    #
    # Mesh kernels are not module-level jitted attrs: they are built per
    # geometry through the rules.py compile seam.  Contracts abstract-
    # eval each registered kind through BOTH lowerings (jit+NamedSharding
    # and shard_map) on a 1-device probe mesh — geometry-independent
    # shape/dtype truth that holds on any host, mirroring how the ops/
    # kernels are checked without an accelerator.

    from minio_tpu.parallel import mesh as pmesh, rules as prules

    probe = pmesh.make_mesh(jax.devices()[:1], stripe=1, shard=1)
    mesh_checked: "set[str]" = set()

    def mesh_ctx(kind):
        kd = prules.kernel_def(kind)
        return ctx(
            kd.build_local or kd.build_global,
            "minio_tpu/parallel/mesh.py",
        )

    def mesh_modes(kind):
        kd = prules.kernel_def(kind)
        modes = []
        if kd.build_global is not None:
            modes.append("jit")
        if kd.build_local is not None:
            modes.append("shard_map")
        return modes

    def mesh_eval(kind, mode, args, statics):
        fn = prules.compile_kernel(kind, probe, force_mode=mode, **statics)
        return fn.eval_shape(*args)

    mesh_checked.add("sharded_encode")
    c = mesh_ctx("sharded_encode")
    for k, m, L in CONFIG_GRID:
        for mode in mesh_modes("sharded_encode"):
            c.config = cfg_str(k, m, L) + f" [{mode}]"
            try:
                out = mesh_eval(
                    "sharded_encode", mode,
                    (S((_BATCH, k, L), u8),), dict(k=k, m=m),
                )
                c.shape(out, (_BATCH, m, L), "mesh parity bytes")
                c.dtype(out, "uint8", "mesh parity bytes")
            except Exception as e:
                c.fail(e)

    mesh_checked.add("sharded_encode_seq")
    c = mesh_ctx("sharded_encode_seq")
    for k, m, L in CONFIG_GRID:
        for mode in mesh_modes("sharded_encode_seq"):
            c.config = cfg_str(k, m, L) + f" [{mode}]"
            try:
                out = mesh_eval(
                    "sharded_encode_seq", mode,
                    (S((k, L), u8),), dict(k=k, m=m),
                )
                c.shape(out, (m, L), "seq parity bytes")
                c.dtype(out, "uint8", "seq parity bytes")
            except Exception as e:
                c.fail(e)

    mesh_checked.add("mesh_encode_hash")
    c = mesh_ctx("mesh_encode_hash")
    for k, m, L in CONFIG_GRID:
        w = L // 4
        for mode in mesh_modes("mesh_encode_hash"):
            c.config = cfg_str(k, m, L) + f" [{mode}]"
            try:
                parity, ddig, pdig = mesh_eval(
                    "mesh_encode_hash", mode,
                    (S((_BATCH, k, w), u32),),
                    dict(k=k, m=m, shard_len=L),
                )
                c.shape(parity, (_BATCH, m, w), "mesh parity words")
                c.dtype(parity, "uint32", "mesh parity words")
                c.shape(ddig, (_BATCH, k, 8), "mesh data digests")
                c.dtype(ddig, "uint32", "mesh data digests")
                c.shape(pdig, (_BATCH, m, 8), "mesh parity digests")
                c.dtype(pdig, "uint32", "mesh parity digests")
            except Exception as e:
                c.fail(e)

    mesh_checked.add("mesh_reconstruct")
    c = mesh_ctx("mesh_reconstruct")
    for k, m, L in CONFIG_GRID:
        w, n = L // 4, k + m
        # worst admissible erasure: all m losses fall on data shards
        idx = tuple(range(m, n))[:k]
        for mode in mesh_modes("mesh_reconstruct"):
            c.config = cfg_str(k, m, L) + f" [{mode}]"
            try:
                out = mesh_eval(
                    "mesh_reconstruct", mode,
                    (S((_BATCH, k, w), u32),),
                    dict(k=k, m=m, idx=idx),
                )
                c.shape(out, (_BATCH, k, w), "mesh recon words")
                c.dtype(out, "uint32", "mesh recon words")
                # MTPU203: mesh encode -> reconstruct round-trip
                parity, _, _ = mesh_eval(
                    "mesh_encode_hash", mesh_modes("mesh_encode_hash")[0],
                    (S((_BATCH, k, w), u32),),
                    dict(k=k, m=m, shard_len=L),
                )
                surv = S((_BATCH, parity.shape[1] + (k - m), w), parity.dtype)
                rt = mesh_eval(
                    "mesh_reconstruct", mode,
                    (surv,), dict(k=k, m=m, idx=idx),
                )
                c.expect(
                    "MTPU203",
                    (tuple(rt.shape), str(rt.dtype)),
                    ((_BATCH, k, w), "uint32"),
                    "mesh encode->reconstruct round-trip (words)",
                )
            except Exception as e:
                c.fail(e)

    mesh_checked.add("mesh_digest")
    c = mesh_ctx("mesh_digest")
    for k, m, L in CONFIG_GRID:
        w = L // 4
        for mode in mesh_modes("mesh_digest"):
            c.config = cfg_str(k, m, L) + f" [{mode}]"
            try:
                out = mesh_eval(
                    "mesh_digest", mode,
                    (S((_BATCH, w), u32),), dict(shard_len=L),
                )
                c.shape(out, (_BATCH, 8), "mesh digests")
                c.dtype(out, "uint32", "mesh digests")
            except Exception as e:
                c.fail(e)

    mesh_checked.add("mesh_verify_reconstruct")
    c = mesh_ctx("mesh_verify_reconstruct")
    for k, m, L in CONFIG_GRID:
        w, n = L // 4, k + m
        present = (False,) * m + (True,) * (n - m)
        for mode in mesh_modes("mesh_verify_reconstruct"):
            c.config = cfg_str(k, m, L) + f" [{mode}]"
            try:
                data, ok = mesh_eval(
                    "mesh_verify_reconstruct", mode,
                    (S((_BATCH, n, w), u32), S((_BATCH, n, 8), u32)),
                    dict(k=k, m=m, present=present, shard_len=L),
                )
                c.shape(data, (_BATCH, k, w), "mesh fused GET data words")
                c.dtype(data, "uint32", "mesh fused GET data words")
                c.shape(ok, (_BATCH, n), "mesh fused GET ok mask")
                c.dtype(ok, "bool", "mesh fused GET ok mask")
            except Exception as e:
                c.fail(e)

    # seam-registry closure: a kernel registered with the compile seam
    # but missing a contract block above fails MTPU204 the same way a
    # new module-level jitted entry point does
    for kind in prules.registered_kernels():
        if kind not in mesh_checked:
            kd = prules.kernel_def(kind)
            path, line = _anchor(
                kd.build_local or kd.build_global,
                "minio_tpu/parallel/mesh.py",
            )
            findings.append(
                Finding(
                    "MTPU204",
                    path,
                    line,
                    f"mesh kernel {kind!r} registered with the compile "
                    "seam has no contract check; add one in "
                    "minio_tpu/analysis/kernel_contracts.py",
                )
            )

    # ---- coverage closure (MTPU204) -------------------------------------

    for (mod, name), fn in jit_entry_points().items():
        if (mod, name) not in checked:
            path, line = _anchor(fn, f"minio_tpu/ops/{mod}.py")
            findings.append(
                Finding(
                    "MTPU204",
                    path,
                    line,
                    f"jitted entry point {mod}.{name} has no registered "
                    "kernel contract; add a check in "
                    "minio_tpu/analysis/kernel_contracts.py",
                )
            )

    return findings


def covered_entry_points() -> "set[tuple[str, str]]":
    """The (module, name) pairs the contract run exercises.

    Derived by running the checker against the live registry: everything
    introspection finds minus whatever MTPU204 flags.
    """
    flagged = {
        f.message.split(" ")[3] for f in run() if f.rule == "MTPU204"
    }
    return {
        key
        for key in jit_entry_points()
        if f"{key[0]}.{key[1]}" not in flagged
    }
