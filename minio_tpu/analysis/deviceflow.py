"""MTPU5xx: interprocedural device-value provenance over the call graph.

The existing passes are per-file pattern matches; the bug classes that
actually bit this tree are whole-program dataflow facts:

* **MTPU501 — use-after-donate.**  A value passed at a ``donate_argnums``
  position is dead after the call (XLA may alias its buffer into an
  output); reading it again is the PR 14 donation-aliasing hazard,
  previously caught only by a runtime regression test.
* **MTPU502 — interprocedural D2H escape.**  A device-provenance value
  (return of a registered jitted entry point, or anything derived from
  one) reaching ``np.asarray`` / ``bytes()`` / ``.item()`` /
  ``jax.device_get`` outside a registered drain seam — anywhere in the
  tree, through calls.  Generalizes MTPU107/111, which stay as fast
  local checks on their two hand-scoped modules.
* **MTPU503 — device value across a thread boundary.**  A closure (or
  argument) crossing a `submit`/`run_coroutine_threadsafe`/`Thread`
  boundary while capturing a device value: the D2H then happens as a
  hidden sync on an arbitrary worker thread, outside every seam.
* **MTPU504 — call-graph-deep blocking-under-async.**  MTPU108 one
  level deep only sees blocking calls lexically inside an ``async
  def``; this walks the call graph from every ``server/`` async def
  through plain (non-boundary) edges and flags blocking calls in the
  sync callees that therefore run ON the loop.  Pool/executor/thread
  boundary edges cut the traversal — that is exactly the sanctioned
  sync-def bridge (``_LoopReader``/``_LoopWriter`` block on worker
  threads by design) — while ``call_soon_threadsafe`` /
  ``run_coroutine_threadsafe`` closures remain loop-resident and are
  traversed.
* **MTPU505 — registry drift.**  The ``kernel_contracts`` dataflow
  registry (entry points, donation positions, drain seams) is
  cross-checked against the tree in both directions, the MTPU403
  orphan-check discipline applied to the new facts.

The value-tracking is deliberately locals-only and conservative:
attributes and containers are untracked, unresolvable calls produce no
taint, and control flow is approximated by source order.  It
under-approximates (no false paths through attributes) — every finding
it does emit survives triage or gets a reasoned ``# noqa``.
"""

from __future__ import annotations

import ast
import dataclasses
import time
from collections import deque

from . import callgraph as cg
from .astcache import ParsedModule
from .findings import Finding

# canonical dotted names (after import-alias resolution) -------------------

# producers: calls whose result is device-resident
_DEVICE_PRODUCER_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.nn.")
_DEVICE_PRODUCER_EXACT = {"jax.device_put", "jax.jit"}

# D2H sinks (the MTPU502 escape set); ``bytes`` is matched as a bare
# builtin name, ``.item()``/``.tobytes()`` as zero-arg methods
_SINK_CALLS = {"numpy.asarray", "numpy.array", "jax.device_get"}
_SINK_METHODS = {"item", "tobytes"}

# attribute loads on a device value that yield HOST metadata
_HOST_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes", "sharding"}

# blocking-call shapes for MTPU504 (mirrors MTPU108, which owns the
# lexically-async case; 504 owns the reachable-sync-callee case)
_BLOCK_SLEEPS = {"time.sleep", "_time.sleep"}
_BLOCK_SOCKET_ATTRS = {"recv", "recv_into", "sendall", "sendto", "recvfrom"}

_SERVER_PREFIX = "minio_tpu/server/"


@dataclasses.dataclass
class Registry:
    """The dataflow fact tables, resolved to qname form.

    Defaults come from ``kernel_contracts``; tests inject synthetic
    registries to exercise fixture files in isolation.
    """

    # "rel/path.py::name" of every device-producing jitted entry point
    entry_qnames: "frozenset[str]"
    # "rel/path.py::name" -> donated positional indices
    donating_qnames: "dict[str, tuple[int, ...]]"
    # mesh kernel kind -> donated positional indices of the compiled fn
    mesh_donating: "dict[str, tuple[int, ...]]"
    # rel path -> bare function names that are sanctioned drain seams
    drain_seams: "dict[str, tuple[str, ...]]"
    # short module -> rel path (for the MTPU505 existence checks)
    entry_point_paths: "dict[str, str]"
    # (short module, name) pairs, as registered
    known_entry_points: "frozenset[tuple[str, str]]"
    donating_entry_points: "dict[tuple[str, str], tuple[int, ...]]"

    @classmethod
    def default(cls) -> "Registry":
        from . import kernel_contracts as kc

        paths = kc.ENTRY_POINT_PATHS
        return cls(
            entry_qnames=frozenset(
                f"{paths[m]}::{n}" for m, n in kc.KNOWN_ENTRY_POINTS
            ),
            donating_qnames={
                f"{paths[m]}::{n}": pos
                for (m, n), pos in kc.DONATING_ENTRY_POINTS.items()
            },
            mesh_donating=dict(kc.MESH_DONATING_KERNELS),
            drain_seams=dict(kc.DRAIN_SEAMS),
            entry_point_paths=dict(paths),
            known_entry_points=frozenset(kc.KNOWN_ENTRY_POINTS),
            donating_entry_points=dict(kc.DONATING_ENTRY_POINTS),
        )

    def is_drain(self, qname: str) -> bool:
        rel, _, qual = qname.partition("::")
        name = qual.rsplit(".", 1)[-1]
        return name in self.drain_seams.get(rel, ())


def _canonical(facts, func: ast.AST) -> "str | None":
    """Import-alias-resolved dotted name of a call target expression."""
    parts = cg._dotted_parts(func)
    if parts is None:
        return None
    head = facts.imports.get(parts[0], parts[0]) if facts else parts[0]
    return ".".join([head] + parts[1:])


def _param_names(info: cg.FuncInfo) -> "list[str]":
    """Positional parameter names as seen by a caller (self/cls elided
    for methods, since every resolved method edge is a bound call)."""
    a = info.node.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if info.cls is not None and names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _collect_awaited(func_node: ast.AST) -> "set[int]":
    """ids of Call nodes that are awaited (directly or as coroutine
    args of an awaited asyncio.* wrapper) — the MTPU108 exemption."""
    out: "set[int]" = set()
    for node in ast.walk(func_node):
        if not isinstance(node, ast.Await):
            continue
        v = node.value
        if isinstance(v, ast.Call):
            out.add(id(v))
            dotted = ".".join(cg._dotted_parts(v.func) or [])
            if dotted.startswith("asyncio."):
                for a in list(v.args) + [kw.value for kw in v.keywords]:
                    if isinstance(a, ast.Call):
                        out.add(id(a))
    return out


# ---------------------------------------------------------------------------
# per-function taint interpretation
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _FuncResult:
    returns_device: bool = False
    # callee qname -> tainted parameter names discovered at call sites
    param_out: "dict[str, set[str]]" = dataclasses.field(
        default_factory=dict
    )


class _Interp:
    """One forward pass over a function body, locals-only taint.

    Source order approximates control flow: a donation "happens before"
    any read on a later line of the same body, branches share one
    environment.  Nested def/class bodies are skipped — they are their
    own call-graph nodes (and MTPU503 owns the capture case).
    """

    def __init__(
        self,
        pass_: "_DeviceflowPass",
        qname: str,
        facts,
        body: "list[ast.stmt]",
        seeded_params: "set[str]",
        emit: bool,
    ):
        self.p = pass_
        self.qname = qname
        self.rel_path = qname.partition("::")[0]
        self.facts = facts
        self.body = body
        self.emit = emit
        self.in_drain = pass_.registry.is_drain(qname)
        self.is_entry = qname in pass_.registry.entry_qnames
        self.env: "set[str]" = set(seeded_params)
        # name -> (line, callee label) of an outstanding donation
        self.donated: "dict[str, tuple[int, str]]" = {}
        # local var -> donated positions of a compiled donating kernel
        self.donating_fns: "dict[str, tuple[int, ...]]" = {}
        self.result = _FuncResult()

    # -- findings ---------------------------------------------------------

    def _emit(self, rule: str, node: ast.AST, msg: str) -> None:
        if self.emit:
            self.p.findings.append(
                Finding(
                    rule, self.rel_path, getattr(node, "lineno", 1), msg
                )
            )

    # -- statements -------------------------------------------------------

    def run(self) -> _FuncResult:
        for stmt in self.body:
            self._stmt(stmt)
        return self.result

    def _stmt(self, s: ast.stmt) -> None:
        if isinstance(
            s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return  # own graph node
        if isinstance(s, ast.Assign):
            t = self._eval(s.value)
            for tgt in s.targets:
                self._assign(tgt, s.value, t)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self._assign(s.target, s.value, self._eval(s.value))
        elif isinstance(s, ast.AugAssign):
            t = self._eval(s.value)
            if isinstance(s.target, ast.Name):
                self._read(s.target)
                if t:
                    self.env.add(s.target.id)
        elif isinstance(s, ast.Return):
            if s.value is not None and self._eval(s.value):
                self.result.returns_device = True
        elif isinstance(s, ast.Expr):
            self._eval(s.value)
        elif isinstance(s, ast.If):
            self._eval(s.test)
            for b in (s.body, s.orelse):
                saved = dict(self.donated)
                for st in b:
                    self._stmt(st)
                if b and isinstance(
                    b[-1],
                    (ast.Return, ast.Raise, ast.Break, ast.Continue),
                ):
                    # a branch that cannot fall through takes its
                    # donation records (and kills) with it
                    self.donated = saved
        elif isinstance(s, ast.While):
            self._eval(s.test)
            for b in (s.body, s.orelse):
                for st in b:
                    self._stmt(st)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            t = self._eval(s.iter)
            self._assign(s.target, s.iter, t)
            for b in (s.body, s.orelse):
                for st in b:
                    self._stmt(st)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                t = self._eval(item.context_expr)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, item.context_expr, t)
            for st in s.body:
                self._stmt(st)
        elif isinstance(s, ast.Try):
            for st in s.body:
                self._stmt(st)
            for h in s.handlers:
                for st in h.body:
                    self._stmt(st)
            for b in (s.orelse, s.finalbody):
                for st in b:
                    self._stmt(st)
        elif isinstance(s, ast.Delete):
            for tgt in s.targets:
                if isinstance(tgt, ast.Name):
                    self.env.discard(tgt.id)
                    self.donated.pop(tgt.id, None)
        elif isinstance(s, (ast.Raise, ast.Assert)):
            for v in (getattr(s, "exc", None), getattr(s, "test", None),
                      getattr(s, "msg", None)):
                if v is not None:
                    self._eval(v)

    def _assign(self, tgt: ast.AST, value: ast.AST, tainted: bool) -> None:
        if isinstance(tgt, ast.Name):
            # rebinding kills both taint and any outstanding donation:
            # the NAME now refers to a fresh value
            self.donated.pop(tgt.id, None)
            if tainted:
                self.env.add(tgt.id)
            else:
                self.env.discard(tgt.id)
            # track `fn = rules.compile_kernel("kind", ...)` donating
            # callables so the later fn(dd) call donates dd
            if isinstance(value, ast.Call):
                kind = self._compiled_kernel_kind(value)
                if kind is not None:
                    pos = self.p.registry.mesh_donating.get(kind)
                    if pos:
                        self.donating_fns[tgt.id] = pos
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            elems = tgt.elts
            src = (
                value.elts
                if isinstance(value, (ast.Tuple, ast.List))
                and len(value.elts) == len(elems)
                else None
            )
            for i, e in enumerate(elems):
                et = self._eval(src[i]) if src is not None else tainted
                self._assign(e, src[i] if src else value, et)
        elif isinstance(tgt, ast.Starred):
            self._assign(tgt.value, value, tainted)
        # attribute/subscript targets: untracked (locals-only)

    # -- expressions ------------------------------------------------------

    def _read(self, node: ast.Name) -> None:
        """MTPU501: a load of a name with an outstanding donation."""
        rec = self.donated.get(node.id)
        if rec is not None:
            line, label = rec
            self._emit(
                "MTPU501",
                node,
                f"'{node.id}' is read after being donated to {label} "
                f"(line {line}); donated buffers may be aliased into "
                "kernel outputs — use the kernel's result, or pass a "
                "copy if the input must survive",
            )

    def _eval(self, e: ast.AST) -> bool:
        if isinstance(e, ast.Name):
            self._read(e)
            return e.id in self.env
        if isinstance(e, ast.Call):
            return self._eval_call(e)
        if isinstance(e, ast.Attribute):
            t = self._eval(e.value)
            return t and e.attr not in _HOST_ATTRS
        if isinstance(e, ast.Subscript):
            t = self._eval(e.value)
            self._eval(e.slice)
            return t
        if isinstance(e, ast.BinOp):
            left = self._eval(e.left)
            right = self._eval(e.right)
            return left or right
        if isinstance(e, ast.UnaryOp):
            return self._eval(e.operand)
        if isinstance(e, ast.BoolOp):
            return any([self._eval(v) for v in e.values])
        if isinstance(e, ast.Compare):
            self._eval(e.left)
            for c in e.comparators:
                self._eval(c)
            return False  # comparisons yield bools (device bools: rare)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any([self._eval(v) for v in e.elts])
        if isinstance(e, ast.Dict):
            vals = [v for v in e.values if v is not None]
            return any([self._eval(v) for v in vals])
        if isinstance(e, ast.IfExp):
            self._eval(e.test)
            a = self._eval(e.body)
            b = self._eval(e.orelse)
            return a or b
        if isinstance(e, ast.Await):
            return self._eval(e.value)
        if isinstance(e, ast.NamedExpr):
            t = self._eval(e.value)
            self._assign(e.target, e.value, t)
            return t
        if isinstance(e, ast.Starred):
            return self._eval(e.value)
        if isinstance(e, (ast.JoinedStr, ast.FormattedValue)):
            for v in ast.iter_child_nodes(e):
                if isinstance(v, ast.expr):
                    self._eval(v)
            return False
        if isinstance(e, ast.Lambda):
            return False  # body analyzed at boundary sites (MTPU503)
        if isinstance(
            e, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            # comprehensions: evaluate iterables for reads; element
            # taint is untracked (locals-only discipline)
            for gen in e.generators:
                self._eval(gen.iter)
            return False
        return False

    def _compiled_kernel_kind(self, call: ast.Call) -> "str | None":
        dotted = _canonical(self.facts, call.func) or ""
        if not dotted.endswith("compile_kernel"):
            return None
        if call.args and isinstance(call.args[0], ast.Constant):
            v = call.args[0].value
            if isinstance(v, str):
                return v
        return None

    def _eval_call(self, call: ast.Call) -> bool:
        # receiver / function expression first (it is read)
        recv_taint = False
        if isinstance(call.func, ast.Attribute):
            recv_taint = self._eval(call.func.value)
        elif isinstance(call.func, ast.Name):
            self._read(call.func)

        arg_taints = [self._eval(a) for a in call.args]
        kw_taints = {
            kw.arg: self._eval(kw.value)
            for kw in call.keywords
            if kw.arg is not None
        }
        for kw in call.keywords:
            if kw.arg is None:
                self._eval(kw.value)

        dotted = _canonical(self.facts, call.func) or ""
        edge = self.p.graph.call_info.get(id(call))
        callee = edge.callee if edge is not None else None

        # MTPU503: boundary crossings are handled here so the closure
        # sees the env at the crossing point
        if edge is not None and edge.boundary is not None:
            self._check_boundary(call, edge)

        # MTPU502 sinks
        if dotted in _SINK_CALLS or (
            isinstance(call.func, ast.Name) and call.func.id == "bytes"
        ):
            if arg_taints and arg_taints[0]:
                self._sink(call, dotted or "bytes")
            return False
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _SINK_METHODS
            and recv_taint
        ):
            self._sink(call, f".{call.func.attr}()")
            return False

        # donation: registered donating entry point, or a local var
        # bound to a compiled donating mesh kernel
        donate_pos: "tuple[int, ...]" = ()
        label = ""
        if callee is not None and callee in self.p.registry.donating_qnames:
            donate_pos = self.p.registry.donating_qnames[callee]
            label = callee.rsplit("::", 1)[-1]
        elif (
            isinstance(call.func, ast.Name)
            and call.func.id in self.donating_fns
        ):
            donate_pos = self.donating_fns[call.func.id]
            label = f"compiled kernel '{call.func.id}'"
        for pos in donate_pos:
            if pos < len(call.args) and isinstance(
                call.args[pos], ast.Name
            ):
                self.donated[call.args[pos].id] = (call.lineno, label)

        # interprocedural parameter taint
        if (
            callee is not None
            and edge.boundary is None
            and callee in self.p.graph.funcs
        ):
            info = self.p.graph.funcs[callee]
            pnames = _param_names(info)
            hit = {
                pnames[i]
                for i, t in enumerate(arg_taints)
                if t and i < len(pnames)
            }
            hit |= {k for k, t in kw_taints.items() if t and k in pnames}
            if hit:
                self.result.param_out.setdefault(callee, set()).update(hit)

        # producer classification
        if callee is not None:
            if callee in self.p.registry.entry_qnames:
                return True
            if self.p.registry.is_drain(callee):
                return False  # drained: the return is a host fact
            if self.p.summaries.get(callee):
                return True
        if dotted in _DEVICE_PRODUCER_EXACT or dotted.startswith(
            _DEVICE_PRODUCER_PREFIXES
        ):
            return True
        # method on a device value stays device (astype/reshape/...)
        if isinstance(call.func, ast.Attribute) and recv_taint:
            return True
        return False

    def _sink(self, call: ast.Call, what: str) -> None:
        if self.in_drain or self.is_entry:
            return
        self._emit(
            "MTPU502",
            call,
            f"device-provenance value reaches {what} outside a "
            "registered drain seam: this D2H sync belongs in a "
            "*_end/drain function from kernel_contracts.DRAIN_SEAMS "
            "(or register this one)",
        )

    # -- MTPU503 ----------------------------------------------------------

    def _free_loads(self, node: ast.AST) -> "set[str]":
        """Names a closure body loads that it does not itself bind."""
        bound: "set[str]" = set()
        loads: "set[str]" = set()
        if isinstance(node, ast.Lambda):
            a = node.args
            bound |= {
                p.arg
                for p in a.posonlyargs + a.args + a.kwonlyargs
            }
            if a.vararg:
                bound.add(a.vararg.arg)
            if a.kwarg:
                bound.add(a.kwarg.arg)
            walk_root: "ast.AST" = node.body
        else:  # FunctionDef / AsyncFunctionDef
            a = node.args
            bound |= {
                p.arg
                for p in a.posonlyargs + a.args + a.kwonlyargs
            }
            if a.vararg:
                bound.add(a.vararg.arg)
            if a.kwarg:
                bound.add(a.kwarg.arg)
            walk_root = ast.Module(body=node.body, type_ignores=[])
        for n in ast.walk(walk_root):
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Store):
                    bound.add(n.id)
                else:
                    loads.add(n.id)
        return loads - bound

    def _check_boundary(self, call: ast.Call, edge: cg.Edge) -> None:
        captured: "set[str]" = set()
        local_defs = self.p.graph.locals_of.get(self.qname, {})
        for arg in cg.closure_args(call, edge.boundary):
            if isinstance(arg, ast.Lambda):
                captured |= self._free_loads(arg) & self.env
            elif isinstance(arg, ast.Name):
                target = local_defs.get(arg.id)
                info = (
                    self.p.graph.funcs.get(target)
                    if target is not None
                    else None
                )
                if info is not None:
                    captured |= self._free_loads(info.node) & self.env
                elif arg.id in self.env:
                    captured.add(arg.id)  # device value passed as data
        # device values passed as plain data args (run_in_executor
        # style) also cross the boundary
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Name) and a.id in self.env:
                captured.add(a.id)
        if captured:
            names = ", ".join(f"'{n}'" for n in sorted(captured))
            self._emit(
                "MTPU503",
                call,
                f"device value {names} crosses a {edge.boundary} "
                "thread-boundary without materialization; the D2H then "
                "happens as a hidden sync on an arbitrary thread — "
                "materialize through a drain seam first, or ship host "
                "data",
            )


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


class _DeviceflowPass:
    def __init__(
        self,
        sources: "dict[str, ParsedModule]",
        graph: cg.CallGraph,
        registry: Registry,
    ):
        self.sources = sources
        self.graph = graph
        self.registry = registry
        self.findings: "list[Finding]" = []
        self.summaries: "dict[str, bool]" = {}
        self.tainted_params: "dict[str, set[str]]" = {}

    # -- driver -----------------------------------------------------------

    def run(self) -> "list[Finding]":
        self._fixpoint()
        for qname in sorted(self.graph.funcs):
            self._analyze(qname, emit=True)
        self._check_loop_reachable()
        self._check_registry_drift()
        self.findings.sort(key=lambda f: f.sort_key())
        return self.findings

    def _analyze(self, qname: str, emit: bool) -> _FuncResult:
        info = self.graph.funcs[qname]
        facts = self.graph.modules.get(info.rel_path)
        seeded = self.tainted_params.get(qname, set())
        body = info.node.body
        if isinstance(info.node, ast.Lambda):
            # a lambda body is one expression: analyze it as a return
            ret = ast.Return(value=body)
            ast.copy_location(ret, body)
            body = [ret]
        interp = _Interp(
            self, qname, facts, body, set(seeded), emit
        )
        res = interp.run()
        if qname in self.registry.entry_qnames:
            res.returns_device = True
        if self.registry.is_drain(qname):
            res.returns_device = False
        return res

    def _fixpoint(self) -> None:
        callers: "dict[str, set[str]]" = {}
        for e in self.graph.edges:
            if e.callee is not None and e.boundary is None:
                callers.setdefault(e.callee, set()).add(e.caller)
        work = deque(sorted(self.graph.funcs))
        queued = set(work)
        while work:
            qname = work.popleft()
            queued.discard(qname)
            res = self._analyze(qname, emit=False)
            if res.returns_device != self.summaries.get(qname, False):
                self.summaries[qname] = res.returns_device
                for caller in callers.get(qname, ()):
                    if caller in self.graph.funcs and caller not in queued:
                        work.append(caller)
                        queued.add(caller)
            for callee, pnames in res.param_out.items():
                cur = self.tainted_params.setdefault(callee, set())
                if pnames - cur:
                    cur |= pnames
                    if callee in self.graph.funcs and callee not in queued:
                        work.append(callee)
                        queued.add(callee)

    # -- MTPU504 ----------------------------------------------------------

    def _check_loop_reachable(self) -> None:
        """Blocking calls in sync functions that run on the event loop
        because a server async def (or a loop-resident closure) calls
        them through plain edges."""
        edges_from = self.graph.edges_from()
        roots = [
            q
            for q, info in self.graph.funcs.items()
            if info.is_async and info.rel_path.startswith(_SERVER_PREFIX)
        ]
        for e in self.graph.boundary_edges():
            if (
                e.boundary in cg.LOOP_RESIDENT_KINDS
                and e.callee in self.graph.funcs
            ):
                roots.append(e.callee)
        first_root: "dict[str, str]" = {}
        work = deque()
        for r in sorted(set(roots)):
            if r not in first_root:
                first_root[r] = r
                work.append(r)
        while work:
            q = work.popleft()
            for e in edges_from.get(q, ()):
                if e.boundary is not None and (
                    e.boundary not in cg.LOOP_RESIDENT_KINDS
                ):
                    continue  # worker-pool bridge: blocking is legal
                callee = e.callee
                if callee in self.graph.funcs and callee not in first_root:
                    first_root[callee] = first_root[q]
                    work.append(callee)
        for qname in sorted(first_root):
            info = self.graph.funcs[qname]
            if info.is_async and info.rel_path.startswith(_SERVER_PREFIX):
                continue  # MTPU108's lexical turf
            root = first_root[qname]
            self._scan_blocking(info, root)

    def _scan_blocking(self, info: cg.FuncInfo, root: str) -> None:
        facts = self.graph.modules.get(info.rel_path)
        awaited = _collect_awaited(info.node) if info.is_async else set()
        nested = self._nested_def_calls(info.node)
        for node in ast.walk(info.node):
            if (
                isinstance(node, ast.Call)
                and id(node) not in awaited
                and id(node) not in nested
            ):
                desc = self._blocking_desc(facts, node)
                if desc is not None:
                    root_name = root.split("::", 1)[-1]
                    self.findings.append(
                        Finding(
                            "MTPU504",
                            info.rel_path,
                            node.lineno,
                            f"{desc} blocks the event loop: "
                            f"{info.name}() runs on the loop (reachable "
                            f"from async {root_name} through plain "
                            "calls) — move the call behind a worker-"
                            "pool boundary or await an async "
                            "equivalent",
                        )
                    )

    @staticmethod
    def _nested_def_calls(func_node: ast.AST) -> "set[int]":
        """ids of Call nodes inside defs nested under ``func_node`` —
        those bodies are their own call-graph nodes and are reached (or
        not) through their own edges."""
        out: "set[int]" = set()
        for node in ast.walk(func_node):
            if node is func_node:
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for inner in ast.walk(node):
                    if isinstance(inner, ast.Call):
                        out.add(id(inner))
        return out

    def _blocking_desc(self, facts, call: ast.Call) -> "str | None":
        dotted = _canonical(facts, call.func) or ""
        if dotted in _BLOCK_SLEEPS or dotted == "time.sleep":
            return f"{dotted}()"
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        if attr in _BLOCK_SOCKET_ATTRS:
            return f"raw socket .{attr}()"
        if attr == "result":
            return "Future.result()"
        if attr == "wait" and not dotted.startswith("asyncio."):
            return ".wait()"
        return None

    # -- MTPU505 ----------------------------------------------------------

    def _drift(self, path: str, line: int, msg: str) -> None:
        self.findings.append(Finding("MTPU505", path, line, msg))

    def _check_registry_drift(self) -> None:
        reg = self.registry
        rel_to_short = {v: k for k, v in reg.entry_point_paths.items()}

        # 1. every registered entry point must resolve to a def
        for mod, name in sorted(reg.known_entry_points):
            rel = reg.entry_point_paths.get(mod)
            if rel is None or rel not in self.sources:
                continue  # can't check what we didn't parse
            if self.graph.lookup(rel, name) is None:
                self._drift(
                    rel,
                    1,
                    f"registry drift: KNOWN_ENTRY_POINTS declares "
                    f"{mod}.{name} but no such def exists in {rel}",
                )

        # 2./3. donation: decorator facts vs DONATING_ENTRY_POINTS
        declared: "dict[tuple[str, str], tuple[tuple[int, ...], int]]" = {}
        for rel, mod in self.sources.items():
            if mod.tree is None:
                continue
            for node in mod.tree.body:
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    pos = self._decorator_donation(node)
                    if pos is not None:
                        declared[(rel, node.name)] = (pos, node.lineno)
        registered = {
            (reg.entry_point_paths[m], n): p
            for (m, n), p in reg.donating_entry_points.items()
            if reg.entry_point_paths.get(m)
        }
        for key, (pos, line) in sorted(declared.items()):
            rel, name = key
            want = registered.get(key)
            if want is None:
                self._drift(
                    rel,
                    line,
                    f"registry drift: {name} declares donate_argnums="
                    f"{pos} in its jit decorator but is not in "
                    "kernel_contracts.DONATING_ENTRY_POINTS",
                )
            elif tuple(want) != pos:
                self._drift(
                    rel,
                    line,
                    f"registry drift: {name} donates {pos} but "
                    f"DONATING_ENTRY_POINTS registers {tuple(want)}",
                )
        for key, want in sorted(registered.items()):
            rel, name = key
            if rel not in self.sources:
                continue
            if key not in declared:
                info = self.graph.lookup(rel, name)
                self._drift(
                    rel,
                    info.lineno if info else 1,
                    f"registry drift: DONATING_ENTRY_POINTS registers "
                    f"{name} donating {tuple(want)} but its jit "
                    "decorator declares no donate_argnums",
                )

        # 4. mesh kernels: register_kernel literals vs registry
        seen_kernels: "dict[str, tuple[tuple[int, ...], str, int]]" = {}
        for rel, mod in self.sources.items():
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _canonical(
                    self.graph.modules.get(rel), node.func
                ) or ""
                if not dotted.endswith("register_kernel"):
                    continue
                if not (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    continue
                kind = node.args[0].value
                pos = ()
                literal = True
                for kw in node.keywords:
                    if kw.arg == "donate_argnums":
                        lit = self._int_tuple_literal(kw.value)
                        if lit is None:
                            literal = False
                        else:
                            pos = lit
                if literal:
                    seen_kernels[kind] = (pos, rel, node.lineno)
        for kind, (pos, rel, line) in sorted(seen_kernels.items()):
            want = reg.mesh_donating.get(kind, ())
            if pos and tuple(want) != pos:
                self._drift(
                    rel,
                    line,
                    f"registry drift: register_kernel('{kind}') "
                    f"declares donate_argnums={pos} but "
                    f"MESH_DONATING_KERNELS registers {tuple(want)}",
                )
        for kind, want in sorted(reg.mesh_donating.items()):
            if kind in seen_kernels:
                continue
            if not any(
                rel.startswith("minio_tpu/parallel/")
                for rel in self.sources
            ):
                continue  # kernel table not in this source set
            self._drift(
                "minio_tpu/parallel/rules.py",
                1,
                f"registry drift: MESH_DONATING_KERNELS registers "
                f"'{kind}' ({tuple(want)}) but no register_kernel call "
                "declares it",
            )

        # 5./6. drain seams: registered names must exist; *_end/drain
        # defs in registered files must be registered
        by_file: "dict[str, set[str]]" = {}
        for qname, info in self.graph.funcs.items():
            by_file.setdefault(info.rel_path, set()).add(info.name)
        for rel, names in sorted(reg.drain_seams.items()):
            if rel not in self.sources:
                continue
            have = by_file.get(rel, set())
            for name in names:
                if name not in have:
                    self._drift(
                        rel,
                        1,
                        f"registry drift: DRAIN_SEAMS registers "
                        f"{name}() in {rel} but no such def exists",
                    )
            registered_names = set(names)
            for qname, info in self.graph.funcs.items():
                if info.rel_path != rel:
                    continue
                n = info.name
                if (
                    n.endswith("_end") or "drain" in n.lower()
                ) and n not in registered_names:
                    self._drift(
                        rel,
                        info.lineno,
                        f"registry drift: {n}() matches the drain-seam "
                        "naming pattern in a DRAIN_SEAMS file but is "
                        "not registered in kernel_contracts.DRAIN_SEAMS",
                    )

    @staticmethod
    def _int_tuple_literal(node: ast.AST) -> "tuple[int, ...] | None":
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(
                    e.value, int
                ):
                    out.append(e.value)
                else:
                    return None
            return tuple(out)
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return (node.value,)
        return None

    def _decorator_donation(self, node) -> "tuple[int, ...] | None":
        """donate_argnums literal from a jit decorator, if any."""
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            dotted = ".".join(cg._dotted_parts(dec.func) or [])
            is_jit = dotted.endswith("jit")
            if not is_jit and dotted.endswith("partial") and dec.args:
                inner = ".".join(cg._dotted_parts(dec.args[0]) or [])
                is_jit = inner.endswith("jit")
            if not is_jit:
                continue
            for kw in dec.keywords:
                if kw.arg == "donate_argnums":
                    return self._int_tuple_literal(kw.value)
        return None


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DeviceflowReport:
    findings: "list[Finding]"  # pre-suppression
    graph: cg.CallGraph
    seconds: float


def analyze_sources(
    sources: "dict[str, ParsedModule]",
    *,
    registry: "Registry | None" = None,
    graph: "cg.CallGraph | None" = None,
) -> DeviceflowReport:
    """Run the deviceflow pass over parsed modules.

    ``registry`` defaults to the kernel_contracts tables; tests inject
    synthetic registries to drive fixture files.  ``graph`` lets the
    CLI reuse a call graph it already built for --changed-only.
    """
    t0 = time.monotonic()
    if graph is None:
        graph = cg.build(sources)
    reg = registry if registry is not None else Registry.default()
    findings = _DeviceflowPass(sources, graph, reg).run()
    return DeviceflowReport(
        findings=findings,
        graph=graph,
        seconds=time.monotonic() - t0,
    )
