"""CLI: ``python -m minio_tpu.analysis [--paths ...] [--json] [--skip ...]``.

Exit status 0 when the tree is clean, 1 when any finding survives noqa
filtering — the same contract tier-1 enforces through
tests/test_analysis.py.

``--changed-only`` narrows the run to what the working tree actually
touches (vs HEAD, plus untracked files): lint runs over just the
changed .py files, and the tree-global passes (contracts, abi, locks)
run only when a file they audit changed.  The deviceflow and lifecycle
passes are interprocedural, so prefix gating would be UNSOUND for them
— editing a callee can create or remove a finding in a caller —
instead they always analyze the whole tree and report findings for the
reverse-dependency closure of the changed files over the call graph.
This keeps the gate fast as the tree grows without weakening a full
run.

``--json`` emits ``{"findings": [...], "passes": {pass: seconds},
"callgraph": {nodes, edges, boundary_edges, seconds}}`` so analyzer
cost is tracked like a benchmark.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

# Canonical directory exclusions for every file-walking pass.  These are
# names, matched against any path component: build artifacts
# (native/build/ holds .so files plus whatever a future codegen step
# drops there) and bytecode caches must never be analyzed, even when a
# user passes them explicitly via --paths.
EXCLUDED_DIR_NAMES = ("__pycache__", "build", ".git", ".claude")

# What each tree-global pass actually audits, for --changed-only: the
# pass runs iff a changed path matches one of its prefixes.
PASS_TRIGGER_PREFIXES = {
    "contracts": (
        "minio_tpu/ops/",
        "minio_tpu/codec/backend.py",
        "minio_tpu/parallel/",
        "minio_tpu/analysis/kernel_contracts.py",
    ),
    "abi": (
        "minio_tpu/utils/native.py",
        "native/csrc/",
        "minio_tpu/analysis/abi_contracts.py",
    ),
    "locks": (
        "minio_tpu/dsync/",
        "minio_tpu/storage/metered.py",
        "minio_tpu/storage/diskcheck.py",
        "minio_tpu/parallel/iopool.py",
        "minio_tpu/analysis/lockorder.py",
    ),
}

PASSES = ("lint", "abi", "contracts", "locks", "deviceflow", "lifecycle")


def _changed_files(repo_root: str) -> "set[str]":
    """Repo-relative paths changed vs HEAD, plus untracked files."""
    out: "set[str]" = set()
    for args in (
        ["diff", "--name-only", "HEAD"],
        ["ls-files", "--others", "--exclude-standard"],
    ):
        r = subprocess.run(
            ["git", "-C", repo_root, *args],
            capture_output=True,
            text=True,
        )
        if r.returncode == 0:
            out.update(
                ln.strip() for ln in r.stdout.splitlines() if ln.strip()
            )
    return out


def main(argv: "list[str] | None" = None) -> int:
    # contract checks must not require an accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from . import REPO_ROOT, RULES, run_all_timed

    ap = argparse.ArgumentParser(
        prog="python -m minio_tpu.analysis",
        description="minio-tpu project-native static analysis "
        "(hot-path lint, ABI contracts, kernel contracts, lock-order "
        "audit, interprocedural device-dataflow, resource-lifecycle "
        "must-release)",
        epilog="directories named "
        + ", ".join(EXCLUDED_DIR_NAMES)
        + " are always excluded from file-walking passes",
    )
    ap.add_argument(
        "--paths",
        nargs="*",
        default=None,
        help="repo-relative files/dirs to lint (default: minio_tpu/); "
        "contract, abi and lock passes are tree-global regardless",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="emit a JSON object: stable-sorted findings, per-pass "
        "wall-time seconds, and call-graph stats (diffable)",
    )
    ap.add_argument(
        "--skip",
        nargs="*",
        default=[],
        choices=list(PASSES),
        help="passes to skip",
    )
    ap.add_argument(
        "--changed-only",
        action="store_true",
        help="analyze only what the working tree changes vs HEAD "
        "(lint: changed .py files; tree-global passes: run only when "
        "a file they audit changed)",
    )
    ap.add_argument(
        "--list-rules",
        action="store_true",
        help="print the MTPU rule catalog and exit",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    skip = set(args.skip)
    paths = args.paths
    deviceflow_restrict = None
    suffix = ""
    if args.changed_only:
        suffix = ", changed-only"
        changed = _changed_files(REPO_ROOT)
        lint_paths = sorted(
            p
            for p in changed
            if p.endswith(".py") and p.startswith("minio_tpu/")
        )
        if lint_paths:
            paths = lint_paths
        else:
            skip.add("lint")
        for pass_name, prefixes in PASS_TRIGGER_PREFIXES.items():
            if not any(p.startswith(prefixes) for p in changed):
                skip.add(pass_name)
        if lint_paths:
            # deviceflow/lifecycle findings are interprocedural:
            # analyze the whole tree, report for the changed files PLUS
            # everything that transitively calls into them (prefix
            # gating would silently skip a caller whose callee just
            # changed); both passes share this one closure
            deviceflow_restrict = _reverse_closure(set(lint_paths))
        else:
            skip.add("deviceflow")
            skip.add("lifecycle")

    findings, pass_seconds, callgraph_stats = run_all_timed(
        paths=paths,
        skip=skip,
        deviceflow_restrict=deviceflow_restrict,
    )

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in findings],
                    "passes": pass_seconds,
                    "callgraph": callgraph_stats,
                },
                indent=2,
                sort_keys=True,
            )
        )
    else:
        for f in findings:
            print(f.render())
        ran = [p for p in PASSES if p not in skip]
        print(
            f"minio_tpu.analysis: {len(findings)} finding(s) "
            f"[{', '.join(ran) or 'nothing to run'}{suffix}]",
            file=sys.stderr,
        )
    return 1 if findings else 0


def _reverse_closure(changed: "set[str]") -> "set[str]":
    """Changed files plus every file that transitively calls into them,
    over the whole-tree call graph (the sound --changed-only set for
    the interprocedural pass)."""
    from . import iter_py_files
    from .astcache import CACHE
    from .callgraph import build

    graph = build(CACHE.load(iter_py_files()))
    return graph.reverse_file_closure(changed)


if __name__ == "__main__":
    sys.exit(main())
