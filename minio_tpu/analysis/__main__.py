"""CLI: ``python -m minio_tpu.analysis [--paths ...] [--json] [--skip ...]``.

Exit status 0 when the tree is clean, 1 when any finding survives noqa
filtering — the same contract tier-1 enforces through
tests/test_analysis.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv: "list[str] | None" = None) -> int:
    # contract checks must not require an accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from . import RULES, run_all

    ap = argparse.ArgumentParser(
        prog="python -m minio_tpu.analysis",
        description="minio-tpu project-native static analysis "
        "(hot-path lint, kernel contracts, lock-order audit)",
    )
    ap.add_argument(
        "--paths",
        nargs="*",
        default=None,
        help="repo-relative files/dirs to lint (default: minio_tpu/); "
        "contract and lock passes are tree-global regardless",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="emit findings as a stable-sorted JSON array (diffable)",
    )
    ap.add_argument(
        "--skip",
        nargs="*",
        default=[],
        choices=["lint", "contracts", "locks"],
        help="passes to skip",
    )
    ap.add_argument(
        "--list-rules",
        action="store_true",
        help="print the MTPU rule catalog and exit",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    findings = run_all(paths=args.paths, skip=set(args.skip))

    if args.json:
        print(
            json.dumps(
                [f.to_dict() for f in findings], indent=2, sort_keys=True
            )
        )
    else:
        for f in findings:
            print(f.render())
        ran = [
            p
            for p in ("lint", "contracts", "locks")
            if p not in set(args.skip)
        ]
        print(
            f"minio_tpu.analysis: {len(findings)} finding(s) "
            f"[{', '.join(ran)}]",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
