"""Hot-path lint: AST rules encoding this codebase's device invariants.

Go's toolchain keeps the reference honest (vet, staticcheck); these
rules are the Python/JAX equivalents for the invariants that actually
bite *this* tree:

* MTPU101 - host-device syncs (``block_until_ready``, ``jax.device_get``,
  ``.item()``) are forbidden inside jit-traced functions anywhere, and
  anywhere at all in the device-only modules (``minio_tpu/ops/``,
  ``minio_tpu/codec/``) outside whitelisted host boundaries (functions
  named ``host_*``).  ``np.asarray``/``np.array``/``np.ascontiguousarray``
  on a *traced* value (a jit parameter not routed through
  ``static_argnames``) is the same sync in disguise and is flagged inside
  jit bodies; on static parameters it happens at trace time and is fine.
* MTPU102 - retrace bombs: a ``jax.jit`` function taking a plain-Python
  parameter (``int``/``str``/``bool``/``bytes``/``float``/``tuple``
  annotation) that is not listed in ``static_argnames``/``static_argnums``
  recompiles on every distinct value while hashing it as a tracer.
* MTPU103 - ``except Exception/BaseException``/bare ``except`` whose body
  is only ``pass``: the silently-dead-path generator (PR 1's mesh encode
  path died exactly this way).
* MTPU104/105 - Prometheus registration conventions at the
  ``server/metrics.py`` emit sites: ``miniotpu_`` prefix, lowercase
  names, ``_total`` suffix on counters, ``[a-z_][a-z0-9_]*`` label keys.

Suppress a deliberate exception with ``# noqa: MTPU###`` on the
offending line (see analysis/findings.py).
"""

from __future__ import annotations

import ast
import re

from .findings import Finding

# modules whose whole body is device-kernel territory: any host sync is
# a hot-path stall, not just ones inside jit
DEVICE_ONLY_PREFIXES = ("minio_tpu/ops/", "minio_tpu/codec/")

# host-boundary functions exempt from the device-module sweep
_HOST_BOUNDARY_RE = re.compile(r"^(host_|_host)|_host$")

_SYNC_ATTRS = {"block_until_ready", "item"}
_NP_MATERIALIZE = {"asarray", "array", "ascontiguousarray", "frombuffer"}
_SCALAR_ANNOTATIONS = {"int", "str", "bool", "bytes", "float", "tuple", "Tuple"}

# MTPU107: eager readback of device parity outputs.  Parity produced on
# device must cross D2H only through the sanctioned seams (encode_end /
# the ParityRef drain path) — an np.asarray/np.array/jax.device_get of
# a parity value anywhere else in the kernel modules or the backend
# re-introduces the eager round-trip the digest-only PUT removed.
_PARITY_SCOPE_PREFIXES = ("minio_tpu/ops/",)
_PARITY_SCOPE_FILES = ("minio_tpu/codec/backend.py",)
_PARITY_SEAM_RE = re.compile(r"(_end$|drain)")

# MTPU110: object-data mutations must flow through the read-cache
# invalidation seam.  Any function in the erasure object layer that
# renames a generation in, deletes a version, or deletes object data
# files leaves stale digest-verified groups in the tiered read cache
# (local AND on peers) unless it also calls the invalidation seam.
# Staging mutations on SYS_VOL (tmp uploads, probe files) touch no
# committed object data and are exempt.
_MUTATION_SCOPE_FILES = (
    "minio_tpu/objectlayer/erasure_object.py",
    "minio_tpu/objectlayer/erasure_multipart.py",
)
_MUTATION_ATTRS = {"rename_data", "delete_version"}
# mutations whose first argument names the volume: staging writes to
# SYS_VOL are exempt, anything on a real bucket is a mutation (the
# metadata writers joined when the FileInfo side-car landed — stale
# xl.meta is as much a cache bug as stale shard groups)
_MUTATION_VOL_ATTRS = {"delete_file", "write_metadata", "update_metadata"}

# MTPU109: hand-written PartitionSpec literals.  parallel/rules.py is
# the single source of truth for shardings (pattern -> PartitionSpec,
# fingerprinted into the compile-seam cache key); a spec literal
# anywhere else in the mesh/ops layers silently forks that truth.
_SPEC_SCOPE_PREFIXES = ("minio_tpu/parallel/", "minio_tpu/ops/")
_SPEC_EXEMPT_FILES = ("minio_tpu/parallel/rules.py",)
_SPEC_CTORS = {"PartitionSpec", "P", "PS"}

_METRIC_NAME_RE = re.compile(r"^miniotpu_[a-z0-9_]+$")
_LABEL_KEY_RE = re.compile(r"^[a-z_][a-z0-9_]*$")
_METRIC_TYPES = {"counter", "gauge", "histogram"}

# MTPU111: S3-Select result drain.  The device scan pipeline keeps the
# object plane and every flag/count word device-resident; candidate row
# bytes are the ONLY payload that crosses D2H, and only through the
# drain seam functions in s3select/device.py (``_drain_scalars`` /
# ``_drain_array`` / ``_drain_fallback_chunk`` / ``drain_plane`` — any
# function whose name contains "drain").  An eager np.asarray/np.array/
# jax.device_get anywhere else in that module re-introduces a
# whole-plane readback and silently turns the pushdown into a host
# scan.  np.frombuffer is exempt: device.py uses it on host bytes.
_SELECT_SCOPE_FILES = ("minio_tpu/s3select/device.py",)
_SELECT_SEAM_RE = re.compile(r"drain")

# MTPU108: event-loop-blocking calls inside ``async def`` bodies of the
# server plane.  One stalled coroutine stalls every connection on the
# loop; blocking work belongs on the worker-pool bridge (server/aio.py
# _LoopReader/_LoopWriter run blocking calls in *sync* defs on worker
# threads, which this rule deliberately does not see).  Awaited calls
# are exempt — ``await ev.wait()`` on an asyncio primitive is the
# non-blocking form — as are coroutine factories passed directly to an
# awaited ``asyncio.*`` wrapper (``await asyncio.wait_for(ev.wait(),``).
_LOOP_SCOPE_PREFIXES = ("minio_tpu/server/",)
_LOOP_BLOCK_SLEEPS = {"time.sleep", "_time.sleep"}
_LOOP_SOCKET_ATTRS = {"recv", "recv_into", "sendall", "sendto", "recvfrom"}


def _dotted(node: ast.AST) -> "str | None":
    """'jax.device_get' for Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _root_name(node: ast.AST) -> "str | None":
    """The base Name of an expression like ``x``, ``x[i]``, ``x.attr``."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _jit_decorator(dec: ast.AST) -> "tuple[bool, set, set] | None":
    """(is_jit, static_argnames, static_argnums) for one decorator.

    Recognizes ``@jax.jit``, ``@jit``, ``@jax.jit(...)`` and
    ``@functools.partial(jax.jit, ...)`` / ``@partial(jax.jit, ...)``.
    """
    names: "set[str]" = set()
    nums: "set[int]" = set()
    target = dec
    keywords: "list[ast.keyword]" = []
    if isinstance(dec, ast.Call):
        fn = _dotted(dec.func)
        if fn in ("functools.partial", "partial") and dec.args:
            target = dec.args[0]
            keywords = dec.keywords
        else:
            target = dec.func
            keywords = dec.keywords
    d = _dotted(target)
    if d not in ("jax.jit", "jit"):
        return None
    for kw in keywords:
        if kw.arg == "static_argnames":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    names.add(c.value)
        elif kw.arg == "static_argnums":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, int):
                    nums.add(c.value)
    return True, names, nums


def _annotation_token(ann: "ast.AST | None") -> "str | None":
    """Leading identifier of an annotation: int, tuple, np, ..."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        m = re.match(r"\s*([A-Za-z_][A-Za-z0-9_]*)", ann.value)
        return m.group(1) if m else None
    if isinstance(ann, ast.Subscript):
        ann = ann.value
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Attribute):
        return _dotted(ann)
    return None


def _only_pass(body: "list[ast.stmt]") -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis
        ):
            continue
        return False
    return True


class _Linter(ast.NodeVisitor):
    def __init__(self, rel_path: str):
        self.rel_path = rel_path
        self.device_module = rel_path.startswith(DEVICE_ONLY_PREFIXES)
        self.parity_scope = (
            rel_path.startswith(_PARITY_SCOPE_PREFIXES)
            or rel_path in _PARITY_SCOPE_FILES
        )
        self.loop_scope = rel_path.startswith(_LOOP_SCOPE_PREFIXES)
        self.select_scope = rel_path in _SELECT_SCOPE_FILES
        self.spec_scope = (
            rel_path.startswith(_SPEC_SCOPE_PREFIXES)
            and rel_path not in _SPEC_EXEMPT_FILES
        )
        self.mutation_scope = rel_path in _MUTATION_SCOPE_FILES
        self.findings: "list[Finding]" = []
        # stack of (func_name, jit_static_names or None)
        self._funcs: "list[tuple[str, set | None]]" = []
        # parallel stack: is the enclosing def async? (MTPU108 keys on
        # the INNERMOST def — a sync closure inside an async def runs
        # on whatever thread calls it, not on the loop)
        self._async_stack: "list[bool]" = []
        # Call nodes that are awaited (directly, or as a coroutine
        # argument to an awaited asyncio.* wrapper)
        self._awaited: "set[int]" = set()

    # -- helpers ----------------------------------------------------------

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(rule, self.rel_path, getattr(node, "lineno", 1), message)
        )

    def _in_jit(self) -> "set | None":
        for _name, static in reversed(self._funcs):
            if static is not None:
                return static
        return None

    def _in_host_boundary(self) -> bool:
        return any(
            _HOST_BOUNDARY_RE.search(name) for name, _ in self._funcs
        )

    # -- function defs: jit detection + MTPU102 ---------------------------

    def _visit_func(self, node):
        static: "set | None" = None
        for dec in node.decorator_list:
            parsed = _jit_decorator(dec)
            if parsed is None:
                continue
            _, names, nums = parsed
            params = [
                a.arg
                for a in node.args.posonlyargs + node.args.args
            ]
            static = set(names)
            for i in nums:
                if i < len(params):
                    static.add(params[i])
            self._check_retrace(node, static)
            break
        if self.mutation_scope:
            self._check_mutation_invalidate(node)
        self._funcs.append((node.name, static))
        self._async_stack.append(isinstance(node, ast.AsyncFunctionDef))
        self.generic_visit(node)
        self._async_stack.pop()
        self._funcs.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Await(self, node: ast.Await) -> None:
        v = node.value
        if isinstance(v, ast.Call):
            self._awaited.add(id(v))
            if (_dotted(v.func) or "").startswith("asyncio."):
                for a in list(v.args) + [kw.value for kw in v.keywords]:
                    if isinstance(a, ast.Call):
                        self._awaited.add(id(a))
        self.generic_visit(node)

    def _check_mutation_invalidate(self, node) -> None:
        """MTPU110: object-data mutation outside the invalidation seam.

        Each def is analyzed on its OWN body: nested defs are skipped
        (they are visited — and judged — separately), while lambdas
        stay attached to the enclosing def (_put_object stages its
        rename_data inside retry lambdas).  A mutation is rename_data/
        delete_version anywhere, or delete_file/write_metadata/
        update_metadata on a volume that is not the SYS_VOL staging
        area (metadata writers count: the FileInfo side-car caches
        xl.meta too); the seam is any call whose name contains
        "invalidate".
        """
        mutations: "list[tuple[str, ast.Call]]" = []
        has_seam = False
        stack = list(ast.iter_child_nodes(node))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(n, ast.Call):
                fn = n.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else ""
                )
                if "invalidate" in name.lower():
                    has_seam = True
                elif name in _MUTATION_ATTRS:
                    mutations.append((name, n))
                elif name in _MUTATION_VOL_ATTRS and n.args:
                    first = n.args[0]
                    if not (
                        isinstance(first, ast.Name)
                        and first.id == "SYS_VOL"
                    ):
                        mutations.append((name, n))
            stack.extend(ast.iter_child_nodes(n))
        if has_seam:
            return
        for name, call in mutations:
            self._emit(
                "MTPU110",
                call,
                f"{name}(...) mutates committed object data but "
                f"{node.name!r} never calls the read-cache invalidation "
                "seam; call self._invalidate_read_cache(bucket, object) "
                "(cache.invalidate_object) so local and peer cached "
                "groups are dropped before the mutation is acked",
            )

    def _check_retrace(self, node, static: "set[str]") -> None:
        args = node.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if a.arg in ("self", "cls") or a.arg in static:
                continue
            token = _annotation_token(a.annotation)
            if token in _SCALAR_ANNOTATIONS:
                self._emit(
                    "MTPU102",
                    a,
                    f"jit function {node.name!r} takes Python-{token} "
                    f"parameter {a.arg!r} outside static_argnames: every "
                    "distinct value retraces and recompiles",
                )

    # -- calls: MTPU101 + metric conventions ------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        self._check_sync(node)
        self._check_parity_readback(node)
        self._check_select_readback(node)
        self._check_partition_literal(node)
        self._check_metric_emit(node)
        self._check_loop_block(node)
        self.generic_visit(node)

    def _check_partition_literal(self, node: ast.Call) -> None:
        """MTPU109: PartitionSpec literal outside parallel/rules.py."""
        if not self.spec_scope:
            return
        fn = node.func
        last = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if last not in _SPEC_CTORS:
            return
        self._emit(
            "MTPU109",
            node,
            f"hand-written {last}(...) sharding literal outside "
            "parallel/rules.py; name the plane and resolve it through "
            "rules.spec_for so the partition-rule table stays the "
            "single source of truth",
        )

    def _check_loop_block(self, node: ast.Call) -> None:
        """MTPU108: blocking call on the event-loop thread."""
        if not self.loop_scope:
            return
        if not self._async_stack or not self._async_stack[-1]:
            return
        if id(node) in self._awaited:
            return
        dotted = _dotted(node.func) or ""
        if dotted in _LOOP_BLOCK_SLEEPS:
            self._emit(
                "MTPU108",
                node,
                f"{dotted}() blocks the event loop inside an async def; "
                "use `await asyncio.sleep(...)` or move the work to the "
                "worker-pool bridge",
            )
            return
        if not isinstance(node.func, ast.Attribute):
            return
        attr = node.func.attr
        if attr in _LOOP_SOCKET_ATTRS:
            self._emit(
                "MTPU108",
                node,
                f".{attr}() is a raw blocking socket call inside an "
                "async def; use the connection's StreamReader/"
                "StreamWriter on the loop",
            )
        elif attr == "result":
            self._emit(
                "MTPU108",
                node,
                ".result() blocks the event loop waiting on a future "
                "inside an async def; await it (or bridge through "
                "loop.run_in_executor)",
            )
        elif attr == "wait" and not dotted.startswith("asyncio."):
            self._emit(
                "MTPU108",
                node,
                f"{dotted or '.' + attr}() without await blocks the "
                "event loop inside an async def (a threading.Event-"
                "style wait, or an asyncio coroutine that never runs); "
                "await an asyncio primitive instead",
            )

    def _check_parity_readback(self, node: ast.Call) -> None:
        """MTPU107: eager parity D2H outside the *_end/drain seams."""
        if not self.parity_scope or not node.args:
            return
        if self._in_host_boundary() or any(
            _PARITY_SEAM_RE.search(name) for name, _ in self._funcs
        ):
            return
        dotted = _dotted(node.func) or ""
        attr = (
            node.func.attr
            if isinstance(node.func, ast.Attribute)
            else dotted
        )
        eager = dotted in ("jax.device_get", "device_get") or (
            dotted.startswith(("np.", "numpy."))
            and attr in ("asarray", "array")
        )
        if not eager:
            return
        root = _root_name(node.args[0])
        if root is None or not (root == "par" or "parity" in root):
            return
        self._emit(
            "MTPU107",
            node,
            f"{dotted}({root}...) eagerly reads device parity back to "
            "host outside the *_end/drain seams; keep the plane "
            "device-resident and route readback through the backend's "
            "digest-only drain",
        )

    def _check_select_readback(self, node: ast.Call) -> None:
        """MTPU111: eager D2H outside the select result-drain seam."""
        if not self.select_scope or not node.args:
            return
        if any(_SELECT_SEAM_RE.search(name) for name, _ in self._funcs):
            return
        dotted = _dotted(node.func) or ""
        attr = (
            node.func.attr
            if isinstance(node.func, ast.Attribute)
            else dotted
        )
        eager = dotted in ("jax.device_get", "device_get") or (
            dotted.startswith(("np.", "numpy."))
            and attr in ("asarray", "array")
        )
        if not eager:
            return
        root = _root_name(node.args[0]) or "<expr>"
        self._emit(
            "MTPU111",
            node,
            f"{dotted}({root}...) reads device data back to host "
            "outside the result-drain seam; only candidate row bytes "
            "may cross D2H, through the drain functions in "
            "s3select/device.py",
        )

    def _check_sync(self, node: ast.Call) -> None:
        static = self._in_jit()
        in_jit = static is not None
        device_scope = (
            self.device_module and not self._in_host_boundary()
        )
        if not in_jit and not device_scope:
            return
        where = (
            "inside jit-traced code" if in_jit else "in a device-only module"
        )
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _SYNC_ATTRS and not node.args:
                self._emit(
                    "MTPU101",
                    node,
                    f".{attr}() is a host-device sync {where}; move it "
                    "to the host boundary",
                )
                return
            dotted = _dotted(node.func)
            if dotted in ("jax.device_get", "jax.device_put_replicated"):
                self._emit(
                    "MTPU101",
                    node,
                    f"{dotted} is a host-device sync {where}; move it to "
                    "the host boundary",
                )
                return
            if (
                in_jit
                and dotted is not None
                and dotted.startswith(("np.", "numpy."))
                and attr in _NP_MATERIALIZE
                and node.args
            ):
                root = _root_name(node.args[0])
                if root is not None and root not in static:
                    top = self._funcs[-1][0] if self._funcs else "<module>"
                    self._emit(
                        "MTPU101",
                        node,
                        f"np.{attr}({root}...) inside jit function "
                        f"{top!r} materializes a traced value on host "
                        "(sync + constant-folding trap); use jnp or mark "
                        f"{root!r} static",
                    )
        elif isinstance(node.func, ast.Name):
            if node.func.id == "device_get":
                self._emit(
                    "MTPU101",
                    node,
                    f"device_get is a host-device sync {where}",
                )

    def _check_metric_emit(self, node: ast.Call) -> None:
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name not in ("emit", "emit_histogram"):
            return
        if not node.args or not (
            isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return
        metric = node.args[0].value
        if name == "emit":
            if len(node.args) < 2 or not (
                isinstance(node.args[1], ast.Constant)
                and node.args[1].value in _METRIC_TYPES
            ):
                return  # not a registration-shaped call
            mtype = node.args[1].value
        else:
            mtype = "histogram"
        if not _METRIC_NAME_RE.match(metric):
            self._emit(
                "MTPU104",
                node,
                f"metric {metric!r} violates naming: must match "
                "miniotpu_[a-z0-9_]+",
            )
        elif mtype == "counter" and not metric.endswith("_total"):
            self._emit(
                "MTPU104",
                node,
                f"counter {metric!r} must end in _total "
                "(prometheus counter convention)",
            )
        elif mtype == "histogram" and metric.endswith(
            ("_total", "_count", "_sum", "_bucket")
        ):
            self._emit(
                "MTPU104",
                node,
                f"histogram {metric!r} must not end in a reserved "
                "series suffix (_total/_count/_sum/_bucket)",
            )
        # label-key hygiene: every dict literal key in the sample args
        for arg in node.args[2:] + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if not isinstance(sub, ast.Dict):
                    continue
                for k in sub.keys:
                    if (
                        isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and not _LABEL_KEY_RE.match(k.value)
                    ):
                        self._emit(
                            "MTPU105",
                            k,
                            f"label key {k.value!r} of {metric!r} must "
                            "match [a-z_][a-z0-9_]*",
                        )
        if name == "emit_histogram" and len(node.args) >= 4:
            lab = node.args[3]
            if (
                isinstance(lab, ast.Constant)
                and isinstance(lab.value, str)
                and not _LABEL_KEY_RE.match(lab.value)
            ):
                self._emit(
                    "MTPU105",
                    lab,
                    f"label key {lab.value!r} of {metric!r} must match "
                    "[a-z_][a-z0-9_]*",
                )

    # -- MTPU103 ----------------------------------------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if self._swallows_broadly(node.type) and _only_pass(node.body):
            caught = (
                "bare except" if node.type is None
                else f"except {_dotted(node.type) or '...'}"
            )
            self._emit(
                "MTPU103",
                node,
                f"{caught}: pass silently swallows failures; narrow the "
                "exception, log it, or count it",
            )
        self.generic_visit(node)

    @staticmethod
    def _swallows_broadly(t: "ast.AST | None") -> bool:
        if t is None:
            return True
        if isinstance(t, ast.Tuple):
            return any(_Linter._swallows_broadly(e) for e in t.elts)
        return _dotted(t) in ("Exception", "BaseException")


def lint_source(
    rel_path: str, text: str, tree: "ast.Module | None" = None
) -> "list[Finding]":
    """Lint one file's source; returns findings BEFORE noqa filtering.

    ``tree`` lets callers hand in an already-parsed module (the shared
    AST cache) so a five-pass run parses each file exactly once.
    """
    if tree is None:
        try:
            tree = ast.parse(text, filename=rel_path)
        except SyntaxError as e:
            return [
                Finding(
                    "MTPU100",
                    rel_path,
                    e.lineno or 1,
                    f"syntax error: {e.msg}",
                )
            ]
    linter = _Linter(rel_path)
    linter.visit(tree)
    return linter.findings
