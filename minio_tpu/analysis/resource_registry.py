"""Declarative acquire/release/transfer registry for the lifecycle pass.

The reference MinIO keeps resource discipline honest with ``defer`` and
the race detector; this registry is the Python tree's substitute: every
manually-paired resource class is named here — who acquires it, who
releases it, which seams take ownership — and ``lifecycle.py`` proves
the pairing over the PR 17 call graph (MTPU601-605).  A paired API that
is NOT registered is itself a finding (MTPU605), so the registry cannot
rot behind the code.

Matching model (all matching is syntactic, scoped by ``scope`` path
prefixes; the call graph supplies interprocedural release credit and
the MTPU605 resolution check):

* ``acquire_calls`` / ``release_calls`` / ``transfer_calls`` name call
  sites.  A plain name matches the called function/attribute name; a
  dotted ``"recv.name"`` form additionally requires the receiver's
  trailing attribute (``"s3.release"`` matches ``self.s3.release()``
  but not ``lock.release()``).
* ``conditional=True`` marks try-style acquires: the resource is held
  only when the call returns truthy (``if not try_enter(t): return``
  refines the obligation away on the shed branch).
* ``handle=True`` marks acquires whose return value IS the resource
  (staging reservation, io-future, parity ref).  Release is the
  handle flowing into a ``release_calls`` function or one of
  ``release_methods`` invoked on it; returning/storing/passing the
  handle transfers ownership out of the local frame.
* ``acquire_attr_ops`` / ``release_attr_ops`` register primitive
  mutations — ``("_res", "append")`` matches ``self._res.append(...)``
  (and simple local aliases of ``self._res``) — for the counters whose
  bodies implement a seam (TokenCounter).
* ``acquire_kwarg`` restricts an acquire to calls carrying that
  keyword (``FaultDisk.inject`` only parks a hang when ``hang_s`` is
  passed).
* ``defs`` pins each registered function to its defining module so the
  MTPU605 drift check (and the introspection-closure test) can demand
  that every entry resolves to a call-graph node.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ResourceClass:
    """One manually-paired resource: how it is acquired, released,
    and handed off, and where the pairing is enforced."""

    name: str
    scope: "tuple[str, ...]"
    acquire_calls: "tuple[str, ...]" = ()
    release_calls: "tuple[str, ...]" = ()
    transfer_calls: "tuple[str, ...]" = ()
    release_methods: "tuple[str, ...]" = ()
    acquire_attr_ops: "tuple[tuple[str, str], ...]" = ()
    release_attr_ops: "tuple[tuple[str, str], ...]" = ()
    acquire_kwarg: "str | None" = None
    conditional: bool = False
    handle: bool = False
    defs: "tuple[tuple[str, str], ...]" = ()

    def in_scope(self, rel_path: str) -> bool:
        return rel_path.startswith(self.scope)


@dataclasses.dataclass(frozen=True)
class Registry:
    """The resource table the lifecycle pass interprets."""

    resources: "tuple[ResourceClass, ...]"

    def scoped(self, rel_path: str) -> "tuple[ResourceClass, ...]":
        return tuple(r for r in self.resources if r.in_scope(rel_path))

    @staticmethod
    def default() -> "Registry":
        return Registry(resources=_DEFAULT_RESOURCES)


_DEFAULT_RESOURCES: "tuple[ResourceClass, ...]" = (
    # Device-budget staging ledger (codec/backend.py): _stage_reserve
    # returns the byte count that _stage_release must give back; the
    # reservation may instead ride into an _AsyncHandle payload, whose
    # *_end drain releases it on the device side.
    ResourceClass(
        name="staging-ledger",
        scope=("minio_tpu/codec/backend.py",),
        acquire_calls=("_stage_reserve",),
        release_calls=("_stage_release",),
        transfer_calls=("_AsyncHandle",),
        handle=True,
        defs=(
            ("minio_tpu/codec/backend.py", "_stage_reserve"),
            ("minio_tpu/codec/backend.py", "_stage_release"),
        ),
    ),
    # Admission tokens (server/): the AdmissionController seams and
    # the TokenCounter reserve/undo primitives they are built from.
    # try_* acquires hold only on a truthy return; a seam returning
    # True hands its internal reservation to the caller.
    ResourceClass(
        name="admission-token",
        scope=("minio_tpu/server/",),
        acquire_calls=(
            "try_enter_tenant",
            "try_enter_select",
            "try_acquire",
        ),
        release_calls=("leave_tenant", "leave_select"),
        acquire_attr_ops=(("_res", "append"), ("_adm", "append")),
        release_attr_ops=(("_res", "pop"), ("_adm", "pop")),
        conditional=True,
        defs=(
            ("minio_tpu/server/admission.py", "AdmissionController.try_enter_tenant"),
            ("minio_tpu/server/admission.py", "AdmissionController.leave_tenant"),
            ("minio_tpu/server/admission.py", "AdmissionController.try_enter_select"),
            ("minio_tpu/server/admission.py", "AdmissionController.leave_select"),
            ("minio_tpu/server/admission.py", "TokenCounter.try_acquire"),
            ("minio_tpu/server/admission.py", "TokenCounter.release"),
        ),
    ),
    # Per-plane inflight gauges (PlaneStats/LoopStats enter/leave):
    # unconditional counters that must stay exactly paired or the
    # shed decisions read a phantom load forever.
    ResourceClass(
        name="plane-inflight",
        scope=("minio_tpu/server/",),
        acquire_calls=("enter",),
        release_calls=("leave",),
        defs=(
            ("minio_tpu/server/admission.py", "PlaneStats.enter"),
            ("minio_tpu/server/admission.py", "PlaneStats.leave"),
            ("minio_tpu/server/admission.py", "LoopStats.enter"),
            ("minio_tpu/server/admission.py", "LoopStats.leave"),
        ),
    ),
    # Threaded-server request slot (S3Server.admit/release): the
    # receiver-qualified form keeps "release" from colliding with the
    # other release verbs that live under server/.
    ResourceClass(
        name="server-slot",
        scope=("minio_tpu/server/http.py",),
        acquire_calls=("s3.admit",),
        release_calls=("s3.release",),
        conditional=True,
        defs=(
            ("minio_tpu/server/http.py", "S3Server.admit"),
            ("minio_tpu/server/http.py", "S3Server.release"),
        ),
    ),
    # Parity-plane cache refs (codec/backend.py): constructing a ref
    # admits it to the ParityPlaneCache; it must be drained, released,
    # or handed to an owner before the frame exits.
    ResourceClass(
        name="parity-ref",
        scope=("minio_tpu/codec/backend.py",),
        acquire_calls=(
            "_EagerParityRef",
            "_DeviceParityRef",
            "_SubchunkParityRef",
        ),
        release_methods=("release", "drain"),
        handle=True,
        defs=(
            ("minio_tpu/codec/backend.py", "_EagerParityRef.release"),
            ("minio_tpu/codec/backend.py", "_DeviceParityRef.release"),
            ("minio_tpu/codec/backend.py", "_DeviceParityRef.drain"),
            ("minio_tpu/codec/backend.py", "_SubchunkParityRef.drain"),
        ),
    ),
    # IO-pool futures: a granted slot's future must be waited,
    # abandoned (hedged losers), or adopted by a band/flusher; a
    # dropped future strands its queue slot accounting.
    ResourceClass(
        name="io-future",
        scope=("minio_tpu/parallel/", "minio_tpu/codec/erasure.py"),
        acquire_calls=("submit", "submit_hedged"),
        transfer_calls=("adopt", "add_done_callback"),
        release_methods=("wait", "result_or_raise", "abandon", "settle"),
        handle=True,
        defs=(
            ("minio_tpu/parallel/iopool.py", "IOPool.submit"),
            ("minio_tpu/parallel/iopool.py", "IOPool.submit_hedged"),
            ("minio_tpu/parallel/iopool.py", "IOFuture.wait"),
            ("minio_tpu/parallel/iopool.py", "IOFuture.result_or_raise"),
            ("minio_tpu/parallel/iopool.py", "IOFuture.abandon"),
            ("minio_tpu/parallel/iopool.py", "ParityBand.adopt"),
        ),
    ),
    # Namespace / dsync locks: timeout'd bool acquires with explicit
    # release verbs (the context managers in namespace.py are built on
    # these and are themselves checked here).
    ResourceClass(
        name="rw-lock",
        scope=("minio_tpu/dsync/",),
        acquire_calls=(
            "acquire_read",
            "acquire_write",
            "get_lock",
            "get_rlock",
        ),
        release_calls=(
            "release_read",
            "release_write",
            "unlock",
            "runlock",
        ),
        conditional=True,
        defs=(
            ("minio_tpu/dsync/namespace.py", "_RWLock.acquire_read"),
            ("minio_tpu/dsync/namespace.py", "_RWLock.release_read"),
            ("minio_tpu/dsync/namespace.py", "_RWLock.acquire_write"),
            ("minio_tpu/dsync/namespace.py", "_RWLock.release_write"),
            ("minio_tpu/dsync/drwmutex.py", "DRWMutex.get_lock"),
            ("minio_tpu/dsync/drwmutex.py", "DRWMutex.unlock"),
            ("minio_tpu/dsync/drwmutex.py", "DRWMutex.get_rlock"),
            ("minio_tpu/dsync/drwmutex.py", "DRWMutex.runlock"),
        ),
    ),
    # FaultDisk parked hangs: inject(hang_s=...) parks worker threads
    # until clear(); a schedule that cannot be cleared wedges every
    # disk op behind it.
    ResourceClass(
        name="fault-hang",
        scope=(
            "minio_tpu/storage/faults.py",
            "minio_tpu/server/admin.py",
        ),
        acquire_calls=("inject",),
        release_calls=("clear",),
        acquire_kwarg="hang_s",
        defs=(
            ("minio_tpu/storage/faults.py", "FaultDisk.inject"),
            ("minio_tpu/storage/faults.py", "FaultDisk.clear"),
        ),
    ),
)


# Names that look like acquires: a def with one of these shapes inside
# a registered scope must itself be registered or MTPU605 fires (the
# other drift direction — code outrunning the registry).
ACQUIRE_SHAPED_PREFIXES = ("try_enter_", "try_acquire", "acquire_")
ACQUIRE_SHAPED_NAMES = ("reserve", "_stage_reserve", "admit")


def registered_call_names(registry: Registry) -> "set[str]":
    """Every bare function name the registry knows (drift whitelist)."""
    out: "set[str]" = set()
    for res in registry.resources:
        for group in (
            res.acquire_calls,
            res.release_calls,
            res.transfer_calls,
        ):
            for name in group:
                out.add(name.rsplit(".", 1)[-1])
        for _, qname in res.defs:
            out.add(qname.rsplit(".", 1)[-1])
    return out
