"""Shared, mtime-keyed AST cache for the file-walking passes.

The analyzer grew from one AST pass to three (hot-path lint, the
callgraph builder, and the deviceflow rules) — re-reading and
re-parsing the whole tree per pass triples the dominant cost of a lint
run for zero benefit.  ``python -m minio_tpu.analysis`` parses each
file ONCE through this cache and hands the parsed modules to every
pass; cache entries are keyed on ``(mtime_ns, size)`` so an edit
between passes (or between CLI runs inside one long-lived process,
e.g. the tier-1 test session) re-parses exactly the edited files.

Entries hold the raw text, the split lines (for noqa filtering), and
the parsed ``ast.Module`` — or ``None`` with the ``SyntaxError`` kept,
so every pass sees the same MTPU100-shaped truth for a broken file.
"""

from __future__ import annotations

import ast
import dataclasses
import os


@dataclasses.dataclass
class ParsedModule:
    """One cached source file: text + lines + AST (or the parse error)."""

    rel_path: str
    text: str
    lines: "list[str]"
    tree: "ast.Module | None"
    error: "SyntaxError | None" = None


class AstCache:
    def __init__(self):
        # rel_path -> ((mtime_ns, size), ParsedModule)
        self._entries: "dict[str, tuple[tuple[int, int], ParsedModule]]" = {}

    def _stamp(self, abs_path: str) -> "tuple[int, int]":
        st = os.stat(abs_path)
        return (st.st_mtime_ns, st.st_size)

    def get(self, rel_path: str) -> ParsedModule:
        """The parsed module for a repo-relative path, (re)parsed iff
        the file changed since the last call."""
        from . import REPO_ROOT

        abs_path = os.path.join(REPO_ROOT, rel_path)
        stamp = self._stamp(abs_path)
        hit = self._entries.get(rel_path)
        if hit is not None and hit[0] == stamp:
            return hit[1]
        with open(abs_path, encoding="utf-8") as fh:
            text = fh.read()
        parsed = parse_source(rel_path, text)
        self._entries[rel_path] = (stamp, parsed)
        return parsed

    def load(self, rel_paths: "list[str]") -> "dict[str, ParsedModule]":
        """Parsed modules for a file set, ordered like the input."""
        return {rel: self.get(rel) for rel in rel_paths}

    def invalidate(self) -> None:
        self._entries.clear()


def parse_source(rel_path: str, text: str) -> ParsedModule:
    """Parse source that is already in memory (fixtures, seeded
    canaries, mutated copies) into the same shape the cache serves."""
    lines = text.splitlines()
    try:
        tree = ast.parse(text, filename=rel_path)
    except SyntaxError as e:
        return ParsedModule(rel_path, text, lines, None, e)
    return ParsedModule(rel_path, text, lines, tree)


# process-wide cache: the CLI, run_lint and the deviceflow pass all
# share it, which is the whole point
CACHE = AstCache()
