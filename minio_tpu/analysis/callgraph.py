"""Module-qualified interprocedural call graph over the tree.

Every MTPU5xx rule is a whole-program fact ("this device value reaches
a D2H sink *through calls*"), so the deviceflow pass needs one shared
structure the per-file linters never had: who calls whom, across
modules, classes and thread boundaries.  This module builds it from
the shared AST cache:

* **nodes** — every ``def``/``async def`` (and named lambda) in the
  analyzed file set, qualified as ``rel/path.py::Class.method``
  (nested defs become ``outer.<locals>.inner``, the runtime
  ``__qualname__`` convention);
* **edges** — resolved call sites.  Resolution is deliberately
  conservative: module-qualified calls through the import table
  (absolute and relative imports), local and nested names,
  ``self.``/``cls.`` methods through the class index including bases
  defined in the tree, and a last-resort unique-method-name match that
  refuses common stdlib-shaped names.  An unresolvable call produces
  no edge — the dataflow rules under-approximate rather than guess;
* **boundary edges** — calls that move work onto another thread or
  onto the event loop: ``iopool.submit``/``submit_hedged``/
  ``ParityBand.submit``, the worker pool's ``try_submit``/
  ``spawn_stream``, executor ``submit``,
  ``asyncio.run_coroutine_threadsafe``, ``loop.run_in_executor``,
  ``loop.call_soon_threadsafe`` and ``threading.Thread(target=...)``.
  Each records which closure / function object crosses, so deviceflow
  can ask "does a device value ride along?" (MTPU503) and "does
  loop-reachability stop here?" (MTPU504).

Calls that resolve into a project module OUTSIDE the analyzed file set
(fixtures and canaries analyze one file, yet must still see the
``minio_tpu.ops`` entry points) resolve to a synthetic
``path::name`` callee with no node — exactly what the provenance
rules key on.
"""

from __future__ import annotations

import ast
import dataclasses
import time

from .astcache import ParsedModule

# thread/loop boundary call shapes; attr name -> boundary kind.
# "pool"/"executor"/"thread" move the closure OFF the calling thread
# onto a worker; "loop-bridge"/"loop-call" move it ONTO the event loop.
BOUNDARY_SUBMIT_ATTRS = {
    "submit": "pool",
    "submit_hedged": "pool",
    "try_submit": "pool",
    "spawn_stream": "pool",
    "run_in_executor": "executor",
    "call_soon_threadsafe": "loop-call",
}
_LOOP_BRIDGE_NAMES = {"run_coroutine_threadsafe"}

# boundary kinds whose closure still runs ON the event loop (MTPU504
# traverses these; the rest stop loop-reachability)
LOOP_RESIDENT_KINDS = frozenset({"loop-bridge", "loop-call"})

# unique-method-name resolution refuses these: too stdlib-shaped to
# trust a single tree definition (queue.get, fut.result, sock.send...)
_AMBIENT_METHOD_NAMES = frozenset(
    {
        "get", "put", "put_nowait", "get_nowait", "read", "write",
        "close", "open", "flush", "send", "recv", "result", "wait",
        "notify", "notify_all", "acquire", "release", "start", "stop",
        "join", "run", "submit", "cancel", "clear", "set", "add",
        "pop", "append", "extend", "remove", "discard", "update",
        "copy", "keys", "values", "items", "split", "strip", "encode",
        "decode", "format", "count", "index", "sort", "reverse",
        "readline", "seek", "tell", "drain", "connect", "bind",
        "listen", "accept", "shutdown", "item", "sum", "reshape",
        "render", "snapshot", "reset", "name", "loop", "fileno",
    }
)


@dataclasses.dataclass
class FuncInfo:
    """One graph node: a def somewhere in the analyzed file set."""

    qname: str
    rel_path: str
    name: str
    node: "ast.AST"
    is_async: bool
    cls: "str | None"
    lineno: int


@dataclasses.dataclass
class Edge:
    """One resolved (or boundary-recorded) call site."""

    caller: str
    callee: "str | None"
    rel_path: str
    line: int
    boundary: "str | None" = None
    text: str = ""


def module_dotted(rel_path: str) -> str:
    """'minio_tpu/ops/rs.py' -> 'minio_tpu.ops.rs'."""
    p = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def dotted_to_rel(dotted: str) -> str:
    """'minio_tpu.ops.rs' -> 'minio_tpu/ops/rs.py' (module form)."""
    return dotted.replace(".", "/") + ".py"


def _dotted_parts(node: ast.AST) -> "list[str] | None":
    parts: "list[str]" = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


class _ModuleFacts:
    """Per-module symbol tables the resolver consults."""

    def __init__(self, rel_path: str):
        self.rel_path = rel_path
        self.dotted = module_dotted(rel_path)
        self.is_package = rel_path.endswith("/__init__.py")
        # alias -> dotted target (module or module.symbol)
        self.imports: "dict[str, str]" = {}
        # top-level def name -> qname
        self.functions: "dict[str, str]" = {}
        # class name -> (base names, {method name -> qname})
        self.classes: "dict[str, tuple[list[str], dict[str, str]]]" = {}


# statement kinds whose nested blocks may hold defs worth indexing
_BLOCK_FIELDS = ("body", "orelse", "finalbody", "handlers")


def _child_blocks(stmt: ast.stmt) -> "list[list[ast.stmt]]":
    out = []
    for field in _BLOCK_FIELDS:
        val = getattr(stmt, field, None)
        if not val:
            continue
        if field == "handlers":
            out.extend(h.body for h in val)
        else:
            out.append(val)
    return out


class CallGraph:
    def __init__(self):
        self.funcs: "dict[str, FuncInfo]" = {}
        self.edges: "list[Edge]" = []
        # id(ast.Call) -> Edge, for the dataflow pass walking the same
        # cached trees
        self.call_info: "dict[int, Edge]" = {}
        self.modules: "dict[str, _ModuleFacts]" = {}
        # enclosing qname -> {nested def name -> qname}
        self.locals_of: "dict[str, dict[str, str]]" = {}
        # method name -> [qname, ...] across every class in the tree
        self._methods_by_name: "dict[str, list[str]]" = {}
        # class name -> [(rel_path, class name), ...]
        self._classes_by_name: "dict[str, list[tuple[str, str]]]" = {}
        self.build_seconds = 0.0

    # -- queries ----------------------------------------------------------

    def lookup(self, rel_path: str, name: str) -> "FuncInfo | None":
        """A def node by file + qualified name."""
        return self.funcs.get(f"{rel_path}::{name}")

    def resolve_short(self, short_mod: str, name: str) -> "FuncInfo | None":
        """Registry-style lookup via kernel_contracts short module name."""
        from .kernel_contracts import ENTRY_POINT_PATHS

        rel = ENTRY_POINT_PATHS.get(short_mod)
        if rel is None:
            return None
        return self.lookup(rel, name)

    def boundary_edges(self) -> "list[Edge]":
        return [e for e in self.edges if e.boundary is not None]

    def stats(self) -> dict:
        return {
            "nodes": len(self.funcs),
            "edges": len(self.edges),
            "boundary_edges": len(self.boundary_edges()),
            "seconds": round(self.build_seconds, 3),
        }

    def edges_from(self) -> "dict[str, list[Edge]]":
        out: "dict[str, list[Edge]]" = {}
        for e in self.edges:
            out.setdefault(e.caller, []).append(e)
        return out

    def reverse_file_closure(self, changed: "set[str]") -> "set[str]":
        """Changed files plus every file that (transitively) calls into
        them — the sound trigger set for --changed-only: a deep finding
        in a CALLER can appear or vanish when its callee is edited."""
        rev: "dict[str, set[str]]" = {}
        for e in self.edges:
            if e.callee is None or e.callee == "<multi>":
                continue
            callee_file = e.callee.split("::", 1)[0]
            if callee_file != e.rel_path:
                rev.setdefault(callee_file, set()).add(e.rel_path)
        out = set(changed)
        work = list(changed)
        while work:
            f = work.pop()
            for caller_file in rev.get(f, ()):
                if caller_file not in out:
                    out.add(caller_file)
                    work.append(caller_file)
        return out


# ---------------------------------------------------------------------------
# pass 1: symbol tables
# ---------------------------------------------------------------------------


def _collect_module_facts(graph: CallGraph, mod: ParsedModule) -> None:
    facts = _ModuleFacts(mod.rel_path)
    graph.modules[mod.rel_path] = facts
    if mod.tree is None:
        return
    pkg_parts = facts.dotted.split(".")

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    facts.imports[alias.asname] = alias.name
                else:
                    head = alias.name.split(".", 1)[0]
                    facts.imports[head] = head
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                # relative import: level 1 is the containing package
                # (the package itself for an __init__), each extra
                # level walks one parent up
                drop = node.level - (1 if facts.is_package else 0)
                base = pkg_parts[: len(pkg_parts) - drop]
                prefix = ".".join(
                    base + ([node.module] if node.module else [])
                )
            else:
                prefix = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                name = alias.asname or alias.name
                facts.imports[name] = (
                    f"{prefix}.{alias.name}" if prefix else alias.name
                )

    def add_func(node, qual, cls):
        qname = f"{mod.rel_path}::{qual}"
        graph.funcs[qname] = FuncInfo(
            qname=qname,
            rel_path=mod.rel_path,
            name=qual.rsplit(".", 1)[-1],
            node=node,
            is_async=isinstance(node, ast.AsyncFunctionDef),
            cls=cls,
            lineno=node.lineno,
        )
        return qname

    def register(node, name, func_qual, cls):
        """Index one def/lambda found in the current scope."""
        if func_qual is not None:
            qual = f"{func_qual}.<locals>.{name}"
            qname = add_func(node, qual, None)
            graph.locals_of.setdefault(
                f"{mod.rel_path}::{func_qual}", {}
            )[name] = qname
        elif cls is not None:
            qual = f"{cls}.{name}"
            qname = add_func(node, qual, cls)
            facts.classes[cls][1][name] = qname
            graph._methods_by_name.setdefault(name, []).append(qname)
        else:
            qual = name
            qname = add_func(node, qual, None)
            facts.functions[name] = qname
        return qual

    def walk_block(body, func_qual, cls):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = register(node, node.name, func_qual, cls)
                walk_block(node.body, qual, None)
            elif isinstance(node, ast.ClassDef):
                bases = []
                for b in node.bases:
                    parts = _dotted_parts(b)
                    if parts:
                        bases.append(parts[-1])
                facts.classes.setdefault(node.name, (bases, {}))
                graph._classes_by_name.setdefault(node.name, []).append(
                    (mod.rel_path, node.name)
                )
                walk_block(node.body, None, node.name)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Lambda
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        register(node.value, tgt.id, func_qual, cls)
            else:
                for block in _child_blocks(node):
                    walk_block(block, func_qual, cls)

    walk_block(mod.tree.body, None, None)


# ---------------------------------------------------------------------------
# pass 2: edges
# ---------------------------------------------------------------------------


class _Resolver:
    """Resolves call expressions inside one module."""

    def __init__(self, graph: CallGraph, facts: _ModuleFacts):
        self.graph = graph
        self.facts = facts

    def _resolve_symbol(self, dotted: str) -> "str | None":
        """'minio_tpu.ops.rs._encode_jit' -> qname; synthetic when the
        module lives outside the analyzed file set."""
        if "." not in dotted or not dotted.startswith("minio_tpu"):
            return None
        mod_dotted, sym = dotted.rsplit(".", 1)
        rel = dotted_to_rel(mod_dotted)
        mod_facts = self.graph.modules.get(rel)
        if mod_facts is None:
            pkg_rel = mod_dotted.replace(".", "/") + "/__init__.py"
            mod_facts = self.graph.modules.get(pkg_rel)
        if mod_facts is not None:
            return mod_facts.functions.get(sym)
        return f"{rel}::{sym}"

    def _resolve_in_class(self, cls_name, attr, seen=None) -> "str | None":
        seen = set() if seen is None else seen
        if cls_name in seen:
            return None
        seen.add(cls_name)
        for rel, cname in self.graph._classes_by_name.get(cls_name, []):
            facts = self.graph.modules.get(rel)
            if facts is None:
                continue
            bases, methods = facts.classes.get(cname, ([], {}))
            qn = methods.get(attr)
            if qn is not None:
                return qn
            for b in bases:
                qn = self._resolve_in_class(b, attr, seen)
                if qn is not None:
                    return qn
        return None

    def _resolve_unique_method(self, attr: str) -> "str | None":
        if attr in _AMBIENT_METHOD_NAMES:
            return None
        hits = self.graph._methods_by_name.get(attr, [])
        if len(hits) == 1:
            return hits[0]
        return None

    def resolve(
        self,
        call_func: ast.AST,
        enclosing_cls: "str | None",
        local_defs: "dict[str, str]",
    ) -> "str | None":
        if isinstance(call_func, ast.Name):
            name = call_func.id
            if name in local_defs:
                return local_defs[name]
            if name in self.facts.functions:
                return self.facts.functions[name]
            target = self.facts.imports.get(name)
            if target is not None:
                return self._resolve_symbol(target)
            return None
        parts = _dotted_parts(call_func)
        if parts is None:
            return None
        attr = parts[-1]
        head = parts[0]
        if head in ("self", "cls"):
            if enclosing_cls is not None and len(parts) == 2:
                qn = self._resolve_in_class(enclosing_cls, attr)
                if qn is not None:
                    return qn
            if len(parts) == 2:
                return self._resolve_unique_method(attr)
            return None
        target = self.facts.imports.get(head)
        if target is not None:
            dotted = ".".join([target] + parts[1:])
            qn = self._resolve_symbol(dotted)
            if qn is not None:
                return qn
        if len(parts) == 2:
            return self._resolve_unique_method(attr)
        return None


def boundary_kind(call: ast.Call) -> "str | None":
    """The boundary class of a call node, or None for a plain call."""
    fn = call.func
    parts = _dotted_parts(fn)
    last = parts[-1] if parts else None
    if last in _LOOP_BRIDGE_NAMES:
        return "loop-bridge"
    if isinstance(fn, ast.Attribute) and fn.attr in BOUNDARY_SUBMIT_ATTRS:
        return BOUNDARY_SUBMIT_ATTRS[fn.attr]
    if last == "Thread" and any(
        kw.arg == "target" for kw in call.keywords
    ):
        return "thread"
    return None


def closure_args(call: ast.Call, kind: str) -> "list[ast.AST]":
    """The argument expressions that cross the boundary as code: every
    lambda, name, 2-part attribute ref (bound method) or nested call
    among the args, plus the ``target=`` kwarg of a Thread."""
    out: "list[ast.AST]" = []
    for a in call.args:
        if isinstance(a, (ast.Lambda, ast.Name, ast.Call)):
            out.append(a)
        elif isinstance(a, ast.Attribute) and isinstance(
            a.value, ast.Name
        ):
            out.append(a)
    for kw in call.keywords:
        if kw.arg == "target" and kind == "thread":
            out.append(kw.value)
    return out


class _EdgeCollector(ast.NodeVisitor):
    def __init__(self, graph: CallGraph, facts: _ModuleFacts):
        self.graph = graph
        self.facts = facts
        self.resolver = _Resolver(graph, facts)
        self._module_qname = f"{facts.rel_path}::<module>"
        self._func_stack: "list[str]" = []  # qual (no rel prefix)
        self._cls_stack: "list[str]" = []

    def _caller(self) -> str:
        if self._func_stack:
            return f"{self.facts.rel_path}::{self._func_stack[-1]}"
        return self._module_qname

    def _local_defs(self) -> "dict[str, str]":
        return self.graph.locals_of.get(self._caller(), {})

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._cls_stack.append(node.name)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _visit_func(self, node) -> None:
        if self._func_stack:
            qual = f"{self._func_stack[-1]}.<locals>.{node.name}"
        elif self._cls_stack:
            qual = f"{self._cls_stack[-1]}.{node.name}"
        else:
            qual = node.name
        self._func_stack.append(qual)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Call(self, node: ast.Call) -> None:
        caller = self._caller()
        cls = self._cls_stack[-1] if self._cls_stack else None
        local_defs = self._local_defs()
        kind = boundary_kind(node)
        text = ast.unparse(node.func) if hasattr(ast, "unparse") else ""
        if kind is None:
            callee = self.resolver.resolve(node.func, cls, local_defs)
            if callee is not None:
                edge = Edge(
                    caller, callee, self.facts.rel_path, node.lineno,
                    None, text,
                )
                self.graph.edges.append(edge)
                self.graph.call_info[id(node)] = edge
        else:
            resolved = []
            for arg in closure_args(node, kind):
                if isinstance(arg, ast.Call):
                    target = self.resolver.resolve(
                        arg.func, cls, local_defs
                    )
                elif isinstance(arg, ast.Lambda):
                    target = None  # analyzed in place at the call site
                else:
                    target = self.resolver.resolve(arg, cls, local_defs)
                if target is not None:
                    resolved.append(target)
            for target in resolved:
                self.graph.edges.append(
                    Edge(
                        caller, target, self.facts.rel_path,
                        node.lineno, kind, text,
                    )
                )
            # always record the boundary site itself, resolved or not:
            # MTPU503 keys on the call node, and the coverage test
            # asserts no submit site goes unrecorded
            edge = Edge(
                caller,
                resolved[0] if resolved else None,
                self.facts.rel_path,
                node.lineno,
                kind,
                text,
            )
            if not resolved:
                self.graph.edges.append(edge)
            self.graph.call_info[id(node)] = edge
        self.generic_visit(node)


def build(sources: "dict[str, ParsedModule]") -> CallGraph:
    """Build the call graph for a set of parsed modules."""
    t0 = time.monotonic()
    graph = CallGraph()
    for mod in sources.values():
        _collect_module_facts(graph, mod)
    for mod in sources.values():
        if mod.tree is None:
            continue
        _EdgeCollector(graph, graph.modules[mod.rel_path]).visit(mod.tree)
    graph.build_seconds = time.monotonic() - t0
    return graph
