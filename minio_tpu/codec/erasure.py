"""Erasure: streaming shard-geometry wrapper over the codec backend.

The counterpart of the reference's Erasure type (cmd/erasure-coding.go:28-143
shard math, cmd/erasure-encode.go Encode, cmd/erasure-decode.go Decode,
cmd/erasure-lowlevel-heal.go Heal) - redesigned around batched device
passes instead of a per-block CPU loop:

* The object stream is cut into ``block_size`` blocks (blockSizeV1 = 10 MiB
  in the reference, cmd/object-api-common.go:31) and BATCHES of blocks are
  encoded/hashed in one fused TPU pass (ops/codec_step), amortizing launch
  overhead and keeping the device queue full - the design BASELINE.json
  calls "erasure-sets.go coalesces shards into TPU-sized batches".
* Shard files use the interleaved bitrot framing of bitrot-streaming.go:
  [32B digest][shard block]... with blocks zero-padded to 32B (device
  alignment); true lengths are recovered from the object size.

Writers/readers are any objects with ``write(bytes) -> None`` /
``read_at(offset, length) -> bytes`` (storage-layer bitrot streams); a
None writer/reader is an offline disk, tolerated down to the quorum.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time

import numpy as np

from . import backend as backend_mod, bitrot, compress
from .telemetry import KERNEL_STATS

from ..parallel import iopool
from ..storage import health as disk_health
from ..utils.log import kv, logger

_log = logger("codec")

BLOCK_SIZE_V1 = 10 * 1024 * 1024  # reference blockSizeV1
DEFAULT_BATCH_BLOCKS = 4

# read-ahead jobs carry a fresh sequence key so concurrent GETs spread
# across pool queues instead of serializing behind one worker
_RA_SEQ = itertools.count()

# stage accounting from iopool workers (frame assembly runs on the
# writer's queue, not the submitting thread)
_STAGE_LK = threading.Lock()


def _codec_stage(be) -> str:
    """Stage key for encode time: backends whose encode() computes
    parity + digests in one fused pass (TPU device pass, native
    single-pass CPU kernel) book under "codec_fused" so the bench stage
    breakdown shows what the fusion bought; split/fallback encodes stay
    under "codec" alongside decode/verify time."""
    return "codec_fused" if getattr(be, "fused_encode", False) else "codec"


def _io_key(obj):
    """Routing key for a writer/reader: the object layer stamps disks
    with a stable endpoint ``io_key``; untagged test doubles hash by
    identity (still one ordered queue per instance)."""
    return getattr(obj, "io_key", None) or ("anon", id(obj))


def _parity_plane_on() -> bool:
    """MINIO_TPU_PARITY_PLANE = on|off (default on): route PUT encodes
    through the digest-only seam so parity stays device-resident until
    the writers pull it (codec/backend.py).  "off" restores the legacy
    eager encode_end readback."""
    return os.environ.get("MINIO_TPU_PARITY_PLANE", "on") != "off"


def subchunk_words(w: int, quantum: int) -> int:
    """Sub-chunk size in uint32 words for the device overlap pipeline
    (MINIO_TPU_CODEC_OVERLAP=async), or 0 when the batch is too small.

    MINIO_TPU_CODEC_SUBCHUNK_KB (default 256 KiB of shard bytes per
    sub-chunk) is rounded down to a multiple of ``quantum`` words —
    the parity pack group when the pack leg is on, the hash partition
    stride otherwise — so chunk cuts land on group AND partition
    boundaries and the per-chunk math composes bit-identically.
    Clamped so the pipeline only engages at S >= 3 full chunks: below
    that the staging ping-pong cannot amortize its second buffer and
    the serialized path is strictly better.
    """
    try:
        kb = float(os.environ.get("MINIO_TPU_CODEC_SUBCHUNK_KB") or 256)
    except ValueError:
        kb = 256.0
    q = max(int(quantum), 1)
    cw = max(q, (int(kb * 256) // q) * q)  # KiB -> u32 words, quantized
    if w // cw < 3:
        return 0
    return cw


class _Begun:
    """One begun encode group: the SINGLE consume point for its handle.

    The success path calls ``end``/``end_digest`` exactly once; the
    error path calls ``cleanup``, which is a no-op for already-consumed
    records and otherwise ends the handle (releasing an undrained
    parity ref without paying the D2H).  The consumed flag replaces the
    old ``started[i] = None`` sentinel bookkeeping, so cleanup can
    never double-consume or leak a device handle no matter which
    iteration of the flush loop failed.
    """

    __slots__ = (
        "handle", "batch", "digest_mode", "consumed", "start_block"
    )

    def __init__(self, handle, batch, digest_mode: bool,
                 start_block: int = 0):
        self.handle = handle
        self.batch = batch
        self.digest_mode = digest_mode
        self.consumed = False
        # absolute index of the group's first object block: the read
        # cache keys groups by (first_block, g, shard_len), so the PUT
        # populate and the GET lookup must agree on block coordinates
        self.start_block = start_block

    def end(self, be):
        self.consumed = True
        return be.encode_end(self.handle)

    def end_digest(self, be):
        self.consumed = True
        return be.encode_digest_end(self.handle)

    def cleanup(self, be) -> None:
        if self.consumed:
            return
        self.consumed = True
        try:
            if self.digest_mode:
                _digests, ref = be.encode_digest_end(self.handle)
                ref.release()
            else:
                be.encode_end(self.handle)
        except Exception as exc:
            _log.debug(
                "encode handle cleanup failed", extra=kv(err=str(exc))
            )


class _ReaderBank:
    """Lazy shard-reader list for the decode path.

    ``source`` is either the reader list itself or a zero-arg callable
    producing it.  A GET whose every group hits the read cache never
    calls ``get`` — the shard streams are never opened, so a cache hit
    makes ZERO disk calls (the chaos grid meters exactly this).  The
    list is materialized at most once and padded to ``n`` slots; the
    quorum reader's in-place ``readers[s] = None`` death marks persist
    across batches exactly as before.
    """

    __slots__ = ("_source", "_list")

    def __init__(self, source):
        if callable(source):
            self._source = source
            self._list = None
        else:
            self._source = None
            self._list = source

    @property
    def opened(self) -> bool:
        return self._list is not None

    def get(self, n: int) -> list:
        if self._list is None:
            self._list = list(self._source())
        while len(self._list) < n:
            self._list.append(None)
        return self._list


def _fanout_reads(fn, slots: list, readers, nbytes: int) -> list:
    """Run ``fn(slot)`` for every slot through the shared iopool, one
    job per shard (replaces the old thread-per-call _parallel_map).
    ``fn`` must capture its own errors — a reader failure is data
    (a dead shard), not an exception."""
    if len(slots) <= 1:
        return [fn(s) for s in slots]
    pool = iopool.get_pool()
    futs = [
        pool.submit(_io_key(readers[s]), (lambda s=s: fn(s)), nbytes=nbytes)
        for s in slots
    ]
    return [f.result_or_raise() for f in futs]


class ErasureError(Exception):
    pass


class QuorumError(ErasureError):
    """Fewer healthy shards than required (errXLReadQuorum/WriteQuorum)."""


@dataclasses.dataclass(frozen=True)
class Erasure:
    """Shard geometry + streaming codec ops for one erasure config."""

    data_blocks: int
    parity_blocks: int
    block_size: int = BLOCK_SIZE_V1

    def __post_init__(self):
        if not (1 <= self.data_blocks <= 16):
            raise ValueError(f"dataBlocks {self.data_blocks} out of range")
        if not (0 <= self.parity_blocks <= 16):
            raise ValueError(f"parityBlocks {self.parity_blocks} out of range")
        if self.block_size <= 0:
            raise ValueError("blockSize must be positive")

    @property
    def total_shards(self) -> int:
        return self.data_blocks + self.parity_blocks

    # ---- shard math (cmd/erasure-coding.go:115-143 semantics + padding) --

    def shard_size(self, block_len: "int | None" = None) -> int:
        """Unpadded shard length for one object block (ShardSize)."""
        if block_len is None:
            block_len = self.block_size
        return -(-block_len // self.data_blocks)

    def shard_size_padded(self, block_len: "int | None" = None) -> int:
        """Device-aligned shard length actually encoded and stored."""
        return bitrot.padded_len(self.shard_size(block_len))

    def block_count(self, total_length: int) -> int:
        if total_length == 0:
            return 0
        return -(-total_length // self.block_size)

    def shard_file_size(self, total_length: int) -> int:
        """On-disk framed size of each shard file (ShardFileSize)."""
        if total_length < 0:
            raise ValueError("negative length")
        if total_length == 0:
            return 0
        full, last = divmod(total_length, self.block_size)
        size = full * bitrot.frame_size(self.shard_size())
        if last:
            size += bitrot.frame_size(self.shard_size(last))
        return size

    def shard_block_offset(self, block_index: int) -> int:
        """Framed offset of block_index within every shard file."""
        return block_index * bitrot.frame_size(self.shard_size())

    def shard_file_offset(
        self, start_offset: int, length: int, total_length: int
    ) -> int:
        """Framed end-offset covering [start, start+length) (ShardFileOffset)."""
        until = start_offset + length
        return self.shard_file_size(min(until, total_length))

    def _block_len(self, block_index: int, total_length: int) -> int:
        start = block_index * self.block_size
        return min(self.block_size, total_length - start)

    # ---- streaming encode (cmd/erasure-encode.go:73-109) ----------------

    def encode(
        self,
        reader,
        writers: list,
        write_quorum: int,
        batch_blocks: int = DEFAULT_BATCH_BLOCKS,
        backend: "backend_mod.CodecBackend | None" = None,
        parity_band: "iopool.ParityBand | None" = None,
        cache_ctx=None,
    ) -> int:
        """Stream from ``reader`` (has .read(n)) into framed shard writers.

        Batches of blocks share one device pass.  Returns total bytes
        consumed.  Raises QuorumError when healthy writers drop below
        write_quorum (the parallelWriter quorum reduction,
        erasure-encode.go:39-70).

        With ``parity_band`` set (quorum-early commit), encode returns
        once every DATA shard write settled and quorum holds — parity
        writes keep draining in the background, adopted by the band:
        a parity failure past this return is heal-flagged through the
        band, never silent.  Requires the digest-only parity plane
        (MINIO_TPU_PARITY_PLANE=on).
        """
        be = backend or backend_mod.get_backend()
        k, m = self.data_blocks, self.parity_blocks
        digest_mode = _parity_plane_on() and m > 0
        if parity_band is not None and not digest_mode:
            parity_band = None  # legacy eager path settles in-line
        total = 0
        eof = False
        band_adopted = False
        # quorum-aware shard fan-out: one ordered pool queue per disk,
        # flush() returns at write_quorum acks, stragglers drain in the
        # background (parallelWriter, erasure-encode.go:39-70)
        flusher = iopool.ShardFlusher(
            iopool.get_pool(), quorum_exc=QuorumError
        )
        stages = {
            "assemble": 0.0, "codec": 0.0, "codec_fused": 0.0,
            "codec_drain": 0.0, "disk": 0.0,
        }
        # double-buffered pipeline (erasure-encode.go:73-109 overlap,
        # SURVEY stage 8): batch k's H2D + device pass is in flight
        # while batch k-1's shards stream to disk/network; exactly one
        # batch pending bounds memory at 2 batches
        pending = None
        blocks_done = 0
        try:
            while not eof:
                blocks: list[bytes] = []
                while len(blocks) < batch_blocks and not eof:
                    buf = _read_full(reader, self.block_size)
                    if not buf:
                        eof = True
                        break
                    if len(buf) < self.block_size:
                        eof = True
                    blocks.append(buf)
                    total += len(buf)
                if not blocks:
                    break
                started = self._encode_begin_batch(
                    be, blocks, stages, digest_mode,
                    base_block=blocks_done,
                )
                blocks_done += len(blocks)
                blocks = None  # scattered into the batch arrays above
                if pending is not None:
                    try:
                        self._flush_batch(
                            be, pending, writers, write_quorum,
                            flusher, stages, cache_ctx,
                        )
                    finally:
                        pending = started
                else:
                    pending = started
            if pending is not None:
                p, pending = pending, None
                self._flush_batch(
                    be, p, writers, write_quorum, flusher, stages,
                    cache_ctx,
                )
            # early-acked batches may still have stragglers in flight:
            # settle them and re-check the quorum over the final disk
            # liveness picture before declaring the object durable.
            # quorum-early mode settles only the DATA slots here — the
            # parity stragglers are adopted by the band, and the
            # liveness picture for them is optimistic until settle
            t0 = time.monotonic()
            dead = (
                flusher.drain_slots(range(k))
                if parity_band is not None
                else flusher.drain()
            )
            for s in dead:
                if s < len(writers):
                    writers[s] = None
            stages["disk"] += time.monotonic() - t0
            if flusher.submitted:
                alive = sum(1 for w in writers if w is not None)
                if alive < write_quorum:
                    raise QuorumError(
                        f"write quorum lost: {alive} < {write_quorum}"
                    )
            if parity_band is not None:
                parity_band.adopt(flusher)
                band_adopted = True
            KERNEL_STATS.record_stream("encode", total)
            KERNEL_STATS.record_stages("put", stages)
            return total
        finally:
            # an error mid-flush must not abandon begun handles: a
            # batching backend counts them active until ended, so a
            # leak would degrade every later codec call
            for rec in pending or []:
                rec.cleanup(be)
            # nor may background shard writes race the caller closing
            # its writers: settle the pool before handing back — unless
            # the band adopted the stragglers, in which case IT owns
            # the settle (that deferral is the quorum-early ack)
            if not band_adopted:
                for s in flusher.drain():
                    if s < len(writers):
                        writers[s] = None

    def _encode_begin_batch(self, be, blocks, stages, digest_mode=False,
                            base_block=0):
        """Kick off the device passes for one batch of blocks; returns
        a list of _Begun records, one per uniform-shard-size group."""
        k = self.data_blocks
        m = self.parity_blocks
        # uniform batch: all blocks but possibly the last share shard size
        groups: list[tuple[int, int, list[bytes]]] = []
        full = [b for b in blocks if len(b) == self.block_size]
        tail = [b for b in blocks if len(b) != self.block_size]
        if full:
            groups.append((self.shard_size_padded(), base_block, full))
        for b in tail:
            # a short read ends the stream, so the tail block is always
            # the batch's last — its absolute index follows the fulls
            groups.append(
                (self.shard_size_padded(len(b)), base_block + len(full),
                 [b])
            )
        started = []
        for shard_len, group_block, group in groups:
            t0 = time.monotonic()
            batch = np.zeros((len(group), k, shard_len), dtype=np.uint8)
            for bi, block in enumerate(group):
                # one reshape scatters the whole block across its k
                # shard rows (the per-shard slice loop was O(k) tiny
                # copies per block)
                ss = self.shard_size(len(block))
                a = np.frombuffer(block, dtype=np.uint8)
                rows, rem = divmod(len(a), ss)
                if rows:
                    batch[bi, :rows, :ss] = a[: rows * ss].reshape(
                        rows, ss
                    )
                if rem:
                    batch[bi, rows, :rem] = a[rows * ss :]
            stages["assemble"] += time.monotonic() - t0
            t0 = time.monotonic()
            handle = (
                be.encode_digest_begin(batch, m)
                if digest_mode
                else be.encode_begin(batch, m)
            )
            started.append(
                _Begun(handle, batch, digest_mode, group_block)
            )
            stages[_codec_stage(be)] += time.monotonic() - t0
        return started

    def _flush_batch(
        self, be, started, writers, write_quorum, flusher, stages,
        cache_ctx=None,
    ) -> None:
        k, m = self.data_blocks, self.parity_blocks
        n = k + m
        try:
            self._flush_groups(
                be, started, writers, write_quorum, k, n,
                flusher, stages, cache_ctx,
            )
        except BaseException:
            # end the groups the failed iteration never reached
            # (batching backends count begun handles as active);
            # _Begun.cleanup skips consumed records, so this can never
            # double-end a handle the loop already materialized
            for rec in started:
                rec.cleanup(be)
            raise

    @staticmethod
    def _run_writer(w, dig_s, src, col, ds, stages):
        """Build the write job for one disk's byte run.  The interleave
        itself executes ON the iopool worker, and the closure pins only
        what this disk actually reads — its digest column plus EITHER
        the data batch OR the parity array — so a straggler generation
        costs one shared array, never per-disk copies."""
        def _job():
            t0 = time.monotonic()
            shard = src[:, col, :]
            B = shard.shape[0]
            run = np.empty((B, ds + shard.shape[1]), dtype=np.uint8)
            run[:, :ds] = dig_s
            run[:, ds:] = shard
            dt = time.monotonic() - t0
            with _STAGE_LK:
                stages["assemble"] += dt
            # hand the writer a view, not a bytes copy: every write
            # path (file, REST pipe, test shards) copies on its own
            # terms, so the run is never duplicated wholesale
            w.write(run.reshape(-1).data)
        return _job

    @staticmethod
    def _run_parity_writer(w, dig_s, pref, col, ds, stages):
        """Parity twin of _run_writer for the digest-only path: the
        closure pins the ParityRef, not host bytes.  The first parity
        job to run pays the (memoized, possibly device-compressed) lazy
        drain on its own iopool worker — behind the data-quorum ack —
        and the sibling parity disks reuse the materialized plane."""
        def _job():
            t0 = time.monotonic()
            par = pref.drain()
            dt = time.monotonic() - t0
            with _STAGE_LK:
                stages["codec_drain"] += dt
            t0 = time.monotonic()
            shard = par[:, col, :]
            B = shard.shape[0]
            run = np.empty((B, ds + shard.shape[1]), dtype=np.uint8)
            run[:, :ds] = dig_s
            run[:, ds:] = shard
            dt = time.monotonic() - t0
            with _STAGE_LK:
                stages["assemble"] += dt
            w.write(run.reshape(-1).data)
        return _job

    def _flush_groups(
        self, be, started, writers, write_quorum, k, n,
        flusher, stages, cache_ctx=None,
    ) -> None:
        """Assemble each disk's contiguous byte run for the whole batch
        with one numpy interleave (digest frames + payload rows) and
        fan the n runs out through the iopool — ONE buffer per disk per
        batch, the write twin of the one-ranged-read-per-shard GET.

        Digest-mode records materialize ONLY the digests here (all the
        metadata/ack path needs); their parity crosses the bus lazily
        inside the parity writers' jobs via the ParityRef."""
        jobs = []
        for rec in started:
            batch = rec.batch
            t0 = time.monotonic()
            if rec.digest_mode:
                digests, pref = rec.end_digest(be)
                par = None
            else:
                parity, digests = rec.end(be)
                par = np.asarray(parity, dtype=np.uint8)
                pref = None
            stages[_codec_stage(be)] += time.monotonic() - t0
            t0 = time.monotonic()
            B, shard_len = batch.shape[0], batch.shape[2]
            ds = bitrot.DIGEST_SIZE
            # digest words -> 32B frames, all (block, shard) cells at
            # once; byte layout matches bitrot.digest_to_bytes
            dig_u32 = np.ascontiguousarray(digests, dtype=np.uint32)
            dig = dig_u32.view(np.uint8).reshape(B, n, ds)
            stages["assemble"] += time.monotonic() - t0
            if cache_ctx is not None:
                # PUT population: the batch's data rows + their digest
                # words, before any disk write settles — the next GET
                # for this object never touches the quorum path
                cache_ctx.populate_from_encode(
                    rec.start_block, batch,
                    dig_u32.reshape(B, n, 8)[:, :k],
                )
            for s in range(n):
                w = writers[s] if s < len(writers) else None
                if w is None:
                    continue
                if s >= k and pref is not None:
                    fn = self._run_parity_writer(
                        w, dig[:, s, :], pref, s - k, ds, stages
                    )
                else:
                    fn = self._run_writer(
                        w,
                        dig[:, s, :],
                        batch if s < k else par,
                        s if s < k else s - k,
                        ds,
                        stages,
                    )
                jobs.append((s, _io_key(w), fn, B * (ds + shard_len)))
        alive = {s for s, _key, _fn, _nb in jobs}
        if len(alive) < write_quorum:
            raise QuorumError(
                f"write quorum lost: {len(alive)} < {write_quorum}"
            )
        t0 = time.monotonic()
        dead = flusher.flush(jobs, write_quorum)
        stages["disk"] += time.monotonic() - t0
        for s in dead:
            if s < len(writers):
                writers[s] = None

    # ---- streaming decode (cmd/erasure-decode.go:211-290) ---------------

    def decode(
        self,
        writer,
        readers: list,
        offset: int,
        length: int,
        total_length: int,
        batch_blocks: int = DEFAULT_BATCH_BLOCKS,
        backend: "backend_mod.CodecBackend | None" = None,
        cache_ctx=None,
    ) -> tuple[int, bool]:
        """Reconstruct [offset, offset+length) into ``writer``.

        ``readers`` is the shard reader list OR a zero-arg callable
        producing it (lazy open: with a ``cache_ctx`` whose groups all
        hit, the readers are never opened at all).

        Returns (bytes_written, heal_required): heal_required is set when
        any shard was missing or failed bitrot verification but quorum
        still allowed reconstruction (errHealRequired semantics,
        erasure-decode.go:165-167).
        """
        stages = {"assemble": 0.0, "codec": 0.0, "disk": 0.0}
        written, heal_required = self._decode_stream(
            writer, readers, offset, length, total_length,
            batch_blocks, backend, stages, cache_ctx,
        )
        KERNEL_STATS.record_stream("decode", written)
        KERNEL_STATS.record_stages("get", stages)
        if heal_required:
            KERNEL_STATS.record_heal_required()
        return written, heal_required

    def _decode_stream(
        self,
        writer,
        readers: list,
        offset: int,
        length: int,
        total_length: int,
        batch_blocks: int = DEFAULT_BATCH_BLOCKS,
        backend: "backend_mod.CodecBackend | None" = None,
        stages: "dict | None" = None,
        cache_ctx=None,
    ) -> tuple[int, bool]:
        if length == 0:
            return 0, False
        if offset < 0 or length < 0 or offset + length > total_length:
            raise ValueError("range out of bounds")
        be = backend or backend_mod.get_backend()
        bank = _ReaderBank(readers)
        k = self.data_blocks
        start_block = offset // self.block_size
        end_block = (offset + length - 1) // self.block_size
        batches: "list[list[int]]" = []
        bi = start_block
        while bi <= end_block:
            batch_idx = list(
                range(bi, min(bi + batch_blocks, end_block + 1))
            )
            batches.append(batch_idx)
            bi += len(batch_idx)
        written = 0
        heal_required = False
        if len(batches) <= 1:
            for batch_idx in batches:
                datas, healed = self._decode_blocks(
                    be, bank, batch_idx, total_length, stages,
                    cache_ctx,
                )
                heal_required = heal_required or healed
                w, done = self._write_blocks(
                    writer, datas, batch_idx, offset, length,
                    total_length,
                )
                written += w
                if done:
                    return written, heal_required
            return written, heal_required
        # read-ahead pipeline (the GET twin of the encode double
        # buffer): batch k+1's shard reads + verify + reconstruct run
        # on an iopool worker while batch k streams to the client —
        # now unconditionally: local reads also fan out per disk, so
        # the prefetch overlaps the decode device pass with the next
        # group's reads just like encode double-buffers its flush.
        # Exactly one prefetch is in flight, so _decode_blocks never
        # runs concurrently with itself (it mutates `readers`).
        pool = iopool.get_pool()
        fut = None
        try:
            # aux band: the prefetch BLOCKS on leaf read futures, so it
            # must never occupy (or queue behind) a disk queue worker
            fut = pool.submit(
                ("readahead", next(_RA_SEQ)),
                lambda b=batches[0]: self._decode_blocks(
                    be, bank, b, total_length, stages, cache_ctx
                ),
                aux=True,
            )
            for i, batch_idx in enumerate(batches):
                datas, healed = fut.result_or_raise()
                fut = None
                heal_required = heal_required or healed
                if i + 1 < len(batches):
                    fut = pool.submit(
                        ("readahead", next(_RA_SEQ)),
                        lambda b=batches[i + 1]: self._decode_blocks(
                            be, bank, b, total_length, stages,
                            cache_ctx,
                        ),
                        aux=True,
                    )
                w, done = self._write_blocks(
                    writer, datas, batch_idx, offset, length,
                    total_length,
                )
                datas = None  # release batch k before blocking on k+1
                written += w
                if done:
                    return written, heal_required
            return written, heal_required
        finally:
            # an early return (RangeSatisfied, client gone) must not
            # leave the prefetch racing the caller's reader close -
            # drain the in-flight read before handing back
            if fut is not None:
                fut.wait()
                if fut.error is not None:
                    _log.debug("prefetch drain after early return", extra=kv(err=str(fut.error)))

    def _write_blocks(
        self, writer, datas, batch_idx, offset, length, total_length
    ) -> "tuple[int, bool]":
        """Stream one decoded batch's range slices; (written, done)
        where done means a skipping decompressor downstream has its
        full range (RangeSatisfied - stop paying decode I/O, but keep
        the heal verdict observed so far: losing it would mask bitrot
        on range reads)."""
        written = 0
        for j, block_index in enumerate(batch_idx):
            block_start = block_index * self.block_size
            block_len = self._block_len(block_index, total_length)
            lo = max(offset, block_start) - block_start
            hi = (
                min(offset + length, block_start + block_len)
                - block_start
            )
            if hi > lo:
                # memoryview slice: the decoded block goes to the sink
                # (socket, decompressor) without the copy a bytes slice
                # would make — the async plane's transport consumes the
                # view before the batch is released
                try:
                    writer.write(memoryview(datas[j])[lo:hi])
                except compress.RangeSatisfied:
                    return written, True
                written += hi - lo
        return written, False

    def _decode_blocks(
        self, be, bank: "_ReaderBank", block_indices: list[int],
        total_length: int, stages: "dict | None" = None,
        cache_ctx=None,
    ) -> tuple[list[bytes], bool]:
        """Read + verify + reconstruct a batch of blocks -> raw block bytes.

        Reads only ``data_blocks`` shards up front (local readers
        preferred, data shards first among equals) and escalates to
        parity shards only on read failure or bitrot — a healthy GET
        never touches parity (erasure-decode.go:63-88 newParallelReader
        with prefer[], :120-183 Read with missingPartsHeal escalation).

        With a ``cache_ctx``, each group first consults the tiered
        read cache: a hit serves the digest-verified data rows without
        opening a single shard reader — no hedging, no breakers, no
        disk.  A healthy-path miss populates the cache (subject to
        frequency admission) from the decoded data rows — read intact
        with their on-disk digest words, or reconstructed from
        digest-verified shards with freshly computed words.
        """
        k, m = self.data_blocks, self.parity_blocks
        n = k + m
        if stages is None:
            stages = {"assemble": 0.0, "codec": 0.0, "disk": 0.0}
        sizes = [
            self.shard_size_padded(self._block_len(b, total_length))
            for b in block_indices
        ]
        readers = None
        heal = False
        out: list[bytes] = []
        # group contiguous runs with equal shard size into one device pass
        i = 0
        while i < len(block_indices):
            j = i
            while j < len(block_indices) and sizes[j] == sizes[i]:
                j += 1
            group = block_indices[i:j]
            shard_len = sizes[i]
            if cache_ctx is not None:
                t0 = time.monotonic()
                cached = cache_ctx.lookup(
                    be, group[0], len(group), shard_len
                )
                stages["codec"] += time.monotonic() - t0
                if cached is not None:
                    t0 = time.monotonic()
                    for gi, b in enumerate(group):
                        block_len = self._block_len(b, total_length)
                        ss = self.shard_size(block_len)
                        # one strided copy; the [:block_len] trim is a
                        # view and _write_blocks streams views as-is
                        flat = np.ascontiguousarray(
                            cached[gi, :, :ss]
                        ).reshape(-1)
                        out.append(flat[:block_len])
                    stages["assemble"] += time.monotonic() - t0
                    i = j
                    continue
            if readers is None:
                readers = bank.get(n)
                # a reader slot known-dead before we start is a missing
                # shard: flag heal even though the k-read path may
                # never need it (a fully-cached GET skips this check by
                # design — it observes no disks at all)
                heal = heal or any(
                    readers[s] is None for s in range(n)
                )
            shards, digests, ok, g_heal = self._read_group_quorum(
                be, readers, group, shard_len, stages
            )
            heal = heal or g_heal
            # verify stays a separate pass HERE (unlike heal, which
            # uses the fused reconstruct_and_verify - ONE device
            # launch under fused1): the quorum read needs per-shard
            # verdicts BEFORE deciding whether to escalate to more
            # reads, and on the healthy path there is no reconstruct
            # at all - fusing would decode k rows per group that the
            # fast path below streams out as views
            # reconstruct per distinct pattern (usually one)
            t0 = time.monotonic()
            patterns: dict[tuple, list[int]] = {}
            for gi in range(len(group)):
                pat = tuple(bool(x) for x in ok[gi])
                patterns.setdefault(pat, []).append(gi)
            if len(patterns) == 1 and all(next(iter(patterns))[:k]):
                # healthy fast path: every block has its data rows
                # intact, so stream straight out of the frame buffer -
                # no (g, k, shard_len) copy, no fancy-index temporaries
                datas = shards[:, :k, :]
            else:
                datas = np.zeros(
                    (len(group), k, shard_len), dtype=np.uint8
                )
                for pat, gis in patterns.items():
                    if all(pat[:k]):
                        datas[gis] = shards[gis][:, :k]
                    else:
                        datas[np.asarray(gis)] = be.reconstruct(
                            shards[np.asarray(gis)], pat, k, m
                        )
            stages["codec"] += time.monotonic() - t0
            if cache_ctx is not None and not g_heal:
                # admit the decoded data rows.  When every data slot
                # read intact, reuse the digest words that just
                # verified against disk; when the preferred k readers
                # included parity (local shards first: a node whose
                # drives hold parity reconstructs on every healthy
                # GET), the rows came out of reconstruct over
                # digest-verified shards, so recompute their words —
                # the cache only needs digests self-consistent with
                # the rows it stores to catch in-cache rot on hit
                if bool(ok[:, :k].all()):
                    cache_ctx.admit_from_decode(
                        group[0], len(group), shard_len,
                        datas, digests[:, :k, :],
                    )
                else:
                    cache_ctx.admit_from_decode(
                        group[0], len(group), shard_len,
                        datas, be.digest(datas),
                    )
            # raw frames die before blocks copy out
            shards = digests = ok = None
            t0 = time.monotonic()
            for gi, b in enumerate(group):
                block_len = self._block_len(b, total_length)
                ss = self.shard_size(block_len)
                block = datas[gi, :, :ss].reshape(-1)[:block_len]
                out.append(block.tobytes())
            datas = None  # only the extracted blocks survive the group
            stages["assemble"] += time.monotonic() - t0
            i = j
        return out, heal

    def _read_group_quorum(
        self, be, readers, group: list[int], shard_len: int,
        stages: "dict | None" = None,
    ):
        """Read shard frames for one equal-size block group until every
        block has >= k intact shards, escalating through the preference
        order; shard reads always fan out per disk through the shared
        iopool (local disks too — 12 spindles seek concurrently) and
        contiguous frames are fetched in one ranged read per shard (one
        RTT per shard per batch, the read twin of the pipelined shard
        writers).

        The escalation loop is hedged and deadline-bounded (the Tail at
        Scale discipline over the reference's parallelReader shape):
        outstanding reads race a deadline derived from the pool-wide
        read p99 (storage/health.py); when the deadline expires with
        the quorum still short, a duplicate read launches on the next
        preferred shard instead of blocking on the straggler.  Losers
        are abandoned — their band slot frees without blocking us — and
        reported to the straggler's circuit breaker as censored slow
        samples, so the NEXT GET's preference order already routes
        around the slow disk.  Suspect/tripped disks sort last among
        otherwise-equal shards; a straggler that merely lost a hedge
        race does NOT set the heal flag (slowness is not damage), but
        observed missing/short/corrupt frames still do.
        """
        if stages is None:
            stages = {"assemble": 0.0, "codec": 0.0, "disk": 0.0}
        k, m = self.data_blocks, self.parity_blocks
        n = k + m
        g = len(group)
        frame = bitrot.DIGEST_SIZE + shard_len
        # full-size blocks sit frame-by-frame in the shard file, so a
        # whole group is one contiguous byte range; the tail block's
        # shorter frame is its own group and reads individually
        contiguous = frame == bitrot.frame_size(self.shard_size())
        shards = np.zeros((g, n, shard_len), dtype=np.uint8)
        digests = np.zeros((g, n, 8), dtype=np.uint32)
        present = np.zeros((g, n), dtype=bool)
        ok = np.zeros((g, n), dtype=bool)
        heal = False

        reg = disk_health.registry()
        # endpoint per slot: only endpoint-tagged readers (the object
        # layer stamps disk streams) feed the breakers and estimators;
        # untagged unit-test doubles read exactly as before
        endpoints: "dict[int, str | None]" = {}
        for s in range(n):
            key = getattr(readers[s], "io_key", None)
            endpoints[s] = key if isinstance(key, str) else None

        def read_shard(s) -> "list[bytes | None]":
            r = readers[s]
            frames: "list[bytes | None]" = [None] * g
            if r is None:
                return frames
            ep = endpoints[s]
            t_read = time.monotonic()
            try:
                if contiguous:
                    base = self.shard_block_offset(group[0])
                    # zero-copy frame slices: one ranged read per
                    # shard, parsed as views, never re-copied
                    buf = memoryview(r.read_at(base, frame * g))
                    for gi in range(g):
                        c = buf[gi * frame : (gi + 1) * frame]
                        if len(c) == frame:
                            frames[gi] = c
                else:
                    for gi, b in enumerate(group):
                        c = r.read_at(self.shard_block_offset(b), frame)
                        if len(c) == frame:
                            frames[gi] = c
            except Exception:  # noqa: BLE001 - any failure = dead shard
                readers[s] = None
                if ep:
                    reg.record_shard_read(
                        ep, time.monotonic() - t_read, ok=False
                    )
                return [None] * g
            # service time recorded HERE, on the worker, so the sample
            # is pure disk latency — settle-side timing would fold in
            # decode/verify stalls and bias the hedge deadline slow.
            # An abandoned-but-running read that completes still lands
            # its true (slow) sample, exactly what the estimator wants.
            if ep:
                reg.record_shard_read(
                    ep, time.monotonic() - t_read, ok=True
                )
            return frames

        def slot_state(s: int) -> int:
            ep = endpoints[s]
            return reg.get_disk(ep).state() if ep else disk_health.HEALTHY

        # preference: live readers, breaker-healthy before suspect/
        # tripped, local before remote, then natural order (data shards
        # 0..k-1 first among equals)
        remaining = sorted(
            (s for s in range(n) if readers[s] is not None),
            key=lambda s: (
                slot_state(s),
                not getattr(readers[s], "is_local", True),
                s,
            ),
        )
        pool = iopool.get_pool()
        deadline = reg.hedge_deadline()
        outstanding: "dict[int, tuple]" = {}  # s -> (fut, t0, is_hedge)
        last_hedge = 0.0
        hedges = 0

        def launch(hedge: bool) -> None:
            s = remaining.pop(0)
            submit = pool.submit_hedged if hedge else pool.submit
            fut = submit(
                _io_key(readers[s]),
                (lambda s=s: read_shard(s)),
                nbytes=frame * g,
            )
            outstanding[s] = (fut, time.monotonic(), hedge)

        try:
            while True:
                deficit = int(k - ok.sum(axis=1).min()) if g else 0
                if deficit <= 0:
                    break
                while len(outstanding) < deficit and remaining:
                    launch(hedge=False)
                if not outstanding:
                    intact = int(ok.sum(axis=1).min())
                    raise QuorumError(
                        f"read quorum lost: {intact}/{n} shards intact,"
                        f" need {k}"
                    )
                t0 = time.monotonic()
                # wait for any completion, racing the hedge deadline
                # (clocked from the oldest outstanding read or the last
                # hedge, whichever is later — each hedge gets a full
                # deadline before the next one may fire)
                timeout = None
                if (
                    deadline is not None
                    and remaining
                    and hedges < m
                ):
                    base = max(
                        min(v[1] for v in outstanding.values()),
                        last_hedge,
                    )
                    timeout = max(0.0, base + deadline - t0)
                done = iopool.wait_any(
                    [v[0] for v in outstanding.values()], timeout
                )
                stages["disk"] += time.monotonic() - t0
                if not done:
                    # deadline expired, quorum still short: duplicate
                    # read on the next preferred (parity) shard
                    launch(hedge=True)
                    hedges += 1
                    last_hedge = time.monotonic()
                    continue
                # settle every completed slot in one batch
                batch = sorted(
                    s for s, v in outstanding.items() if v[0].done()
                )
                t0 = time.monotonic()
                for s in batch:
                    fut, t_launch, is_hedge = outstanding.pop(s)
                    frames = fut.result if fut.error is None else None
                    if frames is None:
                        frames = [None] * g
                    got_any = False
                    for gi, c in enumerate(frames):
                        if c is None:
                            heal = True  # chosen shard missing/short
                            continue
                        digests[gi, s] = bitrot.digest_from_bytes(
                            c[: bitrot.DIGEST_SIZE]
                        )
                        shards[gi, s] = np.frombuffer(
                            c[bitrot.DIGEST_SIZE :], dtype=np.uint8
                        )
                        present[gi, s] = True
                        got_any = True
                    if is_hedge and got_any:
                        KERNEL_STATS.record_hedge("won")
                frames = None  # ranged-read buffers die before verify
                stages["assemble"] += time.monotonic() - t0
                # verify only the shards just read: a healthy GET
                # hashes exactly k columns, and escalation rounds never
                # re-hash already-verified shards
                t0 = time.monotonic()
                bcols = np.asarray(batch)
                if batch == list(
                    range(batch[0], batch[0] + len(batch))
                ):
                    # contiguous columns (the healthy k-data-shard
                    # case): basic slices give verify views, not 4 MiB
                    # temporaries
                    sh_cols = shards[:, batch[0] : batch[0] + len(batch)]
                    dg_cols = digests[:, batch[0] : batch[0] + len(batch)]
                else:
                    sh_cols = shards[:, bcols]
                    dg_cols = digests[:, bcols]
                okb = be.verify(sh_cols, dg_cols) & present[:, bcols]
                sh_cols = dg_cols = None
                if (okb != present[:, bcols]).any():
                    heal = True  # bitrot detected somewhere
                ok[:, bcols] = okb
                stages["codec"] += time.monotonic() - t0
        finally:
            # disavow stragglers: quorum is met (or lost) without them.
            # Queued losers resolve IopoolAbandoned without running;
            # running ones finish unobserved.  Their elapsed time is a
            # CENSORED sample — real latency is at least this — so it
            # feeds the straggler's slow-strike ladder but never the
            # latency estimators.
            now = time.monotonic()
            for s, (fut, t_launch, is_hedge) in outstanding.items():
                fut.abandon()
                ep = endpoints[s]
                if ep:
                    reg.record_shard_read(
                        ep, now - t_launch, ok=True, censored=True
                    )
                if is_hedge:
                    KERNEL_STATS.record_hedge("wasted")
        return shards, digests, ok, heal

    # ---- heal (cmd/erasure-lowlevel-heal.go:28-48) ----------------------

    def heal(
        self,
        readers: list,
        writers: list,
        total_length: int,
        backend: "backend_mod.CodecBackend | None" = None,
    ) -> None:
        """Rebuild missing shard files from survivors (quorum = k).

        readers[i] is None for the outdated/offline disks; writers[i] is
        non-None exactly where a shard must be rebuilt.  Streams
        block-by-block: verify survivors, reconstruct all shards, re-frame
        and write the ones needed.
        """
        be = backend or backend_mod.get_backend()
        k, m = self.data_blocks, self.parity_blocks
        n = k + m
        for b in range(self.block_count(total_length)):
            block_len = self._block_len(b, total_length)
            shard_len = self.shard_size_padded(block_len)
            frame = bitrot.DIGEST_SIZE + shard_len
            off = self.shard_block_offset(b)
            shards = np.zeros((1, n, shard_len), dtype=np.uint8)
            digests = np.zeros((1, n, 8), dtype=np.uint32)
            present = np.zeros(n, dtype=bool)

            def read_frame(s):
                try:
                    buf = readers[s].read_at(off, frame)
                except Exception:  # noqa: BLE001 - dead shard (the
                    # remote plane raises StorageError, not OSError)
                    return None
                return buf if len(buf) == frame else None

            live = [
                s
                for s in range(n)
                if s < len(readers) and readers[s] is not None
            ]
            # survivors read concurrently, one iopool queue per disk
            # (the heal twin of the decode fan-out)
            results = _fanout_reads(read_frame, live, readers, frame)
            for s, buf in zip(live, results):
                if buf is None:
                    continue
                digests[0, s] = bitrot.digest_from_bytes(
                    buf[: bitrot.DIGEST_SIZE]
                )
                shards[0, s] = np.frombuffer(
                    buf[bitrot.DIGEST_SIZE :], dtype=np.uint8
                )
                present[s] = True
            # fused GET-side pass: digest checks + survivor decode in
            # one memory pass over the frames (CpuBackend runs it as a
            # single native call; TpuBackend under fused1 runs it as
            # ONE device launch - codec_step.verify_and_reconstruct_
            # words / mesh_verify_reconstruct - and composes the
            # legacy verify + reconstruct pair only as the bisection
            # oracle, MINIO_TPU_CODEC_KERNEL=legacy)
            try:
                data, ok = be.reconstruct_and_verify(
                    shards, digests, present, k, m
                )  # data (1, k, L)
            except ValueError:
                ok = (be.verify(shards, digests)[0]) & present
                raise QuorumError(
                    f"heal: {int(ok.sum())}/{n} shards intact, need {k}"
                ) from None
            parity, new_digests = be.encode(data, m)
            full = np.concatenate([data, parity], axis=1)[0]
            for s in range(n):
                w = writers[s] if s < len(writers) else None
                if w is None:
                    continue
                frame_bytes = bitrot.digest_to_bytes(new_digests[0, s])
                w.write(frame_bytes + full[s].tobytes())
        KERNEL_STATS.record_stream("heal", total_length)


def _read_full(reader, size: int) -> bytes:
    """Read exactly size bytes unless EOF (io.ReadFull semantics)."""
    chunks = []
    got = 0
    while got < size:
        chunk = reader.read(size - got)
        if not chunk:
            break
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)
