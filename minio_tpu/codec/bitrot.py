"""Bitrot protection: algorithm registry + streaming shard-file framing.

Mirrors the reference's bitrot framework (cmd/bitrot.go:41-58 registry,
cmd/bitrot-streaming.go interleaved framing) with a TPU-native default:

* ``phash256`` (default): the parallel digest computed on-device in the
  same fused pass as erasure encode (ops/hash.py).  Streaming algorithm -
  one 32-byte digest is interleaved before every shard block:
  ``[digest][block][digest][block]...`` exactly like
  streamingBitrotWriter (bitrot-streaming.go:38-88).
* ``sha256`` / ``blake2b512``: host hashlib algorithms, whole-file mode,
  kept for parity with the reference registry (bitrot.go:24-39).

Shard blocks are zero-padded to 32-byte multiples (device word/tile
alignment); the pad is part of the hashed payload, and true lengths are
recovered from object size metadata at decode.

All algorithms here are INTEGRITY checksums against accidental bitrot,
not MACs: phash256's keys are public (like the reference's hard-coded
HighwayHash key, bitrot.go:41-58) and sha256/blake2b are unkeyed, so a
deliberate on-disk forger is out of scope by design - see the threat
model in ops/hash.py for the full rationale and the keyed escape hatch.
"""

from __future__ import annotations

import hashlib

import numpy as np

from ..ops import hash as phash

DIGEST_SIZE = 32
ALIGN = 32  # shard blocks padded to this; also the digest frame size

# registry: name -> (streaming?, factory for whole-file mode)
_ALGORITHMS = {
    "phash256": (True, None),
    "sha256": (False, hashlib.sha256),
    "blake2b512": (False, hashlib.blake2b),
}

DEFAULT_ALGORITHM = "phash256"


def algorithms() -> list[str]:
    return list(_ALGORITHMS)


def is_streaming(name: str) -> bool:
    try:
        return _ALGORITHMS[name][0]
    except KeyError:
        raise ValueError(f"unknown bitrot algorithm {name!r}") from None


def whole_file_digest(name: str, payload: bytes) -> bytes:
    """Whole-file digest for non-streaming algorithms."""
    streaming, factory = _ALGORITHMS[name]
    if streaming:
        return phash.phash256_host(payload)
    return factory(payload).digest()


def pad_block(data: bytes) -> bytes:
    """Zero-pad a shard block to the device alignment."""
    rem = len(data) % ALIGN
    return data if rem == 0 else data + b"\0" * (ALIGN - rem)


def padded_len(n: int) -> int:
    return (n + ALIGN - 1) // ALIGN * ALIGN


def frame_size(shard_block_len: int) -> int:
    """Bytes one framed shard block occupies on disk (digest + padding).

    The analogue of the per-block accounting in bitrotShardFileSize
    (cmd/bitrot.go:140-145).
    """
    return DIGEST_SIZE + padded_len(shard_block_len)


def digest_to_bytes(d: np.ndarray) -> bytes:
    """(8,) uint32 device digest -> 32-byte frame."""
    return np.ascontiguousarray(d, dtype=np.uint32).tobytes()


def digest_from_bytes(b: bytes) -> np.ndarray:
    if len(b) != DIGEST_SIZE:
        raise ValueError(f"bad digest frame length {len(b)}")
    return np.frombuffer(b, dtype=np.uint32).copy()


def verify_block(payload: bytes, digest_frame: bytes) -> bool:
    """Host-side single-block verification (tools, tests, heal spot checks).

    The hot read path verifies in one batched device pass instead
    (codec backend verify()).
    """
    return phash.phash256_host(payload) == digest_frame
