"""Codec kernel telemetry: what the fused device passes actually did.

The paper's thesis lives in the byte-crunching hot paths (RS erasure,
bitrot hashing) running as batched device passes behind the
``reedsolomon.Encoder``-shaped seam (backend.py).  This module measures
those passes in production:

* ``KernelStats`` - process-wide registry of per-op counters: calls,
  bytes processed, and host-observed device seconds, labeled by the
  resolved backend (``tpu``/``cpu``); plus batcher occupancy (jobs
  coalesced per flush, queue wait) and erasure-stream totals.
* ``InstrumentedBackend`` - a CodecBackend decorator recording every
  encode / encode_begin-end / digest / reconstruct /
  reconstruct_and_verify through the seam.
  It wraps the CONCRETE backend (below the batching layer), so a
  coalesced flush counts as one call and its seconds are real device
  launch time, not queue wait - queue wait is the batcher's own series.

"Device seconds" are host-observed: the time the calling thread spends
inside the codec call (for the async begin/end pair, dispatch time plus
materialization time).  On a host-only backend that IS compute time; on
a device backend it includes H2D/D2H transfers - which is exactly the
cost an operator provisioning the serving path cares about.

Everything is exported as ``miniotpu_codec_*`` Prometheus families
(server/metrics.py) and snapshot-dumpable via ``admin kernel-stats``
(server/admin.py).
"""

from __future__ import annotations

import threading
import time

from .backend import CodecBackend


class KernelStats:
    """Thread-safe registry of codec hot-path counters."""

    def __init__(self):
        self._mu = threading.Lock()
        # (op, backend) -> [calls, bytes, seconds]
        self._ops: "dict[tuple[str, str], list]" = {}
        # batcher occupancy: flushes, jobs, blocks, queue-wait seconds
        self._batch = [0, 0, 0, 0.0]
        # erasure-layer streams: kind -> [streams, bytes]
        self._streams: "dict[str, list]" = {}
        self._heal_required = 0
        # per-stream stage breakdown: (op, stage) -> [streams, seconds]
        # op in {"put","get"}, stage in {"assemble","codec",
        # "codec_fused","disk"} - codec_fused is encode time on a
        # backend whose parity+digest pass is fused (erasure._codec_stage)
        self._stages: "dict[tuple[str, str], list]" = {}
        # iopool fan-out plane: queue -> [jobs, bytes, busy_seconds]
        self._iopool: "dict[str, list]" = {}
        self._iopool_depth_hwm = 0
        self._iopool_slowest_s = 0.0
        # hedged shard reads: kind in {launched, won, wasted}
        self._hedge: "dict[str, int]" = {}
        # device->host readback by plane: plane -> [transfers, bytes];
        # plane in {"data", "parity"} (digests ride the data plane).
        # The parity-plane PUT restructure exists to drive the parity
        # row of this table to the post-ack drain band only
        self._d2h: "dict[str, list]" = {}
        # host->device staging by plane (mirror of _d2h), and sub-chunk
        # overlap windows by plane: a "window" is one sub-chunk whose
        # transfer was in flight while a neighbor's compute ran — the
        # snapshot-level proof the DMA pipeline actually overlapped
        # (PR 18), not an inference from wall clock
        self._h2d: "dict[str, list]" = {}
        self._overlap: "dict[str, int]" = {}
        # device-program launches by jitted entry point: the fused1
        # acceptance gate (legacy PUT seam = 3 passes/batch, fused1 = 1)
        self._passes: "dict[str, int]" = {}
        # submesh placement: outcome ("span"|"route") -> batches, and
        # per-submesh in-flight depth (current + high-water mark)
        self._placement: "dict[str, int]" = {}
        self._submesh_depth: "dict[str, int]" = {}
        self._submesh_depth_hwm: "dict[str, int]" = {}

    # -- recording --------------------------------------------------------

    def record_op(
        self, op: str, backend: str, nbytes: int, seconds: float
    ) -> None:
        with self._mu:
            row = self._ops.setdefault((op, backend), [0, 0, 0.0])
            row[0] += 1
            row[1] += nbytes
            row[2] += seconds

    def record_batch_flush(
        self, jobs: int, blocks: int, wait_s: float
    ) -> None:
        with self._mu:
            self._batch[0] += 1
            self._batch[1] += jobs
            self._batch[2] += blocks
            self._batch[3] += wait_s

    def record_stream(self, kind: str, nbytes: int) -> None:
        with self._mu:
            row = self._streams.setdefault(kind, [0, 0])
            row[0] += 1
            row[1] += nbytes

    def record_heal_required(self) -> None:
        with self._mu:
            self._heal_required += 1

    def record_d2h(self, plane: str, nbytes: int) -> None:
        """One device->host codec transfer (plane = data|parity)."""
        with self._mu:
            row = self._d2h.setdefault(plane, [0, 0])
            row[0] += 1
            row[1] += nbytes

    def record_h2d(self, plane: str, nbytes: int) -> None:
        """One host->device codec staging transfer (plane = data|parity)."""
        with self._mu:
            row = self._h2d.setdefault(plane, [0, 0])
            row[0] += 1
            row[1] += nbytes

    def record_overlap_windows(self, plane: str, windows: int) -> None:
        """``windows`` sub-chunks (or in-kernel tile steps) whose
        transfer overlapped a neighbor's compute, keyed by direction:
        plane = put (encode side) | get (verify/reconstruct side)."""
        with self._mu:
            self._overlap[plane] = self._overlap.get(plane, 0) + windows

    def record_pass(self, kernel: str) -> None:
        """One device-program launch (jitted codec pass) by entry-point
        name — backend.py records these at every launch site."""
        with self._mu:
            self._passes[kernel] = self._passes.get(kernel, 0) + 1

    def record_stages(self, op: str, stages: "dict[str, float]") -> None:
        """One stream's stage breakdown (assemble / codec / disk)."""
        with self._mu:
            for stage, seconds in stages.items():
                row = self._stages.setdefault((op, stage), [0, 0.0])
                row[0] += 1
                row[1] += seconds

    def record_io_job(
        self, queue: str, nbytes: int, seconds: float, depth: int
    ) -> None:
        """One completed iopool job; ``depth`` is the queue's backlog
        at dequeue (the slowest-disk signal: a healthy disk drains to
        zero, a straggler's queue stays deep)."""
        with self._mu:
            row = self._iopool.setdefault(queue, [0, 0, 0.0])
            row[0] += 1
            row[1] += nbytes
            row[2] += seconds
            if depth > self._iopool_depth_hwm:
                self._iopool_depth_hwm = depth
            if seconds > self._iopool_slowest_s:
                self._iopool_slowest_s = seconds

    def record_hedge(self, kind: str) -> None:
        """One hedged-read event: ``launched`` (duplicate read fired),
        ``won`` (the hedge produced intact shard cells), ``wasted``
        (abandoned without contributing)."""
        with self._mu:
            self._hedge[kind] = self._hedge.get(kind, 0) + 1

    def record_io_depth(self, queue: str, depth: int) -> None:
        """Queue depth observed at enqueue (high-water mark only)."""
        with self._mu:
            if depth > self._iopool_depth_hwm:
                self._iopool_depth_hwm = depth

    def record_placement(self, outcome: str) -> None:
        """One batch placement decision (outcome = span|route)."""
        with self._mu:
            self._placement[outcome] = self._placement.get(outcome, 0) + 1

    def record_submesh_depths(self, depths: "dict[str, int]") -> None:
        """Live per-submesh queue depths from the placement router."""
        with self._mu:
            for name, depth in depths.items():
                self._submesh_depth[name] = depth
                if depth > self._submesh_depth_hwm.get(name, 0):
                    self._submesh_depth_hwm[name] = depth

    # -- reading ----------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-friendly dump (admin kernel-stats, bench.py trajectory)."""
        with self._mu:
            return {
                "ops": [
                    {
                        "op": op,
                        "backend": be,
                        "calls": calls,
                        "bytes": nbytes,
                        "seconds": round(secs, 6),
                    }
                    for (op, be), (calls, nbytes, secs) in sorted(
                        self._ops.items()
                    )
                ],
                "batch": {
                    "flushes": self._batch[0],
                    "jobs": self._batch[1],
                    "blocks": self._batch[2],
                    "wait_seconds": round(self._batch[3], 6),
                },
                "streams": [
                    {"kind": kind, "streams": n, "bytes": nbytes}
                    for kind, (n, nbytes) in sorted(
                        self._streams.items()
                    )
                ],
                "heal_required": self._heal_required,
                "d2h": [
                    {"plane": plane, "transfers": n, "bytes": nbytes}
                    for plane, (n, nbytes) in sorted(self._d2h.items())
                ],
                "h2d": [
                    {"plane": plane, "transfers": n, "bytes": nbytes}
                    for plane, (n, nbytes) in sorted(self._h2d.items())
                ],
                "overlap_windows": {
                    plane: self._overlap.get(plane, 0)
                    for plane in ("put", "get")
                },
                "device_passes": dict(sorted(self._passes.items())),
                "parity_cache": _parity_cache_stats(),
                "hedge": {
                    kind: self._hedge.get(kind, 0)
                    for kind in ("launched", "won", "wasted")
                },
                "stages": [
                    {
                        "op": op,
                        "stage": stage,
                        "streams": n,
                        "seconds": round(secs, 6),
                    }
                    for (op, stage), (n, secs) in sorted(
                        self._stages.items()
                    )
                ],
                "placement": {
                    outcome: self._placement.get(outcome, 0)
                    for outcome in ("span", "route")
                },
                "submeshes": [
                    {
                        "submesh": name,
                        "depth": self._submesh_depth.get(name, 0),
                        "depth_hwm": hwm,
                    }
                    for name, hwm in sorted(
                        self._submesh_depth_hwm.items()
                    )
                ],
                "iopool": {
                    "queues": [
                        {
                            "queue": q,
                            "jobs": jobs,
                            "bytes": nbytes,
                            "busy_seconds": round(busy, 6),
                        }
                        for q, (jobs, nbytes, busy) in sorted(
                            self._iopool.items()
                        )
                    ],
                    "depth_hwm": self._iopool_depth_hwm,
                    "slowest_job_seconds": round(
                        self._iopool_slowest_s, 6
                    ),
                },
            }

    def reset(self) -> None:
        with self._mu:
            self._ops.clear()
            self._batch = [0, 0, 0, 0.0]
            self._streams.clear()
            self._heal_required = 0
            self._stages.clear()
            self._iopool.clear()
            self._iopool_depth_hwm = 0
            self._iopool_slowest_s = 0.0
            self._hedge.clear()
            self._d2h.clear()
            self._h2d.clear()
            self._overlap.clear()
            self._passes.clear()
            self._placement.clear()
            self._submesh_depth.clear()
            self._submesh_depth_hwm.clear()


def _parity_cache_stats() -> dict:
    """Live occupancy of the device parity-plane cache (backend.py) —
    read at snapshot time, not accumulated here, because the cache is
    its own source of truth for current occupancy."""
    from . import backend as backend_mod

    return backend_mod.parity_cache_stats()


# Process-wide singleton: one codec seam per process (backend.py caches
# one backend), so one registry; tests reset() it.
KERNEL_STATS = KernelStats()


class InstrumentedBackend(CodecBackend):
    """CodecBackend decorator feeding a KernelStats registry.

    ``name`` mirrors the inner backend so layers keying behavior off it
    (the batcher's power-of-two padding for ``tpu``) are unaffected.
    ``verify`` is inherited from CodecBackend on purpose: the default
    routes through ``self.digest`` and is therefore recorded.
    """

    def __init__(self, inner: CodecBackend, stats: "KernelStats | None" = None):
        self.inner = inner
        self.stats = stats if stats is not None else KERNEL_STATS
        self.name = getattr(inner, "name", "unknown")

    @property
    def fused_encode(self):  # type: ignore[override]
        # live delegation, not an __init__ snapshot: CpuBackend demotes
        # this when its native build fails mid-process
        return getattr(self.inner, "fused_encode", False)

    def _timed(self, op: str, nbytes: int, fn):
        t0 = time.monotonic()
        try:
            return fn()
        finally:
            self.stats.record_op(
                op, self.name, nbytes, time.monotonic() - t0
            )

    def encode(self, data, parity_shards):
        return self._timed(
            "encode",
            data.nbytes,
            lambda: self.inner.encode(data, parity_shards),
        )

    def encode_begin(self, data, parity_shards):
        # async pair: dispatch time here, materialization time in
        # encode_end; recorded once, at end, as one encode call
        t0 = time.monotonic()
        handle = self.inner.encode_begin(data, parity_shards)
        return ("ktel", handle, time.monotonic() - t0, data.nbytes)

    def encode_end(self, handle):
        if not (
            isinstance(handle, tuple)
            and len(handle) == 4
            and handle[0] == "ktel"
        ):
            return self.inner.encode_end(handle)
        _tag, inner_handle, dispatch_s, nbytes = handle
        t0 = time.monotonic()
        try:
            return self.inner.encode_end(inner_handle)
        finally:
            self.stats.record_op(
                "encode",
                self.name,
                nbytes,
                dispatch_s + (time.monotonic() - t0),
            )

    def encode_digest_begin(self, data, parity_shards):
        # digest-only twin of the encode pair: same one-call recording
        # at end, under the op name "encode_digest" so the readback
        # restructure shows up as its own series next to "encode"
        t0 = time.monotonic()
        handle = self.inner.encode_digest_begin(data, parity_shards)
        return ("ktel", handle, time.monotonic() - t0, data.nbytes)

    def encode_digest_end(self, handle):
        if not (
            isinstance(handle, tuple)
            and len(handle) == 4
            and handle[0] == "ktel"
        ):
            return self.inner.encode_digest_end(handle)
        _tag, inner_handle, dispatch_s, nbytes = handle
        t0 = time.monotonic()
        try:
            return self.inner.encode_digest_end(inner_handle)
        finally:
            self.stats.record_op(
                "encode_digest",
                self.name,
                nbytes,
                dispatch_s + (time.monotonic() - t0),
            )

    def parity_cache_pressure(self) -> float:
        return self.inner.parity_cache_pressure()

    def placement_router(self):
        # explicit delegation (this wrapper has no __getattr__): the
        # batcher feature-detects the routing seam through it
        return self.inner.placement_router()

    def digest(self, shards):
        return self._timed(
            "digest", shards.nbytes, lambda: self.inner.digest(shards)
        )

    def reconstruct(self, shards, present, data_shards, parity_shards):
        return self._timed(
            "reconstruct",
            shards.nbytes,
            lambda: self.inner.reconstruct(
                shards, present, data_shards, parity_shards
            ),
        )

    def reconstruct_and_verify(
        self, shards, digests, present, data_shards, parity_shards
    ):
        # explicit delegation: the CodecBackend default would compose
        # self.verify + self.reconstruct and silently bypass the
        # inner backend's fused single-pass implementation
        return self._timed(
            "reconstruct_and_verify",
            shards.nbytes,
            lambda: self.inner.reconstruct_and_verify(
                shards, digests, present, data_shards, parity_shards
            ),
        )


def instrument(
    backend: CodecBackend, stats: "KernelStats | None" = None
) -> CodecBackend:
    """Wrap a concrete backend with kernel telemetry (idempotent).

    MINIO_TPU_NO_INSTRUMENT=1 returns the backend bare — used by
    `bench.py --no-instrument` to measure the codec without the
    per-op timing/accounting wrapper in the loop.
    """
    import os

    if os.environ.get("MINIO_TPU_NO_INSTRUMENT") == "1":
        return backend
    if isinstance(backend, InstrumentedBackend):
        return backend
    return InstrumentedBackend(backend, stats)
