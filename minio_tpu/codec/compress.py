"""Transparent object compression (the S2 seam,
cmd/object-api-utils.go:434 isCompressible + :686 decompress-skip).

The stored representation is a raw-deflate stream (zlib level 1 - the
speed-over-ratio point S2 occupies in the reference); the erasure codec
and bitrot framing below this layer see only stored bytes, so heal and
verify are untouched.  Range reads decompress from the stream start and
discard up to the requested offset, exactly the reference's
decompress+skip semantics.

Metadata contract (rides FileInfo.metadata like X-Minio-Internal-*):
  x-internal-compression  = "deflate/v1"
  x-internal-actual-size  = original (client-visible) byte count
"""

from __future__ import annotations

import os
import zlib

import numpy as np

ALGORITHM = "deflate/v1"
META_COMPRESSION = "x-internal-compression"
META_ACTUAL_SIZE = "x-internal-actual-size"
MIN_COMPRESS_SIZE = 4 << 10  # tiny objects gain nothing

# extensions/types that are already entropy-coded
# (cmd/config/compress standard excludes)
EXCLUDED_EXTENSIONS = frozenset(
    {
        ".gz", ".bz2", ".zip", ".rar", ".7z", ".xz", ".zst", ".lz4",
        ".mp4", ".mkv", ".mov", ".avi", ".webm",
        ".mp3", ".aac", ".ogg", ".flac",
        ".jpg", ".jpeg", ".png", ".gif", ".webp", ".heic",
        ".pdf", ".docx", ".xlsx", ".pptx",
    }
)
EXCLUDED_TYPE_PREFIXES = ("video/", "audio/", "image/")
EXCLUDED_TYPES = frozenset(
    {
        "application/zip", "application/gzip", "application/x-gzip",
        "application/x-bzip2", "application/x-xz", "application/zstd",
        "application/x-7z-compressed", "application/x-rar-compressed",
        "application/pdf",
    }
)


def enabled() -> bool:
    """Global compression switch (the MINIO_COMPRESS config seam).

    Read per call so the object layer - where the per-write decision
    lives, covering PUT, POST-policy, multipart and copy alike - always
    sees the current configuration."""
    return os.environ.get("MINIO_TPU_COMPRESS", "off") == "on"


def should_compress(key: str, content_type: str, size: int) -> bool:
    """The single write-path predicate: global switch AND per-object
    compressibility.  Shared by PUT and multipart so both paths always
    agree on whether a given key gets compressed."""
    return enabled() and is_compressible(key, content_type, size)


def strip_internal_meta(meta: dict) -> dict:
    """Remove the compression markers before re-storing data that was
    read back decompressed (CopyObject pipes plaintext)."""
    meta.pop(META_COMPRESSION, None)
    meta.pop(META_ACTUAL_SIZE, None)
    return meta


def is_compressible(key: str, content_type: str, size: int) -> bool:
    """Whether a PUT should be transparently compressed
    (isCompressible, object-api-utils.go:434)."""
    if 0 <= size < MIN_COMPRESS_SIZE:
        return False
    dot = key.rfind(".")
    if dot >= 0 and key[dot:].lower() in EXCLUDED_EXTENSIONS:
        return False
    ct = (content_type or "").split(";")[0].strip().lower()
    if ct in EXCLUDED_TYPES:
        return False
    if ct.startswith(EXCLUDED_TYPE_PREFIXES):
        return False
    return True


# -- device parity transport (the fused on-device compression leg) -------
#
# The stored representation above is untouched: shard files hold the
# exact same framed bytes either way.  What compresses here is the BUS
# TRANSFER — the parity plane crossing device->host during the lazy
# drain (codec/backend.py).  Parity of compressible/zero-padded objects
# is mostly zero groups, so ops/codec_step.pack_nonzero_groups compacts
# the nonzero groups to the front on device and only flags + the packed
# prefix cross PCIe; unpack_nonzero_groups below restores the full
# plane host-side, bit-identically.

# words per transport group (1 KiB of parity per flag bit)
PARITY_GROUP_WORDS = 256


def device_compress_mode() -> str:
    """MINIO_TPU_DEVICE_COMPRESS = auto|on|off (default auto).

    auto: screen with ops/codec_step.group_flags and pack only when the
    nonzero fill is below parity_fill_threshold(); on: always pack;
    off: every drain moves the full plane.
    """
    v = os.environ.get("MINIO_TPU_DEVICE_COMPRESS", "auto").lower()
    return v if v in ("auto", "on", "off") else "auto"


def parity_fill_threshold() -> float:
    """Max nonzero-group fill ratio at which auto mode still packs
    (MINIO_TPU_DCOMP_MAX_FILL, default 0.75): past this the packed
    prefix approaches the full plane and the extra device pass loses."""
    try:
        v = float(os.environ.get("MINIO_TPU_DCOMP_MAX_FILL") or 0.75)
    except ValueError:
        v = 0.75
    return min(1.0, max(0.0, v))


def prefix_keep(kept: int, groups: int) -> int:
    """Packed-prefix length (in groups) to move over the bus.

    Rounded up to a power of two so each distinct D2H slice shape is
    its own compiled gather and the shape zoo stays O(log g).  Shared
    by both drain paths (the legacy pack-at-drain kernel and the
    fused1 precomputed planes), so the two can never round differently
    and break bit-identity of the unpacked result.
    """
    if kept <= 0:
        return 0
    return min(1 << (kept - 1).bit_length(), groups)


def unpack_nonzero_groups(
    flags: np.ndarray, packed_prefix: np.ndarray, group: int, w: int
) -> np.ndarray:
    """Invert ops/codec_step.pack_nonzero_groups on the host.

    ``flags`` is the (..., g) bool mask, ``packed_prefix`` the leading
    (..., >=max_kept*group) u32 slice of the packed rows that actually
    crossed the bus.  Returns the full (..., w) u32 rows: packed groups
    scattered back to their np.nonzero(flags) positions, zeros elsewhere.
    """
    flags = np.asarray(flags, dtype=bool)
    lead = flags.shape[:-1]
    g = flags.shape[-1]
    if g * group != w:
        raise ValueError("flags width disagrees with w/group")
    prefix = np.ascontiguousarray(packed_prefix, dtype=np.uint32)
    out = np.zeros(lead + (g, group), dtype=np.uint32)
    flat_flags = flags.reshape(-1, g)
    flat_prefix = prefix.reshape(len(flat_flags), -1)
    flat_out = out.reshape(-1, g, group)
    for r in range(len(flat_flags)):
        nz = np.nonzero(flat_flags[r])[0]
        if nz.size:
            flat_out[r, nz] = flat_prefix[
                r, : nz.size * group
            ].reshape(nz.size, group)
    return out.reshape(lead + (w,))


class CompressReader:
    """Pull-style compressor: read(n) returns stored (deflate) bytes
    while draining the original stream underneath (so an inner
    HashReader still sees and hashes the client payload)."""

    def __init__(self, inner, chunk: int = 1 << 20):
        self._inner = inner
        self._chunk = chunk
        self._z = zlib.compressobj(1, zlib.DEFLATED, -15)
        self._buf = bytearray()
        self._eof = False

    def read(self, n: int = -1) -> bytes:
        while not self._eof and (n < 0 or len(self._buf) < n):
            raw = self._inner.read(self._chunk)
            if not raw:
                self._buf += self._z.flush()
                self._eof = True
                break
            self._buf += self._z.compress(raw)
        if n < 0 or n >= len(self._buf):
            out = bytes(self._buf)
            self._buf.clear()
            return out
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


class RangeSatisfied(Exception):
    """Control-flow signal: the requested range is fully written, the
    caller may stop reading/decoding stored bytes (early exit)."""


_INFLATE_CHUNK = 1 << 20


class DecompressWriter:
    """Push-style decompressor with range skip: stored bytes go in,
    decompressed bytes [offset, offset+length) come out to ``writer``
    (the decompress-and-discard of object-api-utils.go:686-697).

    Inflation is bounded: decompression emits at most 1 MiB at a time
    (a stored block of zeros can inflate thousandfold - one
    unbounded decompress() call would materialize it whole).  Once the
    range is satisfied the next write raises RangeSatisfied so the
    erasure decode can stop paying I/O for the tail.
    """

    def __init__(self, writer, offset: int = 0, length: int = -1):
        self._w = writer
        self._skip = offset
        self._remaining = length
        self._z = zlib.decompressobj(-15)

    @property
    def done(self) -> bool:
        return self._remaining == 0

    def write(self, stored: bytes) -> int:
        if self._remaining == 0:
            raise RangeSatisfied()
        data = self._z.decompress(stored, _INFLATE_CHUNK)
        self._emit(data)
        while self._z.unconsumed_tail and self._remaining != 0:
            data = self._z.decompress(
                self._z.unconsumed_tail, _INFLATE_CHUNK
            )
            self._emit(data)
        return len(stored)

    def _emit(self, data: bytes) -> None:
        if not data:
            return
        if self._skip:
            drop = min(self._skip, len(data))
            self._skip -= drop
            data = data[drop:]
            if not data:
                return
        if self._remaining >= 0:
            data = data[: self._remaining]
            self._remaining -= len(data)
        if data:
            self._w.write(data)

    def finish(self) -> None:
        while self._remaining != 0:
            tail = self._z.unconsumed_tail
            if not tail:
                break
            self._emit(self._z.decompress(tail, _INFLATE_CHUNK))
        if self._remaining == 0:
            # range satisfied: whatever is left in unconsumed_tail must
            # NOT be inflated - a crafted all-zeros stream expands
            # ~1032x and an unbounded flush() would materialize it whole
            return
        self._emit(self._z.flush())
