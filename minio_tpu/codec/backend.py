"""Codec backend seam: the reedsolomon.Encoder-shaped boundary.

The reference hides its codec behind reedsolomon.Encoder constructed at
cmd/erasure-coding.go:54-64; everything above (Erasure.Encode/Decode/Heal)
is codec-agnostic.  This module is that seam for the new framework:

    backend = get_backend()        # MINIO_ERASURE_BACKEND=tpu|cpu|auto

* TpuBackend: batched fused Pallas/JAX device passes (ops/codec_step).
* CpuBackend: native C++ AVX2 nibble-shuffle codec (native/csrc/gf_cpu.cc)
  + vectorized numpy phash256 - the klauspost/reedsolomon-equivalent host
  path, also the fallback when no accelerator is present.

Both produce byte-identical parity and digests; shard files written by one
backend verify and decode under the other.
"""

from __future__ import annotations

import os
import subprocess
import threading

import numpy as np

from ..ops import gf, hash as phash


class CodecBackend:
    """Batched erasure codec + bitrot digest interface.

    Shapes are byte-domain; implementations may view as words internally.
    """

    name = "abstract"

    def encode(self, data: np.ndarray, parity_shards: int):
        """(B, k, L) u8 -> (parity (B, m, L) u8, digests (B, k+m, 8) u32).

        L must be a multiple of 32.  Digest order: data rows then parity.
        """
        raise NotImplementedError

    def reconstruct(
        self,
        shards: np.ndarray,
        present: "tuple[bool, ...]",
        data_shards: int,
        parity_shards: int,
    ) -> np.ndarray:
        """(B, n, L) u8 + survivor mask -> (B, k, L) u8 data rows."""
        raise NotImplementedError

    def digest(self, shards: np.ndarray) -> np.ndarray:
        """(B, n, L) u8 -> (B, n, 8) u32 phash256 digests."""
        raise NotImplementedError

    def verify(self, shards: np.ndarray, digests: np.ndarray) -> np.ndarray:
        """(B, n, L) u8 + (B, n, 8) digests -> (B, n) bool intact mask."""
        return (self.digest(shards) == np.asarray(digests)).all(axis=-1)

    # -- async pipeline seam (erasure-encode.go:73-109 overlap) --------
    #
    # encode_begin enqueues the H2D transfer + device pass and returns
    # an opaque handle WITHOUT synchronizing; encode_end materializes
    # the results.  The streaming encoder keeps exactly one batch in
    # flight so the device works on block-batch k while the host does
    # disk/network I/O for batch k-1 (double buffering).  Host-only
    # backends fall back to eager evaluation - the handle IS the
    # result, and end() is free.

    def encode_begin(self, data: np.ndarray, parity_shards: int):
        return self.encode(data, parity_shards)

    def encode_end(self, handle):
        return handle


class TpuBackend(CodecBackend):
    """Device backend: single-chip fused passes, mesh-parallel when the
    process sees >1 device (the driver's virtual CPU mesh or a real pod
    slice).  The mesh path shards stripes over "stripe" and the k data
    shards over "shard" with an XOR all-reduce (parallel.mesh), mirroring
    the reference's set- and disk-level fan-out (SURVEY.md section 2.4).
    Set MINIO_MESH=0 to force the single-device path.
    """

    name = "tpu"

    def __init__(self):
        self._meshes: dict[tuple[int, int], object] = {}

    def _mesh_for(self, batch: int, k: int):
        """Pick a mesh for this call's geometry, or None for single-device."""
        import jax

        if os.environ.get("MINIO_MESH", "1") == "0":
            return None
        devices = jax.devices()
        if len(devices) <= 1:
            return None
        from ..parallel import mesh as pm

        stripe, shard = pm.pick_axes(len(devices), batch, k)
        key = (stripe, shard)
        m = self._meshes.get(key)
        if m is None:
            m = pm.make_mesh(devices, stripe=stripe, shard=shard)
            self._meshes[key] = m
        return m

    def encode(self, data, parity_shards):
        return self.encode_end(self.encode_begin(data, parity_shards))

    def encode_begin(self, data, parity_shards):
        """Asynchronous start: JAX dispatch is async, so the returned
        device arrays are futures - the H2D copy and the fused pass
        run while the caller streams the PREVIOUS batch to disk."""
        import jax.numpy as jnp

        from ..ops import codec_step

        data = np.ascontiguousarray(data, dtype=np.uint8)
        B, k, L = data.shape
        mesh = self._mesh_for(B, k)
        if mesh is not None:
            # the mesh path synchronizes internally; eager result
            from ..parallel import mesh as pm

            parity_w, digests = pm.mesh_encode_hash(
                mesh, codec_step.host_bytes_to_words(data),
                parity_shards, L,
            )
            return (
                codec_step.host_words_to_bytes(parity_w), digests,
            )
        words = jnp.asarray(codec_step.host_bytes_to_words(data))
        parity_w, digests = codec_step.encode_and_hash_words(
            words, parity_shards, L
        )
        return ("async", parity_w, digests)

    def encode_end(self, handle):
        if not (
            isinstance(handle, tuple)
            and len(handle) == 3
            and isinstance(handle[0], str)
            and handle[0] == "async"
        ):
            return handle
        from ..ops import codec_step

        _tag, parity_w, digests = handle
        parity = codec_step.host_words_to_bytes(np.asarray(parity_w))
        return parity, np.asarray(digests)

    def reconstruct(self, shards, present, data_shards, parity_shards):
        import jax.numpy as jnp

        from ..ops import codec_step

        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        B = shards.shape[0]
        mesh = self._mesh_for(B, data_shards)
        if mesh is not None:
            from ..parallel import mesh as pm

            dw = pm.mesh_reconstruct(
                mesh,
                codec_step.host_bytes_to_words(shards),
                tuple(bool(b) for b in present),
                data_shards,
                parity_shards,
            )
            return codec_step.host_words_to_bytes(dw)
        words = jnp.asarray(codec_step.host_bytes_to_words(shards))
        dw = codec_step.reconstruct_words_batch(
            words, tuple(bool(b) for b in present), data_shards, parity_shards
        )
        return codec_step.host_words_to_bytes(np.asarray(dw))

    def digest(self, shards):
        import jax.numpy as jnp

        from ..ops import codec_step

        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        B, n, L = shards.shape
        mesh = self._mesh_for(B * n, 1)
        if mesh is not None:
            from ..parallel import mesh as pm

            words = codec_step.host_bytes_to_words(shards)
            flat = words.reshape(B * n, -1)
            return pm.mesh_digest(mesh, flat, L).reshape(B, n, 8)
        words = jnp.asarray(codec_step.host_bytes_to_words(shards))
        got = phash.phash256_words_batched(words, L)
        return np.asarray(got)


class CpuBackend(CodecBackend):
    name = "cpu"

    def encode(self, data, parity_shards):
        from ..utils import native

        data = np.ascontiguousarray(data, dtype=np.uint8)
        B, k, L = data.shape
        m = parity_shards
        parity = np.empty((B, m, L), dtype=np.uint8)
        matrix = gf.parity_matrix(k, m)
        for b in range(B):
            parity[b] = native.gf_matmul_cpu(matrix, data[b])
        digests = self.digest(
            np.concatenate([data, parity], axis=1)
        )
        return parity, digests

    def reconstruct(self, shards, present, data_shards, parity_shards):
        from ..utils import native

        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        B = shards.shape[0]
        out = np.empty(
            (B, data_shards, shards.shape[2]), dtype=np.uint8
        )
        pres = np.asarray(present, dtype=bool)
        for b in range(B):
            out[b] = native.reconstruct_cpu(
                shards[b], pres, data_shards, parity_shards
            )
        return out

    # None = untried, False = unavailable (decision cached: the
    # fallback must not re-attempt a failing g++ build per block)
    _native_hash_ok: "bool | None" = None

    def digest(self, shards):
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        L = shards.shape[-1]
        words = shards.view(np.uint32)
        if CpuBackend._native_hash_ok is not False:
            from ..utils import native

            try:
                out = native.phash256_rows(words, L)
                CpuBackend._native_hash_ok = True
                return out
            except (
                OSError,
                AttributeError,  # stale .so without the symbol
                subprocess.CalledProcessError,
            ):
                CpuBackend._native_hash_ok = False
        # no toolchain / stale lib: numpy twin (bit-identical, slower)
        return phash.phash256_host_batched(words, L)


_lock = threading.Lock()
_backend: "CodecBackend | None" = None


def get_backend(name: "str | None" = None) -> CodecBackend:
    """Resolve the codec backend (MINIO_ERASURE_BACKEND=tpu|cpu|auto)."""
    global _backend
    if name is None:
        with _lock:
            if _backend is not None:
                return _backend
            name = os.environ.get("MINIO_ERASURE_BACKEND", "auto")
            _backend = _make(name)
            return _backend
    return _make(name)


def _make(name: str) -> CodecBackend:
    # kernel telemetry wraps the CONCRETE backend, under the batcher:
    # a coalesced flush is one recorded call with real device seconds,
    # while queue wait is the batcher's own series (codec/telemetry.py)
    from .batcher import maybe_wrap
    from .telemetry import instrument

    if name == "cpu":
        return maybe_wrap(instrument(CpuBackend()))
    if name == "tpu":
        return maybe_wrap(instrument(TpuBackend()))
    if name == "auto":
        try:
            import jax

            # any jax backend (tpu or the CPU test platform) works; the
            # device path dispatches pallas-vs-portable internally
            jax.devices()
            return maybe_wrap(instrument(TpuBackend()))
        except Exception:
            return maybe_wrap(instrument(CpuBackend()))
    raise ValueError(f"unknown erasure backend {name!r}")


def reset_backend() -> None:
    """Testing aid: drop the cached backend so env changes take effect."""
    global _backend
    with _lock:
        _backend = None
