"""Codec backend seam: the reedsolomon.Encoder-shaped boundary.

The reference hides its codec behind reedsolomon.Encoder constructed at
cmd/erasure-coding.go:54-64; everything above (Erasure.Encode/Decode/Heal)
is codec-agnostic.  This module is that seam for the new framework:

    backend = get_backend()        # MINIO_ERASURE_BACKEND=tpu|cpu|auto

* TpuBackend: batched fused Pallas/JAX device passes (ops/codec_step).
* CpuBackend: native C++ AVX2 nibble-shuffle codec (native/csrc/gf_cpu.cc)
  + vectorized numpy phash256 - the klauspost/reedsolomon-equivalent host
  path, also the fallback when no accelerator is present.

Both produce byte-identical parity and digests; shard files written by one
backend verify and decode under the other.
"""

from __future__ import annotations

import os
import subprocess
import threading

import numpy as np

from ..ops import gf, hash as phash
from ..utils.log import kv, logger

_log = logger("codec.backend")


def _record_d2h(plane: str, nbytes: int) -> None:
    """Account one device->host transfer (plane = data|parity).

    Lazy import: telemetry imports this module at load, so the reverse
    edge must resolve at call time.
    """
    from .telemetry import KERNEL_STATS

    KERNEL_STATS.record_d2h(plane, int(nbytes))


def _record_pass(kernel: str) -> None:
    """Account one device-program launch (jitted codec pass) by entry
    point name.  The fused1 acceptance gate reads these counters: the
    legacy PUT seam launches three passes per batch (digest encode,
    group_flags, pack_nonzero_groups) and fused1 exactly one."""
    from .telemetry import KERNEL_STATS

    KERNEL_STATS.record_pass(kernel)


def _record_h2d(plane: str, nbytes: int) -> None:
    """Account one host->device codec staging transfer (plane =
    data|parity), the H2D twin of _record_d2h."""
    from .telemetry import KERNEL_STATS

    KERNEL_STATS.record_h2d(plane, int(nbytes))


def _record_overlap(plane: str, windows: int) -> None:
    """Account completed overlap windows (plane = put|get): iterations
    where a transfer provably ran concurrently with compute — the
    snapshot-level evidence the MINIO_TPU_CODEC_OVERLAP pipeline
    engaged (bench --codec-micro gates on this being > 0)."""
    from .telemetry import KERNEL_STATS

    KERNEL_STATS.record_overlap_windows(plane, int(windows))


# Ping-pong staging ledger for the async sub-chunk pipeline: while a
# batch is between encode_digest_begin and _end, TWO sub-chunk staging
# buffers are live on device (the one computing and the one prefetching)
# on top of the parity planes the ParityPlaneCache already accounts.
# Posted to the shared device-byte budget so cache admission sees the
# real headroom (cache/allocator.py).
_staging_bytes = 0


def _stage_reserve(nbytes: int) -> int:
    global _staging_bytes
    nbytes = int(nbytes)
    with _lock:
        _staging_bytes += nbytes
        total = _staging_bytes
    _post_staging(total)
    return nbytes


def _stage_release(nbytes: int) -> None:
    global _staging_bytes
    with _lock:
        _staging_bytes = max(0, _staging_bytes - int(nbytes))
        total = _staging_bytes
    _post_staging(total)


def _post_staging(total: int) -> None:
    try:
        from ..cache.allocator import device_budget

        device_budget().set_usage("codec_staging", total)
    except Exception as exc:  # noqa: BLE001 - must never fail I/O
        _log.debug("staging budget accounting failed: %s", exc)


# ---------------------------------------------------------------------------
# Device-resident parity plane: refs + the bounded write-back cache
# ---------------------------------------------------------------------------


class ParityPlaneCache:
    """Bounded write-back cache of device-resident parity planes.

    One entry per (encode handle, shard-size group) — a ParityRef whose
    bytes still live on the device.  ``add`` evicts FIFO once occupancy
    exceeds the byte budget, and eviction IS the write-back: the victim
    ref drains D2H (outside the cache lock — drain re-enters via
    ``forget``), so a burst of concurrent PUTs can never pin unbounded
    device memory; it just loses laziness for the oldest planes.
    """

    def __init__(self, capacity_bytes: int):
        self._mu = threading.Lock()
        # insertion-ordered (dict preserves it): FIFO eviction
        self._refs: "dict[int, object]" = {}
        self._bytes = 0
        self.capacity = max(1, int(capacity_bytes))
        self.added = 0
        self.evictions = 0

    def _account(self) -> None:
        """Report occupancy to the shared device-byte ledger this plane
        splits with the read cache (cache/allocator.py). Lock held."""
        try:
            from ..cache.allocator import device_budget

            device_budget().set_usage("parity_plane", self._bytes)
        except Exception as exc:  # noqa: BLE001 - must never fail I/O
            _log.debug("parity budget accounting failed: %s", exc)

    def add(self, ref) -> None:
        while True:
            victim = None
            with self._mu:
                if id(ref) not in self._refs:
                    self._refs[id(ref)] = ref
                    self._bytes += ref.nbytes
                    self.added += 1
                    self._account()
                if self._bytes > self.capacity:
                    for r in self._refs.values():
                        if r is not ref:
                            victim = r
                            break
                    if victim is not None:
                        self.evictions += 1
                if victim is None:
                    return  # within budget (or lone oversized plane)
            victim.drain()  # write-back outside the lock; drain forgets

    def forget(self, ref) -> None:
        """Drop a drained/released ref (called by the ref itself)."""
        with self._mu:
            if self._refs.pop(id(ref), None) is not None:
                self._bytes -= ref.nbytes
                self._account()

    def pressure(self) -> float:
        """Occupancy over budget; >= 1.0 means the batcher should back
        off admitting new encodes until drains catch up."""
        with self._mu:
            return self._bytes / self.capacity

    def stats(self) -> dict:
        with self._mu:
            return {
                "capacity_bytes": self.capacity,
                "occupancy_bytes": self._bytes,
                "entries": len(self._refs),
                "added": self.added,
                "evictions": self.evictions,
            }


class _EagerParityRef:
    """ParityRef over host-resident parity (eager/CPU backends): the
    bytes never were on a device, so drain is a handover."""

    __slots__ = ("_parity",)

    def __init__(self, parity_b: np.ndarray):
        self._parity = parity_b

    @property
    def nbytes(self) -> int:
        return 0 if self._parity is None else self._parity.nbytes

    def drain(self) -> np.ndarray:
        return self._parity

    def release(self) -> None:
        self._parity = None


class _DeviceParityRef:
    """One batch's device-resident parity plane ((B, m, w) u32 words).

    ``drain()`` is the single D2H seam: thread-safe and memoized, so
    the m per-disk parity writers sharing this ref pay one transfer —
    and when the transport screen finds the plane sparse, the packed
    prefix (ops/codec_step.pack_nonzero_groups), not the raw plane,
    crosses the bus.  Registered with the ParityPlaneCache until
    drained or released.

    Under the fused1 kernel the occupancy ``flags`` and the prefix
    ``packed`` plane are produced by the SAME pallas_call as the parity
    itself (ops/rs_pallas.encode_pack_fused), so the ref carries them
    and the drain launches ZERO further device passes — it only picks
    which precomputed plane crosses the bus.  The legacy ref (no
    precomputed planes) launches group_flags + pack_nonzero_groups at
    drain time as before.
    """

    __slots__ = (
        "_lk",
        "_cache",
        "_parity_w",
        "_flags",
        "_packed",
        "_group",
        "_host",
        "nbytes",
    )

    def __init__(
        self,
        cache: ParityPlaneCache,
        parity_w,
        flags=None,
        packed=None,
        group: int = 0,
    ):
        self._lk = threading.Lock()
        self._cache = cache
        self._parity_w = parity_w
        self._flags = flags
        self._packed = packed
        self._group = int(group)
        self._host: "np.ndarray | None" = None
        plane = int(
            parity_w.shape[0] * parity_w.shape[1] * parity_w.shape[2] * 4
        )
        # the packed twin is a second device-resident plane of the same
        # size: account it honestly against the write-back budget
        self.nbytes = plane * (2 if packed is not None else 1)
        cache.add(self)

    def drain(self) -> np.ndarray:
        """(B, m, L) uint8 parity bytes, materialized at most once."""
        with self._lk:
            if self._host is None and self._parity_w is not None:
                if self._packed is not None:
                    self._host = self._drain_precomputed(
                        self._parity_w,
                        self._flags,
                        self._packed,
                        self._group,
                    )
                else:
                    self._host = self._drain_d2h(self._parity_w)
                self._parity_w = None
                self._flags = None
                self._packed = None
                self._cache.forget(self)
            return self._host

    def release(self) -> None:
        """Drop an unused plane without the transfer (error-path
        cleanup of handles whose writers were never scheduled)."""
        with self._lk:
            if self._parity_w is not None:
                self._parity_w = None
                self._flags = None
                self._packed = None
                self._cache.forget(self)

    @staticmethod
    def _drain_d2h(parity_w) -> np.ndarray:
        """The one sanctioned eager readback of a parity plane."""
        from ..ops import codec_step
        from . import compress as compmod

        mode = compmod.device_compress_mode()
        w = int(parity_w.shape[-1])
        G = compmod.PARITY_GROUP_WORDS
        g = w // G if w % G == 0 else 0
        if mode != "off" and g >= 2:
            _record_pass("group_flags")
            flags = np.asarray(codec_step.group_flags(parity_w, G))
            kept = int(flags.sum(axis=-1).max()) if flags.size else 0
            if kept == 0:
                _record_d2h("parity", flags.nbytes)
                return np.zeros(
                    parity_w.shape[:-1] + (w * 4,), dtype=np.uint8
                )
            if (
                mode == "on"
                or kept / g <= compmod.parity_fill_threshold()
            ):
                _record_pass("pack_nonzero_groups")
                _f, packed = codec_step.pack_nonzero_groups(parity_w, G)
                keep = compmod.prefix_keep(kept, g)
                prefix = np.asarray(packed[..., : keep * G])
                _record_d2h("parity", flags.nbytes + prefix.nbytes)
                words = compmod.unpack_nonzero_groups(
                    flags, prefix, G, w
                )
                return codec_step.host_words_to_bytes(words)
        parity = np.asarray(parity_w)
        _record_d2h("parity", parity.nbytes)
        return codec_step.host_words_to_bytes(parity)

    @staticmethod
    def _drain_precomputed(parity_w, flags_d, packed_d, group) -> np.ndarray:
        """fused1 drain: occupancy screen + pack came out of the encode
        pallas_call itself, so no device pass launches here — only the
        chosen plane's D2H (flags are a few bytes per row)."""
        from ..ops import codec_step
        from . import compress as compmod

        mode = compmod.device_compress_mode()
        w = int(parity_w.shape[-1])
        g = w // group
        flags = np.asarray(flags_d)  # (B, m, g) bool, tiny
        if mode != "off":
            kept = int(flags.sum(axis=-1).max()) if flags.size else 0
            if kept == 0:
                _record_d2h("parity", flags.nbytes)
                return np.zeros(
                    parity_w.shape[:-1] + (w * 4,), dtype=np.uint8
                )
            if (
                mode == "on"
                or kept / g <= compmod.parity_fill_threshold()
            ):
                keep = compmod.prefix_keep(kept, g)
                prefix = np.asarray(packed_d[..., : keep * group])
                _record_d2h("parity", flags.nbytes + prefix.nbytes)
                words = compmod.unpack_nonzero_groups(
                    flags, prefix, group, w
                )
                return codec_step.host_words_to_bytes(words)
        parity = np.asarray(parity_w)
        _record_d2h("parity", parity.nbytes)
        return codec_step.host_words_to_bytes(parity)


class _SubchunkParityRef:
    """One batch's device-resident parity plane held as the S sub-chunk
    arrays the async overlap pipeline produced (splits along the
    stripe-length axis, MINIO_TPU_CODEC_OVERLAP=async).

    Same contract as _DeviceParityRef: ``drain()`` is the single
    memoized D2H seam shared by the m parity writers, ``release()``
    drops the plane without the transfer, and the ParityPlaneCache
    accounts every live device plane — parity AND the packed twin when
    the pack leg ran — so write-back pressure stays honest about the
    doubled footprint.
    """

    __slots__ = (
        "_lk",
        "_cache",
        "_parity",
        "_flags",
        "_packed",
        "_group",
        "_host",
        "nbytes",
    )

    def __init__(
        self,
        cache: ParityPlaneCache,
        parity_chunks,
        flags=None,
        packed=None,
        group: int = 0,
    ):
        self._lk = threading.Lock()
        self._cache = cache
        self._parity = list(parity_chunks)
        self._flags = list(flags) if flags else None
        self._packed = list(packed) if packed else None
        self._group = int(group)
        self._host: "np.ndarray | None" = None
        plane = sum(
            int(p.shape[0]) * int(p.shape[1]) * int(p.shape[2]) * 4
            for p in self._parity
        )
        self.nbytes = plane * (2 if self._packed is not None else 1)
        cache.add(self)

    def drain(self) -> np.ndarray:
        """(B, m, L) uint8 parity bytes, materialized at most once."""
        with self._lk:
            if self._host is None and self._parity is not None:
                self._host = self._drain_chunks()
                self._parity = None
                self._flags = None
                self._packed = None
                self._cache.forget(self)
            return self._host

    def release(self) -> None:
        """Drop an undrained plane without the transfer."""
        with self._lk:
            if self._parity is not None:
                self._parity = None
                self._flags = None
                self._packed = None
                self._cache.forget(self)

    def _drain_chunks(self) -> np.ndarray:
        """Per-chunk D2H, concatenated along the length axis.

        Each chunk reuses the fused1 drain bodies — the occupancy
        screen picks the packed prefix or the raw plane per chunk, so
        a sparse chunk of an otherwise dense plane still crosses the
        bus compressed.  Chunk reads are independent async device
        values: reading chunk s overlaps the device-side screen of
        chunk s+1.
        """
        parts = [
            (
                _DeviceParityRef._drain_precomputed(
                    p, self._flags[i], self._packed[i], self._group
                )
                if self._packed is not None
                else _DeviceParityRef._drain_d2h(p)
            )
            for i, p in enumerate(self._parity)
        ]
        return np.concatenate(parts, axis=-1)


_PARITY_CACHE: "ParityPlaneCache | None" = None


def parity_plane_cache() -> ParityPlaneCache:
    """The process-wide parity cache (MINIO_TPU_PARITY_CACHE_MB,
    default 128 MiB; env read once at creation, reset_backend() drops
    it so tests can resize)."""
    global _PARITY_CACHE
    c = _PARITY_CACHE
    if c is None:
        with _lock:
            if _PARITY_CACHE is None:
                try:
                    mb = float(
                        os.environ.get("MINIO_TPU_PARITY_CACHE_MB")
                        or 128
                    )
                except ValueError:
                    mb = 128.0
                _PARITY_CACHE = ParityPlaneCache(int(mb * (1 << 20)))
            c = _PARITY_CACHE
    return c


def parity_cache_stats() -> dict:
    """Occupancy/eviction counters for telemetry (zeros before first use)."""
    c = _PARITY_CACHE
    if c is None:
        return {
            "capacity_bytes": 0,
            "occupancy_bytes": 0,
            "entries": 0,
            "added": 0,
            "evictions": 0,
        }
    return c.stats()


def parity_cache_pressure() -> float:
    """Cache pressure without forcing the singleton into existence."""
    c = _PARITY_CACHE
    return 0.0 if c is None else c.pressure()


class _AsyncHandle:
    """Mutable in-flight encode handle.

    ``consumed``/``result`` make encode_end IDEMPOTENT: error-path
    cleanup racing the normal consume gets the first call's result back
    instead of re-materializing (or corrupting wrapper bookkeeping).
    Single-threaded consumption is the contract — the erasure layer's
    _Begun records serialize end() per handle.
    """

    __slots__ = ("kind", "payload", "consumed", "result")

    def __init__(self, kind: str, payload):
        self.kind = kind
        self.payload = payload
        self.consumed = False
        self.result = None


class CodecBackend:
    """Batched erasure codec + bitrot digest interface.

    Shapes are byte-domain; implementations may view as words internally.
    """

    name = "abstract"

    # True when encode() computes parity and digests in one fused pass
    # over the bytes (TPU device pass, native single-pass CPU kernel).
    # The erasure layer keys its stage accounting on this so the fused
    # time shows up as "codec_fused" in put_stages breakdowns.
    fused_encode = False

    def encode(self, data: np.ndarray, parity_shards: int):
        """(B, k, L) u8 -> (parity (B, m, L) u8, digests (B, k+m, 8) u32).

        L must be a multiple of 32.  Digest order: data rows then parity.
        """
        raise NotImplementedError

    def reconstruct(
        self,
        shards: np.ndarray,
        present: "tuple[bool, ...]",
        data_shards: int,
        parity_shards: int,
    ) -> np.ndarray:
        """(B, n, L) u8 + survivor mask -> (B, k, L) u8 data rows."""
        raise NotImplementedError

    def digest(self, shards: np.ndarray) -> np.ndarray:
        """(B, n, L) u8 -> (B, n, 8) u32 phash256 digests."""
        raise NotImplementedError

    def verify(self, shards: np.ndarray, digests: np.ndarray) -> np.ndarray:
        """(B, n, L) u8 + (B, n, 8) digests -> (B, n) bool intact mask."""
        return (self.digest(shards) == np.asarray(digests)).all(axis=-1)

    def reconstruct_and_verify(
        self,
        shards: np.ndarray,
        digests: np.ndarray,
        present: "tuple[bool, ...] | np.ndarray",
        data_shards: int,
        parity_shards: int,
    ) -> "tuple[np.ndarray, np.ndarray]":
        """Verify digests of present shards AND decode the data rows.

        (B, n, L) u8 + (B, n, 8) digests + present mask ->
        (data (B, k, L) u8, ok (B, n) bool).  The returned ok mask
        reflects per-shard digest checks (absent shards are False);
        decode uses only shards that verified intact.  Raises
        ValueError when fewer than k shards verify for some stripe.
        Backends may fuse the two passes; this default composes them.
        """
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        pres = np.asarray(present, dtype=bool)
        ok = self.verify(shards, digests) & pres
        return (
            self._reconstruct_from_ok(
                shards, ok, data_shards, parity_shards
            ),
            ok,
        )

    def _reconstruct_from_ok(self, shards, ok, data_shards, parity_shards):
        """Decode each stripe from its own verified-intact shard set,
        grouping stripes that share a survivor pattern into one
        reconstruct call."""
        B, n, L = shards.shape
        out = np.empty((B, data_shards, L), dtype=np.uint8)
        groups: "dict[tuple[bool, ...], list[int]]" = {}
        for b in range(B):
            if int(ok[b].sum()) < data_shards:
                raise ValueError(
                    f"stripe {b}: {int(ok[b].sum())}/{n} shards intact,"
                    f" need {data_shards}"
                )
            groups.setdefault(tuple(bool(x) for x in ok[b]), []).append(b)
        for pat, idxs in groups.items():
            out[idxs] = self.reconstruct(
                shards[idxs], pat, data_shards, parity_shards
            )
        return out

    # -- async pipeline seam (erasure-encode.go:73-109 overlap) --------
    #
    # encode_begin enqueues the H2D transfer + device pass and returns
    # an opaque handle WITHOUT synchronizing; encode_end materializes
    # the results.  The streaming encoder keeps exactly one batch in
    # flight so the device works on block-batch k while the host does
    # disk/network I/O for batch k-1 (double buffering).  Host-only
    # backends fall back to eager evaluation - the handle IS the
    # result, and end() is free.

    def encode_begin(self, data: np.ndarray, parity_shards: int):
        return self.encode(data, parity_shards)

    def encode_end(self, handle):
        return handle

    # -- digest-only pipeline seam (device-resident parity plane) ------
    #
    # Same begin/end split, but _end eagerly materializes ONLY the
    # digests (all the commit path needs to build bitrot metadata and
    # ack) and returns the parity as a ParityRef whose .drain() is the
    # lazy D2H seam the parity writers pull through behind quorum.
    # Host backends compose the eager defaults below — the "ref" wraps
    # parity that is already host-resident; device backends override to
    # keep the plane on device (TpuBackend).

    def encode_digest_begin(self, data: np.ndarray, parity_shards: int):
        return self.encode_begin(data, parity_shards)

    def encode_digest_end(self, handle):
        """handle -> (digests (B, k+m, 8) u32, parity ref)."""
        parity, digests = self.encode_end(handle)
        return (
            np.asarray(digests),
            _EagerParityRef(
                np.ascontiguousarray(parity, dtype=np.uint8)
            ),
        )

    def parity_cache_pressure(self) -> float:
        """Write-back cache pressure seen by this backend (0.0 when the
        backend keeps nothing device-resident)."""
        return 0.0

    def placement_router(self):
        """Submesh router for multi-chip placement, or None when the
        backend has no device set to carve (host backends, single
        device).  The batcher feature-detects this seam to route
        independent merged batches to disjoint submeshes."""
        return None


class TpuBackend(CodecBackend):
    """Device backend: single-chip fused passes, mesh-parallel when the
    process sees >1 device (the driver's virtual CPU mesh or a real pod
    slice).  The mesh path shards stripes over "stripe" and the k data
    shards over "shard" with an XOR all-reduce (parallel.mesh), mirroring
    the reference's set- and disk-level fan-out (SURVEY.md section 2.4).
    Set MINIO_MESH=0 to force the single-device path.
    """

    name = "tpu"
    fused_encode = True  # ops/codec_step fuses encode+hash on device

    def __init__(self, devices=None):
        # devices=None -> every visible device; an explicit tuple pins
        # the backend to a slice of the machine (bench chip sweeps)
        self._devices = tuple(devices) if devices is not None else None
        self._meshes: dict[tuple, object] = {}
        self._router = None
        self._router_mu = threading.Lock()

    def _base_devices(self) -> tuple:
        import jax

        if self._devices is not None:
            return self._devices
        return tuple(jax.devices())

    def _mesh_for(self, batch: int, k: int):
        """Pick a mesh for this call's geometry, or None for single-device.

        A submesh routed by the batcher (parallel.rules.placed) narrows
        the device set for this thread; otherwise the full base set
        spans.
        """
        if os.environ.get("MINIO_MESH", "1") == "0":
            return None
        from ..parallel import mesh as pm, rules as prules

        devices = prules.current_placement() or self._base_devices()
        if len(devices) <= 1:
            return None
        stripe, shard = pm.pick_axes(len(devices), batch, k)
        # key on device ids, not the tuple of Device objects: cheap and
        # stable across jax.devices() calls
        key = (tuple(int(d.id) for d in devices), stripe, shard)
        m = self._meshes.get(key)
        if m is None:
            m = pm.make_mesh(list(devices), stripe=stripe, shard=shard)
            self._meshes[key] = m
        return m

    def placement_router(self):
        devices = self._base_devices()
        if len(devices) <= 1:
            return None
        with self._router_mu:
            if self._router is None:
                from ..parallel import rules as prules

                self._router = prules.PlacementRouter(devices)
            return self._router

    def encode(self, data, parity_shards):
        return self.encode_end(self.encode_begin(data, parity_shards))

    def encode_begin(self, data, parity_shards):
        """Asynchronous start: JAX dispatch is async, so the returned
        device arrays are futures - the H2D copy and the fused pass
        run while the caller streams the PREVIOUS batch to disk."""
        import jax.numpy as jnp

        from ..ops import codec_step

        data = np.ascontiguousarray(data, dtype=np.uint8)
        B, k, L = data.shape
        mesh = self._mesh_for(B, k)
        if mesh is not None:
            # shard_map dispatch is as async as plain jit: the mesh
            # begin/end split returns device-array futures, so the
            # encode/write overlap survives on the mesh path too
            from ..parallel import mesh as pm

            h = pm.mesh_encode_hash_begin(
                mesh, codec_step.host_bytes_to_words(data),
                parity_shards, L,
            )
            _record_pass("mesh_encode_hash")
            return _AsyncHandle("async-mesh", h)
        words = jnp.asarray(codec_step.host_bytes_to_words(data))
        parity_w, digests = codec_step.encode_and_hash_words(
            words, parity_shards, L
        )
        _record_pass("encode_and_hash_words")
        return _AsyncHandle("async", (parity_w, digests))

    def encode_end(self, handle):
        if not isinstance(handle, _AsyncHandle):
            return handle  # foreign/eager handle: already a result
        if handle.consumed:
            return handle.result
        from ..ops import codec_step

        if handle.kind == "async-mesh":
            from ..parallel import mesh as pm

            parity_w, digests = pm.mesh_encode_hash_end(handle.payload)
            parity_w = np.asarray(parity_w)
            digests = np.asarray(digests)
            _record_d2h("parity", parity_w.nbytes)
            _record_d2h("data", digests.nbytes)
            result = codec_step.host_words_to_bytes(parity_w), digests
        elif handle.kind == "async":
            parity_w, digests = handle.payload
            parity_w = np.asarray(parity_w)
            digests = np.asarray(digests)
            _record_d2h("parity", parity_w.nbytes)
            _record_d2h("data", digests.nbytes)
            result = codec_step.host_words_to_bytes(parity_w), digests
        else:
            raise ValueError(
                f"encode_end: unknown handle kind {handle.kind!r}"
            )
        handle.result = result
        handle.consumed = True
        handle.payload = None  # drop the device refs
        return result

    def encode_digest_begin(self, data, parity_shards):
        """Digest-only start: the fused donated kernel keeps parity on
        device; only the 32-byte digests are scheduled for readback.

        Under MINIO_TPU_CODEC_KERNEL=fused1 (default) the single pass
        additionally emits the occupancy flags and the nonzero-group
        prefix pack, so the eventual drain launches nothing; ``legacy``
        keeps the three-pass structure as the bisection oracle.
        """
        import jax.numpy as jnp

        from ..ops import codec_step

        data = np.ascontiguousarray(data, dtype=np.uint8)
        B, k, L = data.shape
        if self._mesh_for(B, k) is not None:
            if codec_step.codec_overlap_mode() != "off":
                # overlap sub-chunking would fight the mesh "seq" axis
                # for the stripe-length dimension: warn once, fall back
                # to the serialized (bit-identical) mesh path
                from ..parallel import mesh as pm

                pm.warn_overlap_fallback()
            # the mesh path has no device-resident cache (planes live
            # sharded across devices): compose the eager seam, still
            # async through the mesh begin/end split
            return _AsyncHandle(
                "digest-eager", self.encode_begin(data, parity_shards)
            )
        words_h = codec_step.host_bytes_to_words(data)
        if codec_step.codec_kernel_mode() == "fused1":
            from . import compress as compmod

            w = L // 4
            G = compmod.PARITY_GROUP_WORDS
            group = (
                G
                if (
                    compmod.device_compress_mode() != "off"
                    and w % G == 0
                    and w // G >= 2
                )
                else 0
            )
            use_pallas, interpret = codec_step.pallas_dispatch(w)
            overlap = codec_step.codec_overlap_mode()
            if overlap == "async":
                handle = self._encode_subchunk_begin(
                    words_h, parity_shards, L, group
                )
                if handle is not None:
                    return handle
                # batch too small for S >= 3 sub-chunks: serialized path
            words = jnp.asarray(words_h)
            _record_h2d("data", words.nbytes)
            # pipeline mode rides the SAME entry point and pallas_call;
            # the static only swaps in the manual-DMA kernel body
            pipeline = overlap == "pipeline" and use_pallas
            parity_w, digests, flags_d, packed_d = (
                codec_step.encode_words_fused1(
                    words,
                    parity_shards,
                    L,
                    group=group,
                    formulation=codec_step.codec_formulation(),
                    use_pallas=use_pallas,
                    interpret=interpret,
                    pipeline=pipeline,
                )
            )
            _record_pass("encode_words_fused1")
            if pipeline:
                from ..ops import rs_pallas

                nt = w // rs_pallas._TW
                if nt > 1:
                    # one window per in-kernel tile step whose prefetch
                    # DMA overlapped the previous tile's compute
                    _record_overlap("put", B * (nt - 1))
            return _AsyncHandle(
                "digest-fused1",
                (
                    parity_w,
                    digests,
                    flags_d if group else None,
                    packed_d if group else None,
                    group,
                ),
            )
        words = jnp.asarray(words_h)
        _record_h2d("data", words.nbytes)
        parity_w, digests = codec_step.encode_and_hash_words_digest(
            words, parity_shards, L
        )
        _record_pass("encode_and_hash_words_digest")
        return _AsyncHandle("digest", (parity_w, digests))

    def _encode_subchunk_begin(self, words_h, parity_shards, shard_len, group):
        """MINIO_TPU_CODEC_OVERLAP=async PUT: split the stripe batch
        along w into S sub-chunks and double-buffer them through the
        device — chunk s+1's H2D staging (async jnp.asarray dispatch)
        overlaps chunk s's encode pass, whose donated ping-pong
        accumulator carries the phash256 partials; the LAST chunk
        finalizes the digests in its own program, so the chain launches
        S passes and nothing extra for the digest.

        Returns the in-flight handle, or None when the batch is too
        small to cut S >= 3 chunks (caller takes the serialized path).
        """
        import jax.numpy as jnp

        from ..ops import codec_step
        from .erasure import subchunk_words

        B, k, w = words_h.shape
        m = parity_shards
        cw = subchunk_words(w, group if group else 8)
        if not cw:
            return None
        offs = list(range(0, w, cw))
        # ping-pong staging: two sub-chunk input buffers live at once
        reserved = _stage_reserve(2 * B * k * cw * 4)
        try:
            acc = jnp.zeros((B, k + m, 8), jnp.uint32)
            parity_c, flags_c, packed_c = [], [], []
            for i, off in enumerate(offs):
                end = min(off + cw, w)
                chunk = jnp.asarray(
                    np.ascontiguousarray(words_h[:, :, off:end])
                )
                _record_h2d("data", (end - off) * B * k * 4)
                p_c, acc, f_c, pk_c = codec_step.encode_subchunk_words(
                    chunk,
                    acc,
                    np.uint32(off),
                    m,
                    shard_len,
                    group=group,
                    finalize=i == len(offs) - 1,
                )
                _record_pass("encode_subchunk_words")
                parity_c.append(p_c)
                if group:
                    flags_c.append(f_c)
                    packed_c.append(pk_c)
            _record_overlap("put", len(offs) - 1)
        except BaseException:
            _stage_release(reserved)
            raise
        return _AsyncHandle(
            "digest-subchunk",
            (
                parity_c,
                acc,
                flags_c or None,
                packed_c or None,
                group,
                reserved,
            ),
        )

    def encode_digest_end(self, handle):
        if not isinstance(handle, _AsyncHandle) or handle.kind not in (
            "digest",
            "digest-fused1",
            "digest-subchunk",
            "digest-eager",
        ):
            return super().encode_digest_end(handle)
        if handle.consumed:
            return handle.result
        if handle.kind == "digest-eager":
            parity, digests = self.encode_end(handle.payload)
            result = (
                np.asarray(digests),
                _EagerParityRef(
                    np.ascontiguousarray(parity, dtype=np.uint8)
                ),
            )
        elif handle.kind == "digest-fused1":
            # digests are the ONLY eager readback (MTPU107); parity,
            # flags and packed stay device-resident behind the ref
            parity_w, digests_d, flags_d, packed_d, group = handle.payload
            digests = np.asarray(digests_d)
            _record_d2h("data", digests.nbytes)
            result = (
                digests,
                _DeviceParityRef(
                    parity_plane_cache(),
                    parity_w,
                    flags=flags_d,
                    packed=packed_d,
                    group=group,
                ),
            )
        elif handle.kind == "digest-subchunk":
            # async-overlap twin: same digest-only eager readback; the
            # staging ping-pong reservation drops here — the last
            # chunk's pass has produced everything the ref holds
            (
                parity_c,
                digests_d,
                flags_c,
                packed_c,
                group,
                reserved,
            ) = handle.payload
            # the reservation must drop even when the digest D2H
            # throws (device reset mid-drain): an exception here must
            # not strand staging-ledger bytes for the process lifetime
            try:
                digests = np.asarray(digests_d)
                _record_d2h("data", digests.nbytes)
            finally:
                _stage_release(reserved)
            result = (
                digests,
                _SubchunkParityRef(
                    parity_plane_cache(),
                    parity_c,
                    flags=flags_c,
                    packed=packed_c,
                    group=group,
                ),
            )
        else:
            parity_w, digests_d = handle.payload
            digests = np.asarray(digests_d)
            _record_d2h("data", digests.nbytes)
            result = (
                digests,
                _DeviceParityRef(parity_plane_cache(), parity_w),
            )
        handle.result = result
        handle.consumed = True
        handle.payload = None
        return result

    def drain(self, parity_ref) -> np.ndarray:
        """The lazy readback seam: stream one cached parity plane D2H
        (delegates to the ref — named here so callers/tests have a
        backend surface to drive and the lint exemption a seam name)."""
        return parity_ref.drain()

    def parity_cache_pressure(self) -> float:
        return parity_cache_pressure()

    def reconstruct(self, shards, present, data_shards, parity_shards):
        import jax.numpy as jnp

        from ..ops import codec_step

        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        B = shards.shape[0]
        mesh = self._mesh_for(B, data_shards)
        if mesh is not None:
            from ..parallel import mesh as pm

            dw = pm.mesh_reconstruct(
                mesh,
                codec_step.host_bytes_to_words(shards),
                tuple(bool(b) for b in present),
                data_shards,
                parity_shards,
            )
            _record_pass("mesh_reconstruct")
            return codec_step.host_words_to_bytes(dw)
        words = jnp.asarray(codec_step.host_bytes_to_words(shards))
        dw = codec_step.reconstruct_words_batch(
            words, tuple(bool(b) for b in present), data_shards, parity_shards
        )
        _record_pass("reconstruct_words_batch")
        return codec_step.host_words_to_bytes(np.asarray(dw))

    def reconstruct_and_verify(
        self, shards, digests, present, data_shards, parity_shards
    ):
        """Fused GET-side pass (fused1): digest checks + survivor decode
        in ONE device pass (codec_step.verify_and_reconstruct_words),
        replacing the verify -> reconstruct pair on the quorum-read/heal
        path.  Optimistic like CpuBackend: decode from the first k
        present rows while hashing all of them; on the rare digest
        mismatch among the chosen survivors, re-pick survivors from the
        verified mask and re-solve just the hit stripes.  The legacy
        mode composes the separate passes (bisection oracle)."""
        import jax.numpy as jnp

        from ..ops import codec_step

        if codec_step.codec_kernel_mode() != "fused1":
            return super().reconstruct_and_verify(
                shards, digests, present, data_shards, parity_shards
            )
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        pres = np.asarray(present, dtype=bool)
        B, n, L = shards.shape
        present_t = tuple(bool(b) for b in pres)
        words = codec_step.host_bytes_to_words(shards)
        mesh = self._mesh_for(B, data_shards)
        if mesh is not None:
            from ..parallel import mesh as pm

            if codec_step.codec_overlap_mode() != "off":
                pm.warn_overlap_fallback()
            dw, ok = pm.mesh_verify_reconstruct(
                mesh,
                words,
                np.asarray(digests),
                present_t,
                data_shards,
                parity_shards,
                L,
            )
            _record_pass("mesh_verify_reconstruct")
        else:
            overlap = codec_step.codec_overlap_mode()
            got = None
            if overlap == "async":
                got = self._drain_vr_subchunks(
                    words, digests, present_t, data_shards, parity_shards, L
                )
            if got is not None:
                dw, ok = got
            else:
                w = L // 4
                use_pallas, interpret = codec_step.pallas_dispatch(w)
                pipeline = overlap == "pipeline" and use_pallas
                words_d = jnp.asarray(words)
                _record_h2d("data", words_d.nbytes)
                dw_d, ok_d = codec_step.verify_and_reconstruct_words(
                    words_d,
                    jnp.asarray(digests),
                    present_t,
                    data_shards,
                    parity_shards,
                    L,
                    formulation=codec_step.codec_formulation(),
                    use_pallas=use_pallas,
                    interpret=interpret,
                    pipeline=pipeline,
                )
                _record_pass("verify_and_reconstruct_words")
                if pipeline:
                    from ..ops import rs_pallas

                    nt = w // rs_pallas._TW
                    if nt > 1:
                        _record_overlap("get", B * (nt - 1))
                dw = np.asarray(dw_d)
                ok = np.asarray(ok_d)
        data = codec_step.host_words_to_bytes(dw)
        surv = np.nonzero(pres)[0][:data_shards]
        bad = ~ok[:, surv].all(axis=1)
        if bad.any():
            idxs = np.nonzero(bad)[0]
            if not data.flags.writeable:  # zero-copy view of a jax buffer
                data = data.copy()
            data[idxs] = self._reconstruct_from_ok(
                shards[idxs], ok[idxs], data_shards, parity_shards
            )
        return data, ok

    def _drain_vr_subchunks(
        self, words_h, digests, present, data_shards, parity_shards, shard_len
    ):
        """MINIO_TPU_CODEC_OVERLAP=async GET: the sub-chunked
        verify+reconstruct chain, a registered drain seam — each
        reconstructed chunk drains D2H here WHILE the next chunk's pass
        runs (np.asarray of chunk s syncs only chunk s; chunks s+1.. are
        still in flight behind it), with the digest partials threading
        through the donated ping-pong accumulator and the LAST chunk's
        program producing the verify mask.

        Returns (data words (B, k, w), ok (B, n) bool), or None when
        the batch is too small to cut S >= 3 chunks.
        """
        import jax.numpy as jnp

        from ..ops import codec_step
        from .erasure import subchunk_words

        B, n, w = words_h.shape
        cw = subchunk_words(w, 8)
        if not cw:
            return None
        offs = list(range(0, w, cw))
        reserved = _stage_reserve(2 * B * n * cw * 4)
        try:
            digests_d = jnp.asarray(np.asarray(digests))
            acc = jnp.zeros((B, n, 8), jnp.uint32)
            parts: "list[np.ndarray]" = []
            prev = None
            ok_d = None
            for i, off in enumerate(offs):
                end = min(off + cw, w)
                chunk = jnp.asarray(
                    np.ascontiguousarray(words_h[:, :, off:end])
                )
                _record_h2d("data", (end - off) * B * n * 4)
                d_c, acc, ok_d = (
                    codec_step.verify_reconstruct_subchunk_words(
                        chunk,
                        acc,
                        digests_d,
                        np.uint32(off),
                        present,
                        data_shards,
                        parity_shards,
                        shard_len,
                        finalize=i == len(offs) - 1,
                    )
                )
                _record_pass("verify_reconstruct_subchunk_words")
                if prev is not None:
                    # drain chunk i-1 while chunk i computes: this is
                    # the D2H leg of the three-deep overlap
                    part = np.asarray(prev)
                    _record_d2h("data", part.nbytes)
                    parts.append(part)
                prev = d_c
            part = np.asarray(prev)
            _record_d2h("data", part.nbytes)
            parts.append(part)
            ok = np.asarray(ok_d)
            _record_overlap("get", len(offs) - 1)
        finally:
            _stage_release(reserved)
        return np.concatenate(parts, axis=-1), ok

    def digest(self, shards):
        import jax.numpy as jnp

        from ..ops import codec_step

        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        B, n, L = shards.shape
        mesh = self._mesh_for(B * n, 1)
        if mesh is not None:
            from ..parallel import mesh as pm

            words = codec_step.host_bytes_to_words(shards)
            flat = words.reshape(B * n, -1)
            _record_pass("mesh_digest")
            return pm.mesh_digest(mesh, flat, L).reshape(B, n, 8)
        words = jnp.asarray(codec_step.host_bytes_to_words(shards))
        got = phash.phash256_words_batched(words, L)
        _record_pass("phash256_words_batched")
        return np.asarray(got)


class CpuBackend(CodecBackend):
    """Host backend: the whole batch goes through ONE native call per
    op (fused single-pass encode+hash, batched tiled reconstruct,
    fused reconstruct+verify), stripe-parallel inside the C layer.
    Every native entry point has a bit-identical numpy twin used when
    the toolchain/library is unavailable (warn-once, cached)."""

    name = "cpu"

    # None = untried, False = unavailable (decision cached: the
    # fallback must not re-attempt a failing g++ build per block)
    _native_ok: "bool | None" = None  # fused batch entry points
    _native_hash_ok: "bool | None" = None

    _NATIVE_ERRS = (
        OSError,
        AttributeError,  # stale .so without the symbol
        subprocess.CalledProcessError,
    )

    @property
    def fused_encode(self):  # type: ignore[override]
        return CpuBackend._native_ok is not False

    @classmethod
    def _native_fused(cls):
        """The native module, or None after a failed build (warn-once)."""
        if cls._native_ok is False:
            return None
        from ..utils import native

        if cls._native_ok is None:
            try:
                native.lib()
                cls._native_ok = True
            except cls._NATIVE_ERRS as exc:
                cls._native_ok = False
                _log.warning(
                    "native codec unavailable; numpy twin engaged"
                    " (bit-identical, slower)",
                    extra=kv(err=str(exc)),
                )
                return None
        return native

    def encode(self, data, parity_shards):
        """Fused single-pass batch encode: ONE native call, no Python
        per-stripe loop, no full-batch concatenate copy."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        native = self._native_fused()
        if native is not None:
            try:
                return native.encode_and_hash_cpu(data, parity_shards)
            except self._NATIVE_ERRS as exc:
                CpuBackend._native_ok = False
                _log.warning(
                    "native fused encode failed; numpy twin engaged",
                    extra=kv(err=str(exc)),
                )
        parity = _numpy_encode(data, parity_shards)
        # digests of data and parity rows hashed separately and
        # stacked: digest arrays are (B, n, 8) - tiny - so no
        # full-batch byte concatenate on the fallback path either
        digests = np.concatenate(
            [self.digest(data), self.digest(parity)], axis=1
        )
        return parity, digests

    def encode_split(self, data, parity_shards):
        """Legacy split path: per-stripe native matmul round-trips plus
        a separate full-read digest pass over a concatenated copy.
        Kept callable as the identity/bench baseline the fused kernel
        is asserted bit-identical against (tests, bench --codec-micro);
        not used by the erasure layer."""
        from ..utils import native

        data = np.ascontiguousarray(data, dtype=np.uint8)
        B, k, L = data.shape
        m = parity_shards
        parity = np.empty((B, m, L), dtype=np.uint8)
        matrix = gf.parity_matrix(k, m)
        for b in range(B):
            parity[b] = native.gf_matmul_cpu(matrix, data[b])
        digests = self.digest(np.concatenate([data, parity], axis=1))
        return parity, digests

    def reconstruct(self, shards, present, data_shards, parity_shards):
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        pres = np.asarray(present, dtype=bool)
        native = self._native_fused()
        if native is not None:
            try:
                return native.reconstruct_batch_cpu(
                    shards, pres, data_shards, parity_shards
                )
            except self._NATIVE_ERRS as exc:
                CpuBackend._native_ok = False
                _log.warning(
                    "native batch reconstruct failed; numpy twin engaged",
                    extra=kv(err=str(exc)),
                )
        return _numpy_reconstruct(shards, pres, data_shards, parity_shards)

    def reconstruct_and_verify(
        self, shards, digests, present, data_shards, parity_shards
    ):
        """Fused GET-side pass: digest checks + survivor decode in one
        native memory pass.  Optimistic: decodes from the first k
        present shards while hashing all of them; on the rare digest
        mismatch among the chosen survivors, re-picks survivors from
        the verified mask and reconstructs just the hit stripes."""
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        pres = np.asarray(present, dtype=bool)
        native = self._native_fused()
        if native is None:
            return super().reconstruct_and_verify(
                shards, digests, pres, data_shards, parity_shards
            )
        try:
            data, ok = native.reconstruct_and_verify_cpu(
                shards, digests, pres, data_shards, parity_shards
            )
        except self._NATIVE_ERRS as exc:
            CpuBackend._native_ok = False
            _log.warning(
                "native fused reconstruct_and_verify failed;"
                " numpy twin engaged",
                extra=kv(err=str(exc)),
            )
            return super().reconstruct_and_verify(
                shards, digests, pres, data_shards, parity_shards
            )
        surv = np.nonzero(pres)[0][:data_shards]
        bad = ~ok[:, surv].all(axis=1)
        if bad.any():
            idxs = np.nonzero(bad)[0]
            if not data.flags.writeable:  # zero-copy view of a jax buffer
                data = data.copy()
            data[idxs] = self._reconstruct_from_ok(
                shards[idxs], ok[idxs], data_shards, parity_shards
            )
        return data, ok

    def digest(self, shards):
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        L = shards.shape[-1]
        words = shards.view(np.uint32)
        if CpuBackend._native_hash_ok is not False:
            from ..utils import native

            try:
                out = native.phash256_rows(words, L)
                CpuBackend._native_hash_ok = True
                return out
            except self._NATIVE_ERRS:
                CpuBackend._native_hash_ok = False
        # no toolchain / stale lib: numpy twin (bit-identical, slower)
        return phash.phash256_host_batched(words, L)


def _numpy_encode(data: np.ndarray, parity_shards: int) -> np.ndarray:
    """Vectorized numpy parity twin: loops only over the (m, k) matrix
    cells, each multiply a batched table gather + XOR over (B, L)."""
    B, k, L = data.shape
    m = parity_shards
    matrix = gf.parity_matrix(k, m)
    table = gf.mul_table()
    parity = np.zeros((B, m, L), dtype=np.uint8)
    for r in range(m):
        for c in range(k):
            parity[:, r, :] ^= table[matrix[r, c]][data[:, c, :]]
    return parity


def _numpy_reconstruct(
    shards: np.ndarray,
    present: np.ndarray,
    data_shards: int,
    parity_shards: int,
) -> np.ndarray:
    """Vectorized numpy decode twin of reconstruct_batch_cpu."""
    B, n, L = shards.shape
    k = data_shards
    idx = tuple(int(i) for i in np.nonzero(present)[0])
    if len(idx) < k:
        raise ValueError(f"need {k} shards to reconstruct, have {len(idx)}")
    rm = gf.reconstruction_matrix(k, parity_shards, idx)
    table = gf.mul_table()
    surv = shards[:, list(idx[:k]), :]
    out = np.zeros((B, k, L), dtype=np.uint8)
    for r in range(k):
        for c in range(k):
            if rm[r, c]:
                out[:, r, :] ^= table[rm[r, c]][surv[:, c, :]]
    return out


_lock = threading.Lock()
_backend: "CodecBackend | None" = None


def get_backend(name: "str | None" = None) -> CodecBackend:
    """Resolve the codec backend (MINIO_ERASURE_BACKEND=tpu|cpu|auto)."""
    global _backend
    if name is None:
        with _lock:
            if _backend is not None:
                return _backend
            name = os.environ.get("MINIO_ERASURE_BACKEND", "auto")
            _backend = _make(name)
            return _backend
    return _make(name)


def _make(name: str) -> CodecBackend:
    # kernel telemetry wraps the CONCRETE backend, under the batcher:
    # a coalesced flush is one recorded call with real device seconds,
    # while queue wait is the batcher's own series (codec/telemetry.py)
    from .batcher import maybe_wrap
    from .telemetry import instrument

    if name == "cpu":
        return maybe_wrap(instrument(CpuBackend()))
    if name == "tpu":
        return maybe_wrap(instrument(TpuBackend()))
    if name == "auto":
        try:
            import jax

            # any jax backend (tpu or the CPU test platform) works; the
            # device path dispatches pallas-vs-portable internally
            jax.devices()
            return maybe_wrap(instrument(TpuBackend()))
        except Exception:
            return maybe_wrap(instrument(CpuBackend()))
    raise ValueError(f"unknown erasure backend {name!r}")


def reset_backend() -> None:
    """Testing aid: drop the cached backend (and the parity cache) so
    env changes take effect."""
    global _backend, _PARITY_CACHE, _staging_bytes
    with _lock:
        _backend = None
        _PARITY_CACHE = None
        _staging_bytes = 0
    try:
        from ..cache.allocator import device_budget

        device_budget().set_usage("parity_plane", 0)
        device_budget().set_usage("codec_staging", 0)
    except Exception as exc:  # noqa: BLE001
        _log.debug("parity budget reset failed: %s", exc)
