"""Server-side encryption core (cmd/encryption-v1.go, pkg/crypto,
and the DARE stream format of minio/sio).

Stored representation: the plaintext (possibly already deflated by the
compression seam) is split into fixed 64 KiB chunks; each chunk is
sealed independently with AES-256-GCM as ``[nonce(12)][ct][tag(16)]``.
The 12-byte nonce is an 8-byte random prefix (per object/part) plus a
4-byte big-endian chunk counter, so chunks cannot be reordered or
replayed across positions - the sio DARE package construction.

Key hierarchy (pkg/crypto):
- a random 32-byte **object encryption key** (OEK) encrypts the data;
- the OEK is sealed with AES-256-GCM under a **key encryption key**:
  the client's key for SSE-C, the KMS master key for SSE-S3, with the
  bucket/object path as AAD so a sealed key cannot be replayed onto
  another object (crypto.SealObjectKey);
- only the sealed OEK is stored; for SSE-C the server keeps nothing
  but the client key's MD5 (to reject wrong keys with a clear error).

Metadata contract (rides FileInfo.metadata like the compression seam):
  x-internal-sse            = "C" | "S3"
  x-internal-sse-sealed-key = base64 sealed OEK
  x-internal-sse-nonce      = base64 8-byte base nonce prefix
  x-internal-sse-key-md5    = base64 MD5 of the SSE-C client key
  x-internal-sse-kms-id     = master key id (SSE-S3)
  x-internal-actual-size    = plaintext byte count (shared with
                              compression; encryption adds ~28B/64KiB)
"""

from __future__ import annotations

import base64
import hashlib
import os
import secrets
import struct

# gate the hard dependency: environments without `cryptography` can
# still import the object layer (SSE requests fail with a clear
# SSEError at use time instead of the whole package failing to import)
try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    _CRYPTO_IMPORT_ERROR: "Exception | None" = None
except ImportError as _e:  # pragma: no cover - depends on environment
    _CRYPTO_IMPORT_ERROR = _e

    class InvalidTag(Exception):  # type: ignore[no-redef]
        pass

    class AESGCM:  # type: ignore[no-redef]
        def __init__(self, key):
            raise SSEError(
                "server-side encryption requires the 'cryptography' "
                f"package: {_CRYPTO_IMPORT_ERROR}"
            )

from .compress import RangeSatisfied

CHUNK = 64 << 10  # plaintext bytes per sealed package (DARE payload)
NONCE_LEN = 12
TAG_LEN = 16
OVERHEAD = NONCE_LEN + TAG_LEN  # per chunk

META_SSE = "x-internal-sse"
META_SSE_SEALED_KEY = "x-internal-sse-sealed-key"
META_SSE_NONCE = "x-internal-sse-nonce"
META_SSE_KEY_MD5 = "x-internal-sse-key-md5"
META_SSE_KMS_ID = "x-internal-sse-kms-id"
# the per-object data key sealed by the KMS (crypto.S3KMSSealedKey);
# the OEK is sealed under this data key, not the master key directly
META_SSE_KMS_SEALED_DK = "x-internal-sse-kms-sealed-dk"
# original (client) part numbers, comma-separated: chunk nonces derive
# from the number the part was UPLOADED under, which complete's
# renumbering would otherwise lose
META_SSE_PARTS = "x-internal-sse-parts"


class SSEError(Exception):
    """Key/ciphertext problems (wrong key, tampered data, no KMS)."""


import dataclasses


@dataclasses.dataclass
class SSESpec:
    """Parsed per-request encryption intent (the ObjectOptions
    ServerSideEncryption field)."""

    mode: str  # "C" (client key) | "S3" (KMS master key)
    key: "bytes | None" = None  # raw 32B client key for SSE-C


def master_key() -> "tuple[str, bytes]":
    """(key_id, 32B key) from MINIO_TPU_KMS_MASTER_KEY='id:hex64'
    (the MINIO_SSE_MASTER_KEY bootstrap KMS, cmd/crypto/sse.go)."""
    raw = os.environ.get("MINIO_TPU_KMS_MASTER_KEY", "")
    if not raw or ":" not in raw:
        raise SSEError(
            "SSE-S3 requires MINIO_TPU_KMS_MASTER_KEY=<id>:<hex 32B key>"
        )
    key_id, _, hexkey = raw.partition(":")
    try:
        key = bytes.fromhex(hexkey)
    except ValueError:
        raise SSEError("master key must be hex") from None
    if len(key) != 32:
        raise SSEError("master key must be 32 bytes")
    return key_id, key


def sse_s3_available() -> bool:
    from . import kms as kmsmod

    try:
        return kmsmod.get_kms() is not None
    except kmsmod.KMSError:
        return False


def new_object_key() -> bytes:
    return secrets.token_bytes(32)


def new_nonce_base() -> bytes:
    return secrets.token_bytes(NONCE_LEN - 4)


def seal_key(kek: bytes, oek: bytes, aad: str) -> bytes:
    """Seal the object key under the KEK (crypto.SealObjectKey):
    [nonce(12)][ct||tag]."""
    nonce = secrets.token_bytes(NONCE_LEN)
    return nonce + AESGCM(kek).encrypt(nonce, oek, aad.encode())


def unseal_key(kek: bytes, sealed: bytes, aad: str) -> bytes:
    try:
        return AESGCM(kek).decrypt(
            sealed[:NONCE_LEN], sealed[NONCE_LEN:], aad.encode()
        )
    except (InvalidTag, ValueError):
        raise SSEError(
            "decryption key does not match the object key"
        ) from None


def part_nonce_base(base: bytes, part_number: int) -> bytes:
    """Per-part nonce prefix: parts of one upload share the OEK, so
    their chunk nonces must not collide."""
    if part_number <= 1:
        return base
    return hashlib.sha256(
        base + struct.pack(">I", part_number)
    ).digest()[: NONCE_LEN - 4]


def stored_size(plain: int) -> int:
    """Ciphertext size for `plain` plaintext bytes."""
    if plain <= 0:
        return 0
    chunks = (plain + CHUNK - 1) // CHUNK
    return plain + chunks * OVERHEAD


def key_md5_b64(key: bytes) -> str:
    return base64.b64encode(hashlib.md5(key).digest()).decode()


class EncryptReader:
    """Pull-style encryptor: read(n) returns sealed DARE packages while
    draining the plaintext stream underneath (the inner HashReader
    keeps hashing plaintext, so ETags stay client MD5s)."""

    def __init__(self, inner, oek: bytes, nonce_base: bytes):
        self._inner = inner
        self._aead = AESGCM(oek)
        self._nbase = nonce_base
        self._seq = 0
        self._buf = bytearray()
        self._eof = False

    def _seal_next(self) -> None:
        plain = b""
        while len(plain) < CHUNK:
            got = self._inner.read(CHUNK - len(plain))
            if not got:
                self._eof = True
                break
            plain += got
        if not plain:
            return
        nonce = self._nbase + struct.pack(">I", self._seq)
        self._seq += 1
        self._buf += nonce + self._aead.encrypt(nonce, plain, None)

    def read(self, n: int = -1) -> bytes:
        while not self._eof and (n < 0 or len(self._buf) < n):
            self._seal_next()
        if n < 0 or n >= len(self._buf):
            out = bytes(self._buf)
            self._buf.clear()
            return out
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


class DecryptWriter:
    """Push-style decryptor with range skip: sealed packages go in,
    plaintext [offset, offset+length) comes out to ``writer`` (which
    may itself be a skipping DecompressWriter when the object is both
    compressed and encrypted).

    Raises RangeSatisfied once the requested range is fully written, so
    the erasure decode stops paying I/O; SSEError on a wrong key or a
    tampered/reordered chunk (the GCM tag or nonce sequence fails).
    """

    def __init__(
        self,
        writer,
        oek: bytes,
        nonce_base: bytes,
        offset: int = 0,
        length: int = -1,
        first_chunk: int = 0,
    ):
        self._w = writer
        self._aead = AESGCM(oek)
        self._nbase = nonce_base
        self._seq = first_chunk
        self._skip = offset
        self._remaining = length
        self._buf = bytearray()
        self._downstream_done = False

    @property
    def done(self) -> bool:
        return self._remaining == 0 or self._downstream_done

    def _emit(self, data: bytes) -> None:
        if self._skip:
            drop = min(self._skip, len(data))
            self._skip -= drop
            data = data[drop:]
        if self._remaining >= 0:
            data = data[: self._remaining]
            self._remaining -= len(data)
        if data:
            try:
                self._w.write(data)
            except RangeSatisfied:
                # a chained skipping decompressor has its full range:
                # remember so finish() does not try to open a partial
                # trailing package from the cut-short stream
                self._downstream_done = True
                raise

    def _open_package(self, pkg: bytes) -> None:
        nonce, ct = pkg[:NONCE_LEN], pkg[NONCE_LEN:]
        expect = self._nbase + struct.pack(">I", self._seq)
        if nonce != expect:
            raise SSEError("ciphertext chunk out of sequence")
        self._seq += 1
        try:
            plain = self._aead.decrypt(nonce, ct, None)
        except (InvalidTag, ValueError):
            raise SSEError("ciphertext verification failed") from None
        self._emit(plain)

    def write(self, stored: bytes) -> int:
        if self._remaining == 0:
            raise RangeSatisfied()
        self._buf += stored
        full = CHUNK + OVERHEAD
        while len(self._buf) >= full:
            self._open_package(bytes(self._buf[:full]))
            del self._buf[:full]
            if self._remaining == 0:
                raise RangeSatisfied()
        return len(stored)

    def finish(self) -> None:
        """Open the trailing short package (the stream's last chunk)."""
        if self._remaining == 0 or self._downstream_done:
            return
        if len(self._buf) > OVERHEAD:
            try:
                self._open_package(bytes(self._buf))
            except RangeSatisfied:
                # the chained decompressor completed its range on the
                # final chunk - that IS a clean finish
                return
            self._buf.clear()
        elif self._buf:
            raise SSEError("truncated ciphertext")
        # forward the finish to a chained decompressor
        fin = getattr(self._w, "finish", None)
        if fin is not None:
            fin()
