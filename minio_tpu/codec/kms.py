"""KMS abstraction for SSE-S3 key management (cmd/crypto/kms.go).

Mirrors the reference's KMS interface: ``generate_key`` mints a fresh
per-object data key and returns (plaintext, sealed) so only the sealed
form is ever persisted; ``unseal_key`` reverses it.  The *context* (a
string->string map, canonically serialized) is cryptographically bound
to the sealed key - a sealed key lifted onto another object fails to
unseal (crypto.Context, cmd/crypto/kms.go:44-71).

Two implementations:

- :class:`MasterKeyKMS` - a single local 32-byte master key
  (``MINIO_TPU_KMS_MASTER_KEY=<id>:<hex>``), the masterKeyKMS
  bootstrap path (cmd/crypto/kms.go:104).
- :class:`KESClientKMS` - an HTTP client speaking the KES key-service
  API (``/v1/key/generate/<id>``, ``/v1/key/decrypt/<id>``,
  cmd/crypto/kes.go).  Auth is a bearer token
  (``MINIO_TPU_KMS_KES_TOKEN``) instead of the reference's mTLS
  client certificates - the wire shapes match, the transport
  credential is simpler.
"""

from __future__ import annotations

import base64
import http.client
import json
import os
import secrets
import threading
import urllib.parse

class KMSError(Exception):
    pass


# gate the hard dependency the same way codec/sse.py does: the module
# stays importable without `cryptography`, KMS operations fail with a
# clear KMSError at use time
try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    _CRYPTO_IMPORT_ERROR: "Exception | None" = None
except ImportError as _e:  # pragma: no cover - depends on environment
    _CRYPTO_IMPORT_ERROR = _e

    class InvalidTag(Exception):  # type: ignore[no-redef]
        pass

    class AESGCM:  # type: ignore[no-redef]
        def __init__(self, key):
            raise KMSError(
                "KMS sealing requires the 'cryptography' package: "
                f"{_CRYPTO_IMPORT_ERROR}"
            )


def context_aad(context: "dict[str, str]") -> bytes:
    """Canonical serialization of the KMS context, used as AEAD AAD
    (crypto.Context.MarshalText: sorted keys)."""
    return json.dumps(
        context or {}, sort_keys=True, separators=(",", ":")
    ).encode()


class KMS:
    """cmd/crypto/kms.go:74 interface."""

    def default_key_id(self) -> str:
        raise NotImplementedError

    def create_key(self, key_id: str) -> None:
        raise NotImplementedError

    def generate_key(
        self, key_id: str, context: "dict[str, str]"
    ) -> "tuple[bytes, bytes]":
        """(plaintext 32B data key, sealed data key)."""
        raise NotImplementedError

    def unseal_key(
        self, key_id: str, sealed: bytes, context: "dict[str, str]"
    ) -> bytes:
        raise NotImplementedError

    def info(self) -> dict:
        raise NotImplementedError


class MasterKeyKMS(KMS):
    def __init__(self, key_id: str, master_key: bytes):
        if len(master_key) != 32:
            raise KMSError("master key must be 32 bytes")
        self._id = key_id
        self._mk = master_key

    def default_key_id(self) -> str:
        return self._id

    def create_key(self, key_id: str) -> None:
        raise KMSError(
            "the local master-key KMS cannot create new keys"
        )

    def generate_key(self, key_id, context):
        if key_id != self._id:
            raise KMSError(f"unknown master key {key_id!r}")
        dk = secrets.token_bytes(32)
        nonce = secrets.token_bytes(12)
        sealed = nonce + AESGCM(self._mk).encrypt(
            nonce, dk, context_aad(context)
        )
        return dk, sealed

    def unseal_key(self, key_id, sealed, context):
        if key_id != self._id:
            raise KMSError(f"unknown master key {key_id!r}")
        try:
            return AESGCM(self._mk).decrypt(
                sealed[:12], sealed[12:], context_aad(context)
            )
        except (InvalidTag, ValueError):
            raise KMSError(
                "sealed key does not unseal under this master key / "
                "context"
            ) from None

    def info(self) -> dict:
        return {"endpoint": "local", "name": self._id, "auth": "master-key"}


class KESClientKMS(KMS):
    """KES-shaped HTTP key service client (cmd/crypto/kes.go:149)."""

    def __init__(self, endpoint: str, key_id: str, token: str = "",
                 timeout_s: float = 10.0):
        u = urllib.parse.urlsplit(endpoint)
        if u.scheme not in ("http", "https") or not u.hostname:
            raise KMSError(f"bad KES endpoint {endpoint!r}")
        self._tls = u.scheme == "https"
        self._host = u.hostname
        self._port = u.port or (443 if self._tls else 80)
        self._token = token
        self._timeout = timeout_s
        self._id = key_id
        self._local = threading.local()

    def _conn(self):
        c = getattr(self._local, "conn", None)
        if c is None:
            if self._tls:
                import ssl

                ctx = ssl.create_default_context()
                if os.environ.get("MINIO_TPU_KMS_KES_INSECURE") == "1":
                    ctx.check_hostname = False
                    ctx.verify_mode = ssl.CERT_NONE
                c = http.client.HTTPSConnection(
                    self._host, self._port, timeout=self._timeout,
                    context=ctx,
                )
            else:
                c = http.client.HTTPConnection(
                    self._host, self._port, timeout=self._timeout
                )
            self._local.conn = c
        return c

    def _call(self, path: str, doc: dict) -> dict:
        body = json.dumps(doc).encode()
        headers = {"Content-Type": "application/json"}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        for attempt in (0, 1):  # one retry on a dropped keep-alive
            conn = self._conn()
            try:
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
                break
            except (OSError, http.client.HTTPException):
                self._local.conn = None
                if attempt:
                    raise KMSError(
                        f"KES {self._host}:{self._port} unreachable"
                    ) from None
        if resp.status != 200:
            raise KMSError(
                f"KES {path}: HTTP {resp.status} "
                f"{payload[:200].decode(errors='replace')}"
            )
        try:
            return json.loads(payload)
        except ValueError:
            raise KMSError("KES returned malformed JSON") from None

    def default_key_id(self) -> str:
        return self._id

    def create_key(self, key_id: str) -> None:
        self._call(f"/v1/key/create/{urllib.parse.quote(key_id)}", {})

    def generate_key(self, key_id, context):
        doc = self._call(
            f"/v1/key/generate/{urllib.parse.quote(key_id)}",
            {
                "context": base64.b64encode(
                    context_aad(context)
                ).decode()
            },
        )
        try:
            return (
                base64.b64decode(doc["plaintext"]),
                base64.b64decode(doc["ciphertext"]),
            )
        except (KeyError, ValueError):
            raise KMSError("KES generate: bad response body") from None

    def unseal_key(self, key_id, sealed, context):
        doc = self._call(
            f"/v1/key/decrypt/{urllib.parse.quote(key_id)}",
            {
                "ciphertext": base64.b64encode(sealed).decode(),
                "context": base64.b64encode(
                    context_aad(context)
                ).decode(),
            },
        )
        try:
            return base64.b64decode(doc["plaintext"])
        except (KeyError, ValueError):
            raise KMSError("KES decrypt: bad response body") from None

    def info(self) -> dict:
        return {
            "endpoint": f"{'https' if self._tls else 'http'}://"
            f"{self._host}:{self._port}",
            "name": self._id,
            "auth": "token",
        }


# -- global KMS (GlobalKMS, cmd/globals.go) --------------------------------

_kms: "KMS | None" = None
_kms_loaded = False
_kms_lock = threading.Lock()


def set_kms(kms: "KMS | None") -> None:
    """Install explicitly (tests, embedders); None re-enables the
    env-driven lookup."""
    global _kms, _kms_loaded
    with _kms_lock:
        _kms = kms
        _kms_loaded = kms is not None


def get_kms() -> "KMS | None":
    """The process KMS: KES when configured, else the local master
    key, else None (SSE-S3 unavailable)."""
    global _kms, _kms_loaded
    with _kms_lock:
        if _kms_loaded:
            return _kms
        kes = os.environ.get("MINIO_TPU_KMS_KES_ENDPOINT", "")
        if kes:
            _kms = KESClientKMS(
                kes,
                os.environ.get("MINIO_TPU_KMS_KES_KEY_ID", "minio-tpu"),
                os.environ.get("MINIO_TPU_KMS_KES_TOKEN", ""),
            )
        else:
            raw = os.environ.get("MINIO_TPU_KMS_MASTER_KEY", "")
            if raw:
                key_id, sep, hexkey = raw.partition(":")
                if not sep or not key_id:
                    # a SET but malformed key is a config error, not
                    # "no KMS" - silence here would fail every SSE-S3
                    # write with a misleading 'not configured'
                    raise KMSError(
                        "MINIO_TPU_KMS_MASTER_KEY must be <id>:<hex>"
                    )
                try:
                    mk = bytes.fromhex(hexkey)
                except ValueError:
                    raise KMSError(
                        "MINIO_TPU_KMS_MASTER_KEY must be <id>:<hex>"
                    ) from None
                _kms = MasterKeyKMS(key_id, mk)
            else:
                _kms = None
        _kms_loaded = True
        return _kms


def reset_kms_cache() -> None:
    """Forget the cached env-derived KMS (tests changing env vars)."""
    global _kms, _kms_loaded
    with _kms_lock:
        _kms = None
        _kms_loaded = False
