"""Cross-request codec batching (SURVEY.md section 7 stage 8).

Concurrent PutObject/GetObject requests each produce small codec calls
(a few blocks per pass).  Launched independently they serialize on the
device and pay per-launch overhead; the reference's analogue is the
per-disk goroutine fan-out feeding one disk queue
(cmd/erasure-encode.go:39-70).  Here ALL requests feed one device queue:

* client threads submit jobs (encode / digest / reconstruct) and block;
* a single dispatcher thread coalesces jobs with identical geometry
  into one batched device call, then scatters results back;
* a batch is flushed as soon as every currently-active client has
  submitted (nobody left to wait for), or when ``deadline_s`` expires -
  so a lone stream pays ~zero extra latency while 8 concurrent streams
  coalesce into one launch (the "dynamic batch deadlines" risk note in
  SURVEY.md section 7).

Correctness is trivial: the grouped call is the same math on a
concatenated batch axis, and results are split back by row counts.
"""

from __future__ import annotations

import os
import queue
import threading
import time

import numpy as np

from .backend import CodecBackend
from .telemetry import KERNEL_STATS


class _Job:
    __slots__ = (
        "op", "key", "arrays", "result", "error", "done", "created",
        "client", "ended",
    )

    def __init__(self, op: str, key: tuple, arrays: tuple):
        self.op = op
        self.key = key
        self.arrays = arrays
        self.result = None
        self.error: "BaseException | None" = None
        self.done = threading.Event()
        self.created = time.monotonic()
        self.client = threading.get_ident()
        # set by the first encode_end: a second end of the same handle
        # (error-path cleanup racing the normal consume) must not
        # decrement _active again — that corrupts the distinct-client
        # flush signal for every later batch
        self.ended = False


class _SlicedParityRef:
    """View of a coalesced batch's parity ref: drain pulls the PARENT
    (one shared D2H for the whole merged flush) and hands back this
    job's rows.  release is a no-op — sibling jobs may still need the
    parent, which stays governed by the write-back cache either way."""

    __slots__ = ("_parent", "_lo", "_hi")

    def __init__(self, parent, lo: int, hi: int):
        self._parent = parent
        self._lo = lo
        self._hi = hi

    @property
    def nbytes(self) -> int:
        return 0  # the parent ref carries the cache accounting

    def drain(self):
        return self._parent.drain()[self._lo : self._hi]

    def release(self) -> None:
        return None


class _SubmeshWorker(threading.Thread):
    """One daemon worker per routed submesh: runs merged groups with the
    mesh scoped to that submesh's devices (parallel.rules.placed), so
    two independent batches on disjoint submeshes overlap instead of
    serializing on the dispatcher thread."""

    def __init__(self, backend: "BatchingBackend", router, sub):
        super().__init__(name=f"codec-batcher-{sub.name}", daemon=True)
        self.backend = backend
        self.router = router
        self.sub = sub
        self.q: "queue.SimpleQueue" = queue.SimpleQueue()
        self.start()

    def submit(self, item) -> None:
        self.q.put(item)

    def stop(self) -> None:
        self.q.put(None)

    def run(self) -> None:
        from ..parallel import rules as prules

        while True:
            item = self.q.get()
            if item is None:
                return
            op, key, group = item
            try:
                with prules.placed(self.sub.devices):
                    self.backend._run_group_safe(op, key, group)
            finally:
                self.router.release(self.sub)
                KERNEL_STATS.record_submesh_depths(self.router.depths())


class BatchingBackend(CodecBackend):
    """Wrap any CodecBackend with cross-request batch coalescing."""

    name = "batched"

    # ops the "auto" placement policy may route to a submesh (the
    # PUT-side throughput plane; see _dispatch_group)
    _ROUTED_AUTO_OPS = frozenset({"encode", "encode_digest"})

    def __init__(
        self,
        inner: CodecBackend,
        deadline_s: float = 0.004,
        max_batch_blocks: int = 256,
    ):
        self.inner = inner
        self.deadline_s = deadline_s
        self.max_batch_blocks = max_batch_blocks
        self._cv = threading.Condition()
        self._jobs: list[_Job] = []
        # client threads currently inside a codec call (submitted or
        # about to): thread ident -> outstanding call/handle count.
        # Distinct CLIENTS is the flush signal — a pipelined stream
        # holding an un-ended handle while submitting its next batch is
        # still one client, not two (counting raw handles makes the
        # "everyone submitted" fast path unreachable and every flush
        # waits out the full deadline)
        self._active: "dict[int, int]" = {}
        # submesh placement: feature-detected once from the inner
        # backend (host backends return None -> pure inline dispatch)
        self._router_known = False
        self._router_obj = None
        self._workers: "dict[str, _SubmeshWorker]" = {}
        self._workers_mu = threading.Lock()
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="codec-batcher", daemon=True
        )
        self._thread.start()

    # -- client side ------------------------------------------------------

    def _enter(self, client: int) -> None:
        """cv held: one more outstanding call/handle for ``client``."""
        self._active[client] = self._active.get(client, 0) + 1

    def _exit(self, client: int) -> None:
        """cv held: drop one outstanding call/handle for ``client``."""
        left = self._active.get(client, 0) - 1
        if left <= 0:
            self._active.pop(client, None)
        else:
            self._active[client] = left

    def _submit(self, op: str, key: tuple, arrays: tuple):
        job = _Job(op, key, arrays)
        with self._cv:
            self._jobs.append(job)
            self._cv.notify_all()
        job.done.wait()
        if job.error is not None:
            raise job.error
        return job.result

    def encode(self, data, parity_shards):
        return self.encode_end(self.encode_begin(data, parity_shards))

    def encode_begin(self, data, parity_shards):
        """Non-blocking submit: the job coalesces and runs on the
        dispatcher while the caller flushes its PREVIOUS batch; the
        handle resolves in encode_end (double-buffered PUT pipeline).

        The handle counts toward _active until encode_end so that
        concurrent pipelined streams still coalesce; encode_end's
        decrement NOTIFIES the dispatcher, which then flushes as soon
        as every remaining active client has submitted instead of
        sleeping out the coalesce deadline."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        B, k, L = data.shape
        job = _Job("encode", (k, L, parity_shards), (data,))
        with self._cv:
            self._enter(job.client)
            self._jobs.append(job)
            self._cv.notify_all()
        return job

    def encode_end(self, handle):
        job = handle
        job.done.wait()
        with self._cv:
            # pair with the SUBMITTING thread's entry exactly once: a
            # pipelined caller may end a handle from a different
            # thread, and error-path cleanup may end it a second time
            if not job.ended:
                job.ended = True
                self._exit(job.client)
                self._cv.notify_all()
        if job.error is not None:
            raise job.error
        return job.result

    def encode_digest_begin(self, data, parity_shards):
        """Digest-only twin of encode_begin: coalesces across requests
        like encode, and admission BACKS OFF while the inner backend's
        parity cache is over budget — the flush policy's cache-pressure
        term, bounding device-resident parity under concurrency."""
        self._cache_backoff()
        data = np.ascontiguousarray(data, dtype=np.uint8)
        B, k, L = data.shape
        job = _Job("encode_digest", (k, L, parity_shards), (data,))
        with self._cv:
            self._enter(job.client)
            self._jobs.append(job)
            self._cv.notify_all()
        return job

    def encode_digest_end(self, handle):
        # same handle protocol as encode_end (idempotent, _exit once);
        # the result is (digests, parity_ref) instead of (parity, digests)
        return self.encode_end(handle)

    def parity_cache_pressure(self) -> float:
        return self.inner.parity_cache_pressure()

    def _cache_backoff(self, bound_s: float = 0.25) -> None:
        """Stall new digest-encode admission briefly while the parity
        cache is at/over budget, so lazy drains catch up instead of
        every insert forcing a synchronous write-back eviction.  Time-
        bounded: a wedged drain band degrades to eviction, not a hang."""
        if self.inner.parity_cache_pressure() < 1.0:
            return
        deadline = time.monotonic() + bound_s
        while (
            self.inner.parity_cache_pressure() >= 1.0
            and time.monotonic() < deadline
        ):
            time.sleep(0.002)

    def digest(self, shards):
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        B, n, L = shards.shape
        client = threading.get_ident()
        with self._cv:
            self._enter(client)
        try:
            return self._submit("digest", (n, L), (shards,))
        finally:
            with self._cv:
                self._exit(client)
                self._cv.notify_all()

    def reconstruct(self, shards, present, data_shards, parity_shards):
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        B, n, L = shards.shape
        key = (n, L, tuple(bool(b) for b in present), data_shards,
               parity_shards)
        client = threading.get_ident()
        with self._cv:
            self._enter(client)
        try:
            return self._submit("reconstruct", key, (shards,))
        finally:
            with self._cv:
                self._exit(client)
                self._cv.notify_all()

    @property
    def fused_encode(self):  # type: ignore[override]
        return getattr(self.inner, "fused_encode", False)

    def reconstruct_and_verify(
        self, shards, digests, present, data_shards, parity_shards
    ):
        # straight delegation, no coalescing: this op serves heal and
        # degraded reads - rare, latency-insensitive, and keyed by a
        # per-call digest array that would defeat batch merging anyway.
        # The default composition would route through self.verify/
        # self.reconstruct and lose the inner fused pass.
        return self.inner.reconstruct_and_verify(
            shards, digests, present, data_shards, parity_shards
        )

    def placement_router(self):
        return getattr(self.inner, "placement_router", lambda: None)()

    def shutdown(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()
        self._thread.join(timeout=2)
        with self._workers_mu:
            workers, self._workers = dict(self._workers), {}
        for w in workers.values():
            w.stop()
        for w in workers.values():
            w.join(timeout=2)

    # -- dispatcher -------------------------------------------------------

    def _collect(self) -> "list[_Job]":
        """Take a coalescible batch off the queue (holds no deadline
        when every active client has already submitted)."""
        with self._cv:
            while self._running and not self._jobs:
                self._cv.wait(0.1)
            if not self._running and not self._jobs:
                return []
            deadline = time.monotonic() + self.deadline_s
            while True:
                # flush when nobody else could still contribute, when
                # the batch is big enough, or at the deadline.  The
                # contribution test compares DISTINCT clients: every
                # queued job's submitter is guaranteed active, so the
                # batch is complete exactly when each active client
                # has at least one job queued (a client pipelining two
                # begins is one contributor, not two)
                if (
                    len({j.client for j in self._jobs})
                    >= len(self._active)
                ):
                    break
                if (
                    sum(j.arrays[0].shape[0] for j in self._jobs)
                    >= self.max_batch_blocks
                ):
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            jobs, self._jobs = self._jobs, []
            return jobs

    def _loop(self) -> None:
        while True:
            jobs = self._collect()
            if not jobs:
                if not self._running:
                    return
                continue
            now = time.monotonic()
            KERNEL_STATS.record_batch_flush(
                len(jobs),
                sum(j.arrays[0].shape[0] for j in jobs),
                sum(now - j.created for j in jobs),
            )
            groups: dict[tuple, list[_Job]] = {}
            for j in jobs:
                groups.setdefault((j.op, j.key), []).append(j)
            for (op, key), group in groups.items():
                self._dispatch_group(op, key, group)

    def _router(self):
        """The inner backend's submesh router, feature-detected once."""
        if not self._router_known:
            fn = getattr(self.inner, "placement_router", None)
            self._router_obj = fn() if callable(fn) else None
            self._router_known = True
        return self._router_obj

    def _dispatch_group(
        self, op: str, key: tuple, group: "list[_Job]"
    ) -> None:
        """Place one merged group: on the least-loaded submesh (its
        worker thread, overlapping with other submeshes) or inline on
        the dispatcher spanning the full mesh."""
        router = self._router()
        sub = None
        if router is not None:
            # under "auto", only the PUT-side throughput ops are
            # routed: reconstruct/digest serve degraded reads and
            # verify, where a routed submesh's cold single-device
            # compile would be charged to a latency-sensitive GET (an
            # explicit "route" policy still routes everything)
            routable = (
                router.policy == "route" or op in self._ROUTED_AUTO_OPS
            )
            if routable:
                blocks = sum(j.arrays[0].shape[0] for j in group)
                sub = router.route(blocks)
        if sub is None:
            KERNEL_STATS.record_placement("span")
            self._run_group_safe(op, key, group)
            return
        KERNEL_STATS.record_placement("route")
        KERNEL_STATS.record_submesh_depths(router.depths())
        self._worker(router, sub).submit((op, key, group))

    def _worker(self, router, sub) -> _SubmeshWorker:
        with self._workers_mu:
            w = self._workers.get(sub.name)
            if w is None:
                w = _SubmeshWorker(self, router, sub)
                self._workers[sub.name] = w
            return w

    def _run_group_safe(
        self, op: str, key: tuple, group: "list[_Job]"
    ) -> None:
        try:
            self._run_group(op, key, group)
        except BaseException as e:  # noqa: BLE001
            for j in group:
                j.error = e
                j.done.set()

    def _run_group(self, op: str, key: tuple, group: "list[_Job]") -> None:
        if len(group) == 1:
            j = group[0]
            j.result = self._call(op, key, j.arrays[0])
            j.done.set()
            return
        rows = [j.arrays[0].shape[0] for j in group]
        merged = np.concatenate([j.arrays[0] for j in group], axis=0)
        total = merged.shape[0]
        # device backends jit-compile per batch shape: arbitrary merged
        # sizes would each pay a fresh XLA compile (seconds).  Pad the
        # merged batch up to a power of two so the compile cache stays
        # O(log max_batch) regardless of traffic mix.
        padded = total
        if getattr(self.inner, "name", "") == "tpu":
            padded = 1 << (total - 1).bit_length()
            if padded != total:
                pad = np.zeros(
                    (padded - total,) + merged.shape[1:], merged.dtype
                )
                merged = np.concatenate([merged, pad], axis=0)
        out = self._call(op, key, merged)
        # split along the batch axis and fulfill each job
        offsets = np.cumsum([0] + rows)
        for i, j in enumerate(group):
            lo, hi = offsets[i], offsets[i + 1]
            if op == "encode":
                parity, digests = out
                j.result = (parity[lo:hi], digests[lo:hi])
            elif op == "encode_digest":
                digests, pref = out
                j.result = (
                    digests[lo:hi], _SlicedParityRef(pref, lo, hi)
                )
            else:
                j.result = out[lo:hi]
            j.done.set()

    def _call(self, op: str, key: tuple, arr):
        if op == "encode":
            return self.inner.encode(arr, key[2])
        if op == "encode_digest":
            return self.inner.encode_digest_end(
                self.inner.encode_digest_begin(arr, key[2])
            )
        if op == "digest":
            return self.inner.digest(arr)
        if op == "reconstruct":
            n, L, present, k, m = key
            return self.inner.reconstruct(arr, present, k, m)
        raise ValueError(f"unknown op {op}")


def maybe_wrap(backend: CodecBackend) -> CodecBackend:
    """Apply batching per MINIO_CODEC_BATCH (default on; "0"/"off"
    disable - the admin config seam writes on/off)."""
    if os.environ.get("MINIO_CODEC_BATCH", "on").lower() in ("0", "off"):
        return backend
    deadline_ms = 4.0
    try:
        deadline_ms = float(
            os.environ.get("MINIO_CODEC_BATCH_DEADLINE_MS") or 4.0
        )
    except ValueError:
        pass
    return BatchingBackend(backend, deadline_s=deadline_ms / 1e3)
