"""Multi-process cluster harness: N real ``python -m minio_tpu.server``
nodes over loopback, one shared endpoint list (verify-healing.sh style).

Each node is a genuine OS process running the full stack - async
request plane, storage/lock REST planes, heal + crawler threads - so
scenarios exercise the same wire paths a production pool does.  The
harness owns:

- drive layout + port allocation + spawn env (CPU-pinned JAX, fast
  heal/lock cadences, fault injection armed),
- per-node log capture (``<base>/node<i>.log``, appended across
  restarts),
- readiness polling against /minio/health/ready (no sleeps),
- lifecycle: SIGTERM drain, SIGKILL, restart with the same identity,
- programmatic fault control: the admin ``fault/*`` endpoint schedules
  FaultDisk delay/error/corrupt/hang rules inside a REMOTE node,
- per-node Prometheus scrapes merged under a ``node`` label with
  zero-fill, so breaker/hedge/shed counters are node-attributable.

The chaos-scenario DSL that drives this lives in minio_tpu/testgrid/.
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

from ..utils.log import kv, logger

_log = logger("harness")

_REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[2])

# counter families every node must report even when idle: a merged
# scrape that silently omits a node reads as "nothing happened there"
# when the truth may be "the node never exported the family"
ZERO_FILL_FAMILIES = (
    "miniotpu_disk_state",
    "miniotpu_hedge_launched_total",
    "miniotpu_server_shed_total",
)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def parse_prometheus(text: str) -> "list[tuple[str, dict, float]]":
    """Minimal exposition-format parser: (family, labels, value) rows."""
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        labels: dict = {}
        name = name_part
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            body = rest.rsplit("}", 1)[0]
            for item in body.split('",'):
                if not item:
                    continue
                k, _, v = item.partition('="')
                labels[k.strip().strip(",")] = v.rstrip('"')
        try:
            rows.append((name, labels, float(value_part)))
        except ValueError:
            _log.debug(
                "unparseable metric line", extra=kv(line=line[:120])
            )
    return rows


class NodeHandle:
    """One cluster member: identity survives restarts, the process
    object is replaced."""

    def __init__(self, index: int, port: int, drive_dirs: list,
                 log_path: str):
        self.index = index
        self.port = port
        self.drive_dirs = list(drive_dirs)
        self.log_path = log_path
        self.proc: "subprocess.Popen | None" = None

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def log_tail(self, max_bytes: int = 8192) -> str:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - max_bytes))
                return f.read().decode(errors="replace")
        except OSError:
            return ""


class ClusterHarness:
    """Spawn and drive an N-node loopback cluster of real processes."""

    def __init__(
        self,
        base_dir,
        nodes: int = 3,
        drives_per_node: int = 2,
        access_key: str = "minioadmin",
        secret_key: str = "minioadmin",
        env: "dict[str, str] | None" = None,
        fast: bool = True,
        fault_injection: bool = True,
        format_timeout_s: float = 60.0,
    ):
        self.base = pathlib.Path(base_dir)
        self.access_key = access_key
        self.secret_key = secret_key
        self.fault_injection = fault_injection
        self.format_timeout_s = format_timeout_s
        self._extra_env = dict(env or {})
        self._fast = fast
        self.nodes: list[NodeHandle] = []
        for i in range(nodes):
            node_dir = self.base / f"n{i + 1}"
            dirs = []
            for j in range(drives_per_node):
                d = node_dir / f"d{j + 1}"
                d.mkdir(parents=True, exist_ok=True)
                dirs.append(d)
            self.nodes.append(
                NodeHandle(
                    i,
                    free_port(),
                    dirs,
                    str(self.base / f"node{i + 1}.log"),
                )
            )
        # one endpoint list shared verbatim by every node: the set
        # spans all drives of all nodes (single zone, no ellipses)
        self.endpoints = [
            f"http://127.0.0.1:{n.port}{d}"
            for n in self.nodes
            for d in n.drive_dirs
        ]

    # -- lifecycle --------------------------------------------------------

    def _spawn_env(self, node: NodeHandle) -> dict:
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["PYTHONPATH"] = _REPO_ROOT
        env["MINIO_TPU_PROMETHEUS_AUTH_TYPE"] = "public"
        if self.fault_injection:
            env["MINIO_TPU_FAULT_INJECTION"] = "1"
            env["MINIO_TPU_FAULT_SEED"] = str(1000 * (node.index + 1))
        if self._fast:
            # tighten heal/lock cadences so scenarios converge in
            # seconds instead of the production-default minutes
            env.setdefault("MINIO_TPU_FRESH_DISK_INTERVAL_S", "1")
            env.setdefault("MINIO_TPU_LOCK_REFRESH_S", "1")
            env.setdefault("MINIO_TPU_LOCK_EXPIRY_S", "4")
            # a write below lock quorum should 503 well inside the
            # client's socket budget, not after the 30s default
            env.setdefault("MINIO_TPU_WRITE_LOCK_ACQUIRE_S", "5")
        env.update(self._extra_env)
        return env

    def spawn(self, i: int, extra_env: "dict | None" = None) -> None:
        node = self.nodes[i]
        env = self._spawn_env(node)
        env.update(extra_env or {})
        log_f = open(node.log_path, "ab")  # noqa: SIM115 (child owns fd)
        log_f.write(
            f"--- spawn node{i + 1} port={node.port} ---\n".encode()
        )
        node.proc = subprocess.Popen(
            [
                sys.executable, "-m", "minio_tpu.server",
                "--address", f"127.0.0.1:{node.port}",
                "--format-timeout", str(self.format_timeout_s),
                *self.endpoints,
            ],
            env=env,
            stdout=log_f,
            stderr=subprocess.STDOUT,
        )
        log_f.close()  # child inherited the fd

    def start(self, timeout_s: float = 90.0) -> "ClusterHarness":
        for i in range(len(self.nodes)):
            self.spawn(i)
        for i in range(len(self.nodes)):
            self.wait_ready(i, timeout_s=timeout_s)
        return self

    def wait_ready(self, i: int, timeout_s: float = 90.0) -> None:
        """Poll /minio/health/ready until the node reports every
        subsystem up; a dead process fails fast with its log tail."""
        node = self.nodes[i]
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if node.proc is not None and node.proc.poll() is not None:
                raise RuntimeError(
                    f"node{i + 1} died rc={node.proc.returncode}:\n"
                    + node.log_tail()
                )
            try:
                req = urllib.request.Request(
                    f"{node.endpoint}/minio/health/ready", method="GET"
                )
                with urllib.request.urlopen(req, timeout=2) as r:
                    if r.status == 200:
                        return
            except (urllib.error.HTTPError, OSError):
                pass
            time.sleep(0.1)
        raise RuntimeError(
            f"node{i + 1} :{node.port} never became ready:\n"
            + node.log_tail()
        )

    def terminate(self, i: int, timeout_s: float = 30.0) -> int:
        """Graceful stop: SIGTERM, wait for the drain + lock unwind."""
        node = self.nodes[i]
        if node.proc is None or node.proc.poll() is not None:
            return node.proc.returncode if node.proc else 0
        node.proc.send_signal(signal.SIGTERM)
        return node.proc.wait(timeout=timeout_s)

    def kill(self, i: int) -> None:
        """Hard stop (crash simulation): SIGKILL, no drain."""
        node = self.nodes[i]
        if node.proc is not None and node.proc.poll() is None:
            node.proc.kill()
            node.proc.wait(timeout=10)

    def restart(
        self,
        i: int,
        graceful: bool = False,
        wait: bool = True,
        timeout_s: float = 90.0,
        extra_env: "dict | None" = None,
    ) -> None:
        if graceful:
            self.terminate(i)
        else:
            self.kill(i)
        self.spawn(i, extra_env=extra_env)
        if wait:
            self.wait_ready(i, timeout_s=timeout_s)

    def stop(self) -> None:
        for i in range(len(self.nodes)):
            try:
                self.kill(i)
            except Exception as exc:
                _log.debug(
                    "node kill failed", extra=kv(node=i, err=str(exc))
                )

    def __enter__(self) -> "ClusterHarness":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- clients ----------------------------------------------------------

    def client(self, i: int):
        """Signed S3 client against node i (owner credentials)."""
        from ..gateway.client import S3UpstreamClient

        return S3UpstreamClient(
            self.nodes[i].endpoint, self.access_key, self.secret_key
        )

    def admin(
        self,
        i: int,
        method: str,
        tail: str,
        query: "dict[str, str] | None" = None,
        body: "bytes | None" = b"",
    ) -> "tuple[int, dict]":
        """One signed admin call against node i; JSON-decoded body."""
        status, _hdrs, raw = self.client(i).request(
            method, f"/minio-tpu/admin/v1/{tail}", query=query, body=body
        )
        try:
            doc = json.loads(raw) if raw else {}
        except ValueError:
            doc = {"raw": raw.decode(errors="replace")}
        return status, doc

    # -- remote fault control ---------------------------------------------

    def inject_fault(
        self,
        i: int,
        api: str,
        disk: str = "*",
        delay_s: float = 0.0,
        hang_s: float = 0.0,
        error: bool = False,
        corrupt: bool = False,
        prob: float = 1.0,
        calls: "list[int] | None" = None,
    ) -> dict:
        """Schedule one FaultDisk rule on node i's local drives."""
        doc = {
            "disk": disk,
            "api": api,
            "delay_s": delay_s,
            "hang_s": hang_s,
            "error": error,
            "corrupt": corrupt,
            "prob": prob,
        }
        if calls is not None:
            doc["calls"] = list(calls)
        status, out = self.admin(
            i, "POST", "fault/inject", body=json.dumps(doc).encode()
        )
        if status != 200:
            raise RuntimeError(f"fault/inject on node{i + 1}: {out}")
        return out

    def clear_faults(self, i: int, disk: str = "*") -> dict:
        status, out = self.admin(
            i,
            "POST",
            "fault/clear",
            body=json.dumps({"disk": disk}).encode(),
        )
        if status != 200:
            raise RuntimeError(f"fault/clear on node{i + 1}: {out}")
        return out

    def fault_status(self, i: int) -> dict:
        status, out = self.admin(i, "GET", "fault/status")
        if status != 200:
            raise RuntimeError(f"fault/status on node{i + 1}: {out}")
        return out

    # -- metrics ----------------------------------------------------------

    def scrape(self, i: int) -> str:
        """Raw Prometheus exposition from node i (public auth mode)."""
        req = urllib.request.Request(
            f"{self.nodes[i].endpoint}/minio-tpu/prometheus/metrics",
            method="GET",
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.read().decode(errors="replace")

    def merged_metrics(
        self, families: "tuple | None" = None
    ) -> "dict[str, list[tuple[dict, float]]]":
        """Union of every live node's scrape, each sample labelled with
        node="n<i>".  Families in ZERO_FILL_FAMILIES get an explicit
        0-valued sample for nodes that did not export them, so a
        per-node query can always tell "zero" from "absent"."""
        want = families or ZERO_FILL_FAMILIES
        merged: dict[str, list] = {f: [] for f in want}
        for n in self.nodes:
            if not n.alive():
                continue
            tag = f"n{n.index + 1}"
            seen: set[str] = set()
            try:
                rows = parse_prometheus(self.scrape(n.index))
            except OSError:
                rows = []
            for name, labels, value in rows:
                if families is not None and name not in families:
                    continue
                labels = dict(labels, node=tag)
                merged.setdefault(name, []).append((labels, value))
                seen.add(name)
            for fam in want:
                if fam in ZERO_FILL_FAMILIES and fam not in seen:
                    merged[fam].append(({"node": tag}, 0.0))
        return merged

    def disk_states(self, i: int) -> "dict[str, int]":
        """endpoint -> breaker state (0/1/2) as node i observes it."""
        return {
            labels.get("disk", ""): int(value)
            for name, labels, value in parse_prometheus(self.scrape(i))
            if name == "miniotpu_disk_state"
        }
